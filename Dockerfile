# Test/dev image for horovod_tpu (reference: Dockerfile.test.cpu — the
# reference bakes an mpirun-based test matrix into Docker images; here the
# "distributed without a cluster" strategy is a virtual 8-device CPU mesh
# plus real multi-process workers over the native TCP transport, so one
# ordinary Python image covers the whole matrix).
#
# On a real TPU VM, install jax[tpu] instead of the CPU jax pinned here and
# drop the XLA_FLAGS override.

FROM python:3.13-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        build-essential make g++ openssh-client default-jre-headless \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /horovod_tpu
COPY . .

# tensorflow-cpu exercises the TF binding; pyspark (+ the JRE above) the
# real-local[2] Spark tests — the reference bakes both into its test
# image (Dockerfile.test.cpu:53-83)
RUN pip install --no-cache-dir "jax[cpu]" flax optax chex einops pytest \
        torch tensorflow-cpu pyspark --index-url https://pypi.org/simple \
    && pip install --no-cache-dir -e . --no-deps

# the test matrix: collective semantics, fusion, caching, error paths on a
# fake 8-device mesh + real multi-process workers (tests/conftest.py)
CMD ["python", "-m", "pytest", "tests/", "-x", "-q"]
