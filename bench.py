#!/usr/bin/env python
"""Synthetic ResNet-50 training benchmark — the headline perf harness.

TPU-native port of the reference's measurement harness (reference:
examples/pytorch_synthetic_benchmark.py:37-110,
examples/tensorflow2_synthetic_benchmark.py:72-132): ResNet-50 forward +
backward + optimizer update on synthetic ImageNet-shaped data. Each timed
round is ONE compiled program running BENCH_BATCHES_PER_ROUND (default 20)
train steps via lax.scan — host dispatch latency is excluded, which is the
XLA-native reading of the reference's multi-batch rounds. Warmup runs
ceil(BENCH_WARMUP / BENCH_BATCHES_PER_ROUND) rounds first; reports
images/sec over BENCH_ROUNDS rounds.

Baseline for ``vs_baseline``: the reference's only published absolute
number — 1656.82 images/sec on 16 GPUs (ResNet-101, batch 64, 4xP100
servers; reference: docs/benchmarks.rst:32-43) = 103.55 images/sec/GPU.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import horovod_tpu as hvd
from horovod_tpu.models.resnet import ResNet50
from horovod_tpu import training

# Persistent XLA compile cache: the default no-flag sweep spends ~250 s
# compiling four workloads (r4: BERT-Large/Base 87 s each), which is what
# pushed BENCH_r04 past the driver window (rc=124). A repo-local cache
# survives across processes in the same container, so a sweep that runs
# after ANY prior run (tests, a self-run, a prior round) skips most of
# that. Harmless when cold or unsupported.
try:
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
except Exception:  # older jax without the knob: compile cache is optional
    pass

REFERENCE_IMAGES_PER_SEC_PER_CHIP = 1656.82 / 16  # docs/benchmarks.rst:32-43

WARMUP_ITERS = int(os.environ.get("BENCH_WARMUP", "20"))
# 3 timed rounds by default (r5): r4's 10-round medians varied +-0.2%
# across every workload (BENCH_r04.json), so 7 extra ~30 s rounds bought
# nothing but driver-window risk. BENCH_ROUNDS restores the long protocol.
TIMED_ROUNDS = int(os.environ.get("BENCH_ROUNDS", "3"))
# 60 batches/round: the remote-dispatch tunnel costs ~100ms per
# executable launch, so 20-step rounds (r1/r2) under-reported the chip
# by ~10% — tools/resnet_decompose.py's slope measurement (dispatch
# cancelled) shows the true steady-state step; 60-step rounds amortize
# the launch to ~3%.
BATCHES_PER_ROUND = int(os.environ.get("BENCH_BATCHES_PER_ROUND", "60"))

# Per-model CNN configs: (label, image size, default batch/chip, forward
# FLOPs/image). FLOPs count multiplies AND adds separately (2 per MAC) —
# the SAME convention as the chip's published peak (197 bf16 TFLOP/s on
# v5e is 2xMAC) and as the transformer 6N formula, so MFU is comparable
# across every row. The constants are XLA's own cost analysis of each
# model's forward pass at these input sizes (jit(fwd).lower().compile()
# .cost_analysis()["flops"]) — within ±2.5% of 2x the published MAC
# counts (2x4.089 / 2x5.713 @299² / 2x15.47).
#   ROUND-4 CORRECTION: rounds 1-3 computed CNN MFU on the MAC count
# (4.089e9 for ResNet-50), understating it 2x. The r3 per-conv
# microbenchmarks (docs/perf_experiments.md: 96.3% MFU at 155.9us on a
# 29.6e9-FLOP conv) already used the true 2xMAC convention — this fix
# makes the model-level rows consistent with them and with the
# transformer rows. Throughput (img/s) numbers are unaffected.
# Train step fwd + bwd ≈ 3x forward (bwd ≈ 2x fwd FLOPs). The model trio
# is the reference's published benchmark set (reference:
# docs/benchmarks.rst:13-14). Batch defaults are measured v5e sweet
# spots per model.
CNN_CONFIGS = {
    "resnet50": ("ResNet-50", 224, 128, 8.234e9),
    # r4 sweeps: Inception 16/32/48/64 -> 32 best; VGG 32/64/128/192/256
    # -> 1021/1084/1432/1310/1455 img/s, 256 best (128 within 2%)
    "inception": ("Inception-V3", 299, 32, 11.137e9),
    "vgg": ("VGG-16", 224, 256, 30.342e9),
}

# bf16 peak by device kind (jax.devices()[0].device_kind prefix match) —
# published per-chip peaks; None -> mfu reported as null
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_per_chip():
    kind = jax.devices()[0].device_kind
    for prefix in sorted(PEAK_BF16_FLOPS, key=len, reverse=True):
        if kind.startswith(prefix):
            return PEAK_BF16_FLOPS[prefix]
    return None


def mfu(flops_per_sec_per_chip):
    peak = peak_flops_per_chip()
    if peak is None:
        return None
    return round(flops_per_sec_per_chip / peak, 4)


def enable_profiler(flops_per_step=None):
    """Turn on the step profiler for the timed rounds (hvd.profiler): every
    headline then carries a step_breakdown + comm_hidden_fraction, and the
    FLOPs hint feeds the rolling horovod_mfu gauge. Called AFTER warmup so
    compile time never pollutes the step history."""
    os.environ.setdefault("HOROVOD_PROFILE", "1")
    hvd.profiler.configure()
    if flops_per_step is not None:
        hvd.profiler.set_flops_per_step(flops_per_step,
                                        peak_flops_per_chip())


def step_profile(n_rounds):
    """(step_breakdown, comm_hidden_fraction, comm_hidden_fraction_bytes)
    over the last ``n_rounds`` profiled steps — this workload's timed
    rounds; the no-flag sweep's earlier workloads share the profiler
    ring, so slice instead of using the whole-ring summary(). The
    bytes-weighted fraction is the bucket-release acceptance metric:
    payload bytes whose reduction overlapped backward / total reduced
    bytes."""
    steps = hvd.profiler.history()[-n_rounds:]
    if not steps:
        return None, None, None
    n = len(steps)
    breakdown = {k: round(sum(s["phases"][k] for s in steps) / n, 6)
                 for k in ("host", "compute", "exposed_comm", "optimizer")}
    total = sum(s["comm"]["total_seconds"] for s in steps)
    exposed = sum(s["comm"]["exposed_seconds"] for s in steps)
    hidden = (min(1.0, max(0.0, 1.0 - exposed / total))
              if total > 0 else 0.0)
    comm_bytes = sum(s["comm"]["bytes"] for s in steps)
    hidden_bytes = sum(s["comm"]["hidden_fraction_bytes"]
                       * s["comm"]["bytes"] for s in steps)
    hidden_b = (min(1.0, max(0.0, hidden_bytes / comm_bytes))
                if comm_bytes > 0 else 0.0)
    return breakdown, round(hidden, 4), round(hidden_b, 4)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def memory_rows(params_tree=None):
    """Headline memory fields (docs/memory.md): per-subsystem
    ``bytes_per_chip`` from the tracker ledger + ``peak_hbm_bytes``. The
    jitted bench rounds never cross the eager push point in
    DistributedOptimizer, so the caller hands its params tree here for a
    direct push before the pull."""
    try:
        from horovod_tpu import memory

        t = memory.tracker()
        if params_tree is not None:
            t.note_tree_bytes("params", params_tree)
        led = t.ledger()
        per_chip = {name: int(rec["bytes"])
                    for name, rec in led["subsystems"].items()
                    if name != "host_rss" and rec["bytes"]}
        return {"bytes_per_chip": per_chip,
                "peak_hbm_bytes": int(t.peak_hbm_bytes())}
    except Exception:
        return {"bytes_per_chip": None, "peak_hbm_bytes": None}


def comms_rows():
    """Headline comms fields (docs/comms.md): the busiest lane's smoothed
    bus bandwidth + its roofline utilization from the tracker ledger.
    None/None when no collective moved bytes this run (1-chip world with
    nothing on any wire)."""
    try:
        from horovod_tpu import comms

        led = comms.tracker().ledger()
        lanes = {name: rec for name, rec in led["lanes"].items()
                 if rec.get("busbw_gbs")}
        if not lanes:
            return {"busbw_gbs": None, "comms_utilization": None}
        busiest = max(lanes, key=lambda ln: lanes[ln]["bytes_total"])
        rec = lanes[busiest]
        return {"busbw_gbs": rec["busbw_gbs"],
                "comms_utilization": rec.get("utilization")}
    except Exception:
        return {"busbw_gbs": None, "comms_utilization": None}


def goodput_rows():
    """Headline goodput fields (docs/goodput.md): the productive
    fraction of wall-clock from the tracker ledger, gated
    higher-is-better by bench_compare. None when the tracker is off or
    the epoch never started (pre-init entry points)."""
    try:
        from horovod_tpu import goodput

        led = goodput.tracker().ledger()
        if not led.get("wall_seconds"):
            return {"goodput_fraction": None}
        return {"goodput_fraction": led["goodput_fraction"]}
    except Exception:
        return {"goodput_fraction": None}


def bucket_overlap_probe(model, optimizer, state, image_size,
                         batch=8, steps=4):
    """Bytes-weighted hidden fraction of the release plan's wire traffic.

    The jitted round keeps its collectives inside one XLA program, so
    the runtime's dispatch/drain stamps never see them; this probe runs
    a few *eager* bucketed steps (simulated multi-lane wire on the
    single-controller path) on the same model, where each released
    bucket is a real pipelined dispatch. Returns None when nothing hit
    the wire (1-chip world or wire=off)."""
    from horovod_tpu.parallel import buckets as buckets_mod

    plan = buckets_mod.GradReleasePlan()
    one_step = training._make_one_step(model, optimizer,
                                       training._default_loss_fn,
                                       grad_release=plan)
    rng = np.random.RandomState(1)
    images = jnp.asarray(
        rng.uniform(-1, 1, (batch, image_size, image_size, 3)),
        jnp.float32)
    labels = jnp.asarray(rng.randint(0, 1000, (batch,)), jnp.int32)
    params, stats, opt_state = (state.params, state.batch_stats,
                                state.opt_state)
    one_step(params, stats, opt_state, images, labels)  # warmup/compile
    for i in range(steps):
        with hvd.profiler.step(f"overlap probe {i}"):
            out = one_step(params, stats, opt_state, images, labels)
            jax.block_until_ready(out[0])
    probe = hvd.profiler.history()[-steps:]
    comm_bytes = sum(s["comm"]["bytes"] for s in probe)
    if not comm_bytes:
        return None
    hidden = sum(s["comm"]["hidden_fraction_bytes"] * s["comm"]["bytes"]
                 for s in probe)
    return round(min(1.0, max(0.0, hidden / comm_bytes)), 4)


def main(model_name: str = "resnet50", allow_env: bool = True):
    label, image_size, default_batch, fwd_flops = CNN_CONFIGS[model_name]
    batch_per_chip, default_size = default_batch, image_size
    if allow_env:  # single-model runs only — a sweep would apply one
        # override to every model, clobbering per-model sweet spots
        batch_per_chip = int(os.environ.get("BENCH_BATCH",
                                            str(default_batch)))
        image_size = int(os.environ.get("BENCH_IMAGE_SIZE",
                                        str(image_size)))
    # conv FLOPs scale ~quadratically with resolution; keep the MFU
    # basis honest when BENCH_IMAGE_SIZE overrides the default
    fwd_flops *= (image_size / default_size) ** 2
    train_flops_per_image = 3 * fwd_flops

    hvd.init()
    n_chips = hvd.size()
    global_batch = batch_per_chip * n_chips
    log(f"devices: {jax.devices()}  global_batch={global_batch}")

    if model_name == "inception":
        from horovod_tpu.models import InceptionV3
        model = InceptionV3(num_classes=1000, dtype=jnp.bfloat16)
    elif model_name == "vgg":
        from horovod_tpu.models import VGG16
        model = VGG16(num_classes=1000, dtype=jnp.bfloat16)
    else:
        model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    optimizer = hvd.DistributedOptimizer(
        optax.sgd(0.01 * n_chips, momentum=0.9))

    # BENCH_GRAD_BUCKETS=0 restores the post-hoc exchange for A/B; the
    # default rides HOROVOD_GRAD_BUCKET_RELEASE via make_train_round
    # (on the jitted global-batch lane the plan stages the collectives
    # at their backward positions — see docs/performance.md)
    grad_buckets = None
    if allow_env and os.environ.get("BENCH_GRAD_BUCKETS") == "0":
        grad_buckets = False
    elif allow_env and os.environ.get("BENCH_GRAD_BUCKETS") == "1":
        os.environ["HOROVOD_GRAD_BUCKET_RELEASE"] = "1"

    state = training.create_train_state(
        model, optimizer, (1, image_size, image_size, 3))
    # One compiled program per round (lax.scan over the batches) so host
    # dispatch latency stays out of the steady-state measurement.
    round_fn, batch_sharding = training.make_train_round(
        model, optimizer, steps=BATCHES_PER_ROUND,
        grad_release=grad_buckets)

    rng = np.random.RandomState(0)
    images = jax.device_put(
        rng.uniform(-1, 1, (global_batch, image_size, image_size, 3)).astype(np.float32),
        batch_sharding)
    labels = jax.device_put(
        rng.randint(0, 1000, (global_batch,)).astype(np.int32),
        batch_sharding)

    params, stats, opt_state = state.params, state.batch_stats, state.opt_state

    log("compiling + warmup...")
    t0 = time.perf_counter()
    warmup_rounds = max(1, -(-WARMUP_ITERS // BATCHES_PER_ROUND))
    for _ in range(warmup_rounds):
        loss, params, stats, opt_state = round_fn(params, stats, opt_state,
                                                  images, labels)
    jax.block_until_ready(loss)
    log(f"warmup done in {time.perf_counter() - t0:.1f}s "
        f"(loss={float(loss):.3f})")

    enable_profiler(batch_per_chip * BATCHES_PER_ROUND
                    * train_flops_per_image)
    rates = []
    for r in range(TIMED_ROUNDS):
        t0 = time.perf_counter()
        with hvd.profiler.step(f"{label} round {r}"):
            loss, params, stats, opt_state = round_fn(
                params, stats, opt_state, images, labels)
            jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        rates.append(global_batch * BATCHES_PER_ROUND / dt)
        log(f"round {r}: {rates[-1]:.1f} img/s")
    breakdown, hidden_fraction, hidden_bytes = step_profile(TIMED_ROUNDS)
    if grad_buckets is not False:
        probe = bucket_overlap_probe(model, optimizer, state, image_size)
        if probe is not None:
            log(f"bucket overlap probe: hidden_bytes={probe}")
            hidden_bytes = probe

    # median, not mean: a single tunnel hiccup (reconnect mid-round) can
    # make one round read 20x slow — a transport artifact, not the chip
    imgs_per_sec = float(np.median(rates))
    per_chip = imgs_per_sec / n_chips
    result = {
        "metric": f"images/sec/chip ({label} synthetic, bf16, "
                  f"batch {batch_per_chip}/chip)",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        # the reference's only absolute published number is ResNet-family
        # (1656.82 img/s on 16 P100-era GPUs); Inception/VGG appear in
        # its scaling table without absolutes, so vs_baseline is only
        # meaningful for the ResNet row
        "vs_baseline": (
            round(per_chip / REFERENCE_IMAGES_PER_SEC_PER_CHIP, 3)
            if model_name == "resnet50" else None),
        "mfu": mfu(per_chip * train_flops_per_image),
        "step_breakdown": breakdown,
        "comm_hidden_fraction": hidden_fraction,
        "comm_hidden_fraction_bytes": hidden_bytes,
        **memory_rows(params),
        **comms_rows(),
        **goodput_rows(),
    }
    print(json.dumps(result), flush=True)
    return result


def transformer_main(family: str, allow_env: bool = True,
                     micro_step_cap: int = 512):
    """Transformer headlines: tokens/sec + MFU for BERT-Base/-Large MLM
    (BASELINE progression config #5's model family) and GPT-2-small
    causal LM — all on the Pallas flash-attention path
    (models/transformer.py).

    Batch defaults are the measured v5e sweet spots (r2 sweeps: BERT-Base
    seq 512 — 16 -> 46.5% MFU, 32 -> 50.8%, 64 -> 47.7%)."""
    import optax as _optax

    from horovod_tpu.models.transformer import (BertBase, BertLarge,
                                                GPT2Small, causal_lm_loss,
                                                causal_lm_loss_chunked,
                                                masked_lm_loss,
                                                masked_lm_loss_gathered,
                                                sample_masked_positions)

    hvd.init()
    n_chips = hvd.size()
    causal = family == "gpt2"
    large = family == "bert-large"
    default_seq = "1024" if causal else "512"
    seq = int(os.environ.get("BENCH_BERT_SEQ", default_seq)
              if allow_env else default_seq)
    # v5e sweet spots, re-swept r5 with the single-block flash kernel
    # (cheaper attention moved BERT-Base's spot): BERT-Base 48
    # (r5: 32->182.2k, 48->186.7k, 64->178.6k); BERT-Large 8
    # (r5: 8x-accum beats 16x4 56.8k; r3: 4->47.4%, 8->56.4%,
    # 16->53.1%, 32->OOM); GPT-2 16 (r5: 24->122.0k vs 16->130.1k)
    default_batch = "8" if large else "16" if causal else "48"
    batch = int(os.environ.get("BENCH_BERT_BATCH", default_batch)
                if allow_env else default_batch)
    vocab = 50257 if causal else 30522
    global_batch = batch * n_chips
    label = ("GPT-2-small causal LM" if causal
             else "BERT-Large MLM" if large else "BERT-Base MLM")

    # MLM benches default to the gather-before-projection path (r4): the
    # vocab matrix projects only the masked positions (the standard BERT
    # max_predictions_per_seq data layout), so the (batch, seq, vocab)
    # f32 logits tensor never exists. BENCH_MLM_GATHER=0 restores the
    # full-logits r1-r3 protocol for A/B.
    gather = (not causal) and (
        os.environ.get("BENCH_MLM_GATHER", "1") == "1" if allow_env
        else True)
    # BENCH_ADAM_MU_BF16=1: adamw first moment in bf16 (optimizer-state
    # HBM traffic counter-move; A/B knob, default off)
    mu_bf16 = allow_env and os.environ.get("BENCH_ADAM_MU_BF16") == "1"
    # BENCH_QKV_FUSED=1: single (d, 3d) QKV projection per layer
    # (counter-move A/B knob, default off)
    qkv_fused = allow_env and os.environ.get("BENCH_QKV_FUSED") == "1"
    # BENCH_ACCUM=N: gradient accumulation over N micro-batches per
    # optimizer update (effective batch = N*batch, identical gradients
    # to a single N*batch step). The r4 decomposition measured the f32
    # adamw pass at 16.2 ms — 21% of the BERT-Large step and batch-
    # independent — so keeping the micro-batch at the activation sweet
    # spot and amortizing the update is the large-batch training
    # configuration this chip actually favors. BERT-Large defaults to
    # x16 (r5 re-sweep with the faster kernel: x8 62.5k, x16 63.6k,
    # x32 64.1k — x16 is the knee, effective 128 seqs/chip, a standard
    # large-batch recipe; r4's x8 sweep: x2 +0%, x4 +7%, x8 +10.8%);
    # BERT-Base to x4 (+1.6%); GPT-2 measured a wash (122.1k -> 121.3k
    # at x4) and stays at 1.
    default_accum = "16" if large else "1" if causal else "4"
    if allow_env and os.environ.get("BENCH_FUSED_ADAMW") == "1":
        default_accum = "1"  # the fused-adamw A/B runs un-accumulated
    accum = int(os.environ.get("BENCH_ACCUM", default_accum)
                if allow_env else default_accum)
    # BENCH_FUSED_ADAMW=1: the Pallas single-pass adamw
    # (ops/pallas/fused_adamw.py) instead of optax's transform chain —
    # targets the measured 16.2 ms / 21%-of-step optimizer pass
    fused_opt = allow_env and os.environ.get("BENCH_FUSED_ADAMW") == "1"
    if fused_opt and accum > 1:
        raise SystemExit("BENCH_FUSED_ADAMW and BENCH_ACCUM are separate "
                         "A/B knobs; combine them once either wins alone")

    cls = GPT2Small if causal else BertLarge if large else BertBase
    model = cls(vocab_size=vocab, max_seq=seq, dtype=jnp.bfloat16,
                fused_qkv=qkv_fused)
    rng = np.random.RandomState(0)
    rows = global_batch * accum
    tokens = rng.randint(0, vocab, (rows, seq)).astype(np.int32)
    mask = (rng.rand(rows, seq) < 0.15).astype(np.int32)
    n_pred = max(1, round(0.15 * seq))  # 76 at seq 512 (BERT's layout)
    positions = sample_masked_positions(
        np.random.default_rng(0), rows, seq, n_pred)
    labels = np.take_along_axis(tokens, positions, axis=1)
    if accum > 1:
        reshape = lambda a: a.reshape((accum, global_batch) + a.shape[1:])
        tokens, mask, positions, labels = map(
            reshape, (tokens, mask, positions, labels))

    # init on the local CPU backend — a once-only program is not worth a
    # remote compile+dispatch on the tunnel (training.init_on_host; the
    # flash kernel runs one interpret-mode trace there)
    sample = (tokens[0] if accum > 1 else tokens)[:1]
    params = training.init_on_host_fn(
        lambda x: model.init(jax.random.PRNGKey(0), x, train=False),
        np.asarray(sample))
    if fused_opt:
        from horovod_tpu.ops.pallas import fused_adamw as _fused_adamw
        fopt = _fused_adamw(1e-4)
        opt = None
        opt_state = fopt.init(params)
    else:
        opt = hvd.DistributedOptimizer(_optax.adamw(
            1e-4, mu_dtype=jnp.bfloat16 if mu_bf16 else None))
        opt_state = opt.init(params)

    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    # training FLOPs/token: 6*N (fwd+bwd matmuls) + attention term
    # 12*L*S*d (fwd+bwd QK^T and PV). Causal counts the half score
    # matrix — the standard MODEL-FLOPs convention for MFU (the useful
    # math; at this seq/block config the kernel executes full masked
    # blocks, i.e. hardware FLOPs are higher, which only makes the
    # reported MFU conservative about the hardware's utilization).
    # Gathered MLM: the tied vocab matmul runs at n_pred of seq
    # positions, so its 6*|E| term scales by n_pred/seq — counting the
    # full 6*|E| against the faster step would inflate MFU with FLOPs
    # the model no longer executes. (The input lookup and pos_embed are
    # gathers either way; their overcount — <1% — is shared by every
    # published 6N number.)
    l_layers, d_model = (24, 1024) if large else (12, 768)
    attn = 12 * l_layers * seq * d_model
    n_eff = n_params
    if gather:
        n_embed = vocab * d_model
        n_eff = n_params - n_embed + n_embed * n_pred // seq
    flops_per_token = 6 * n_eff + (attn // 2 if causal else attn)

    # Round sizing under accumulation: the tunnel charges a fixed
    # ~150 ms per round (measured r4: 56/120/480 micros per round ->
    # 55.3/56.4/57.3 k tokens/s at accum 8), so rounds should stay as
    # LONG as possible — but rounds beyond ~40 s trip the tunnel's RPC
    # deadline (accum 16 x 60 updates = 74 s rounds died reliably).
    # Cap micro-steps per round at 512 (~35 s at BERT-Large shapes); the
    # no-flag sweep passes 256 (~18 s rounds, dispatch overhead <1%) to
    # fit the driver window.
    updates_per_round = max(1, min(BATCHES_PER_ROUND,
                                   micro_step_cap // accum))

    # BENCH_LM_CHUNK=K: chunked causal loss — the vocab projection runs
    # K seq positions at a time inside the loss, so the (batch, seq,
    # vocab) f32 logits tensor (3.3 GB at GPT-2 bench shapes) never
    # exists. 0 = full-logits (A/B knob; default per measurement below).
    lm_chunk = int(os.environ.get("BENCH_LM_CHUNK", "0")
                   if allow_env and causal else "0")

    def loss_fn(p, toks, msk, pos, lab):
        if causal:
            if lm_chunk:
                hidden = model.apply(p, toks, train=True, output="hidden")
                emb = p["params"]["token_embed"]["embedding"]
                return causal_lm_loss_chunked(hidden, emb, toks,
                                              chunk=lm_chunk)
            return causal_lm_loss(model.apply(p, toks, train=True), toks)
        if gather:
            hidden = model.apply(p, toks, train=True, output="hidden")
            emb = p["params"]["token_embed"]["embedding"]
            return masked_lm_loss_gathered(hidden, emb, pos, lab)
        return masked_lm_loss(model.apply(p, toks, train=True), toks, msk)

    @jax.jit
    def round_fn(p, s, toks, msk, pos, lab):
        def one_update(p, s):
            if accum == 1:
                loss, g = jax.value_and_grad(loss_fn)(p, toks, msk, pos,
                                                      lab)
                if fused_opt:
                    from horovod_tpu.parallel.dp import allreduce_gradients
                    g = allreduce_gradients(g, average=True)
                    p, s = fopt.apply(p, s, g)
                    return p, s, loss
            else:
                # accumulate over micro-batches: mean grad == the grad of
                # one accum*batch step, at batch-8 activation footprint
                def micro(g_sum, mb):
                    t, m, po, la = mb
                    loss, g = jax.value_and_grad(loss_fn)(p, t, m, po, la)
                    return jax.tree_util.tree_map(jnp.add, g_sum, g), loss
                g0 = jax.tree_util.tree_map(jnp.zeros_like, p)
                g, mlosses = jax.lax.scan(micro, g0,
                                          (toks, msk, pos, lab))
                g = jax.tree_util.tree_map(lambda a: a / accum, g)
                loss = mlosses.mean()
            upd, s = opt.update(g, s, p)
            p = _optax.apply_updates(p, upd)
            return p, s, loss

        def body(carry, _):
            p, s = carry
            p, s, loss = one_update(p, s)
            return (p, s), loss

        (p, s), losses = jax.lax.scan(body, (p, s), None,
                                      length=updates_per_round)
        return p, s, losses[-1]

    log(f"{label} seq {seq} batch {batch}/chip "
        f"({n_params / 1e6:.0f}M params"
        f"{', gathered MLM head' if gather else ''}"
        f"{', bf16 adam mu' if mu_bf16 else ''}"
        f"{', fused qkv' if qkv_fused else ''}"
        f"{f', {accum}x grad accumulation' if accum > 1 else ''}"
        f"{', fused pallas adamw' if fused_opt else ''}"
        f"{f', chunked LM loss ({lm_chunk})' if lm_chunk else ''}"
        "), compiling...")
    t0 = time.perf_counter()
    params, opt_state, loss = round_fn(params, opt_state, tokens, mask,
                                       positions, labels)
    jax.block_until_ready(loss)
    log(f"warmup done in {time.perf_counter() - t0:.1f}s "
        f"(loss={float(loss):.3f})")

    enable_profiler(batch * accum * seq * updates_per_round
                    * flops_per_token)
    rates = []
    for r in range(TIMED_ROUNDS):
        t0 = time.perf_counter()
        with hvd.profiler.step(f"{label} round {r}"):
            params, opt_state, loss = round_fn(params, opt_state, tokens,
                                               mask, positions, labels)
            jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        rates.append(global_batch * accum * seq * updates_per_round / dt)
        log(f"round {r}: {rates[-1]:.0f} tokens/s")
    breakdown, hidden_fraction, hidden_bytes = step_profile(TIMED_ROUNDS)

    tokens_per_sec = float(np.median(rates))  # robust to tunnel hiccups
    per_chip = tokens_per_sec / n_chips
    batch_label = (f"batch {batch}/chip" if accum == 1 else
                   f"batch {batch}x{accum} accum/chip")
    if lm_chunk:
        batch_label += f", chunked LM loss ({lm_chunk})"
    result = {
        "metric": f"tokens/sec/chip ({label}, bf16, seq {seq}, "
                  f"{batch_label}, flash attention)",
        "value": round(per_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,  # the reference publishes no absolute
        # transformer number (docs/benchmarks.rst is ResNet/VGG only)
        "mfu": mfu(per_chip * flops_per_token),
        "step_breakdown": breakdown,
        "comm_hidden_fraction": hidden_fraction,
        "comm_hidden_fraction_bytes": hidden_bytes,
        **memory_rows(params),
        **comms_rows(),
        **goodput_rows(),
    }
    print(json.dumps(result), flush=True)
    return result


def control_plane_main(fast: bool = False, np_override: int = None):
    """Control-plane benchmark (VERDICT r2 ask 4): negotiation latency,
    cache fast path, fusion throughput, autotune — measured over a real
    np=4 multi-process world on the host wire (tools/control_plane_bench
    .py). Emits one JSON line per metric so the driver captures the
    Horovod-headline numbers (negotiation amortization + fusion).

    ``fast`` (the no-flag sweep): fewer steps and no autotune launch —
    the reported counter metrics drift slightly (shorter windows
    amortize fixed per-window protocol bytes less; see the tool's
    header comment) but stay the same story; the full protocol (r4:
    5.5 min on a 1-core box) stays behind the explicit
    --control-plane flag.

    ``np_override``: world size for the trimmed always-run probe (the
    budget-squeezed sweep runs np=2 so the control-plane rows are never
    silently absent from the artifact)."""
    import subprocess

    np_workers = (str(np_override) if np_override is not None
                  else os.environ.get("BENCH_CONTROL_PLANE_NP", "4"))
    cmd = [sys.executable,
           os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "control_plane_bench.py"),
           "--np", np_workers]
    if fast:
        cmd.append("--fast")
    raw = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                         check=True)
    r = json.loads(raw.stdout.strip().splitlines()[-1])
    results = []
    for metric, value, unit, baseline in [
        ("control-plane bytes/op, fresh-name slow path",
         r["ctrl_bytes_per_op_slow_path"], "bytes/op", None),
        ("control-plane bytes/op, cache fast path",
         r["ctrl_bytes_per_op_fast_path"], "bytes/op",
         r["negotiation_byte_amortization_x"]),
        ("ring kernel steps/op, fused",
         r["ring_steps_per_op_fused"], "steps/op",
         r["fusion_dispatch_reduction_x"]),
    ]:
        results.append({
            "metric": f"{metric} (np={r['world']}, host wire)",
            "value": value, "unit": unit, "vs_baseline": baseline,
        })
        print(json.dumps(results[-1]), flush=True)
    return results


def hierarchy_main(tiny: bool = False, np_override: int = None):
    """Flat-vs-hierarchical host collective A/B (ISSUE 18 tentpole
    evidence; tools/hierarchy_bench.py): per-payload us/op for the seed
    flat ring vs the two-level decomposition (group size 2) with and
    without the fp16 slow-hop codec, each with and without a simulated
    slow cross-group link (``netdelay:...:hop=cross``). The headline is
    the throttled-hop speedup — unit "x" so tools/bench_compare.py
    gates it higher-is-better. Full mode adds the rebooted autotuner's
    convergence ratio vs the hand-tuned configuration.

    ``tiny``: one small size, few steps, no autotune phase — the tier-1
    smoke mode; numbers are meaningless."""
    import subprocess

    np_workers = (str(np_override) if np_override is not None
                  else os.environ.get("BENCH_HIERARCHY_NP", "4"))
    cmd = [sys.executable,
           os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "hierarchy_bench.py"),
           "--np", np_workers]
    if tiny:
        cmd.append("--tiny")
    raw = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=1800, check=True)
    r = json.loads(raw.stdout.strip().splitlines()[-1])
    big = str(r["sizes"][-1])
    rows = [
        ("hierarchical+fp16 vs flat, throttled cross hop",
         r["throttled_hop_speedup_x"], "x"),
        ("hierarchical vs flat, uniform wire",
         r["uniform_wire_ratio_x"], "x"),
        ("flat ring us/op under netdelay",
         r["us_per_op"]["flat_netdelay"][big], "us/op"),
        ("hierarchical+fp16 us/op under netdelay",
         r["us_per_op"]["hier_fp16_netdelay"][big], "us/op"),
    ]
    if not tiny and r.get("autotuned_vs_hand_tuned_x") is not None:
        rows.append(("autotuned vs hand-tuned, throttled cross hop",
                     r["autotuned_vs_hand_tuned_x"], "x"))
    results = []
    for metric, value, unit in rows:
        results.append({
            "metric": (f"{metric} (np={r['world']}, "
                       f"g={r['group_size']}"
                       f"{', tiny' if tiny else ''})"),
            "value": value, "unit": unit, "vs_baseline": None,
        })
        if tiny:
            results[-1]["tiny"] = True
        print(json.dumps(results[-1]), flush=True)
    return results


def collectives_main(tiny: bool = False):
    """Data-plane microbench: steady-state fused allreduce through the
    background runtime — pipelined dispatch, size-bucketed program cache
    and persistent fusion buffer all on the hot path. Emits ONE JSON line
    (the driver records the last parsed line): per-size p50 latency +
    effective per-worker payload bandwidth, plus the XLA compile count
    during the timed (post-warmup) phase. The compile count is the
    regression canary — steady state over fixed named tensors must stay
    at zero new compiles (tests/test_data_plane.py enforces the same
    invariant at tier 1).

    ``tiny`` (--tiny / the tier-1 smoke test): one small size, a couple
    of steps — exercises every code path in seconds; the numbers are
    meaningless and the line is marked ``"tiny": true``."""
    hvd.init()
    from horovod_tpu.runtime import executor as executor_mod
    from horovod_tpu.runtime.fusion_buffer import bucket_elems
    from horovod_tpu.runtime.runtime import get_runtime

    ex = get_runtime().executor
    world = hvd.size()
    tensors_per_step = 2 if tiny else 4
    # Bin groupings are timing-dependent (the background cycle may catch
    # 1..tensors_per_step of the enqueued tensors per bin) but handles are
    # synchronized before the next step, so bins never span steps and the
    # possible fused totals are exactly k*elems for k in 1..tensors_per_step.
    # Warm up until the program cache covers every such bucket AND a full
    # step adds zero compiles, so the timed phase can't hit a first-ever
    # grouping; the early warmup steps enqueue 1, 2, ... tensors to give
    # each total a deliberate chance to compile.
    max_warmup_steps, timed_steps = (6, 2) if tiny else (24, 7)
    rng = np.random.RandomState(0)
    rows = []
    steady_compiles = 0
    # 16 KiB .. 4 MiB per tensor (tiny: one 1 KiB size)
    for elems in ((256,) if tiny else (4096, 65536, 1 << 20)):
        payload = rng.randn(world, elems).astype(np.float32)

        def one_step(step, count=tensors_per_step):
            hs = [hvd.allreduce_async(
                hvd.stack_per_worker(list(payload + np.float32(step))),
                name=f"bench/ar{elems}/t{j}")
                for j in range(count)]
            for h in hs:
                hvd.synchronize(h)

        expected = {bucket_elems(k * elems, 4, ex.fusion_buffers.quantum_bytes)
                    for k in range(1, tensors_per_step + 1)}

        def buckets_warmed():
            # host-ring-only mode compiles nothing; don't wait on it
            if not ex._programs:
                return True
            keys = list(ex._programs)
            return all(any(b in k for k in keys) for b in expected)

        quiet = 0
        for s in range(max_warmup_steps):
            before = executor_mod._PROGRAM_COMPILES.value
            one_step(s, count=min(s + 1, tensors_per_step))
            quiet = quiet + 1 \
                if executor_mod._PROGRAM_COMPILES.value == before else 0
            if quiet >= 2 and buckets_warmed():
                break
        compiles0 = executor_mod._PROGRAM_COMPILES.value
        lat = []
        for s in range(timed_steps):
            t0 = time.perf_counter()
            one_step(max_warmup_steps + s)
            lat.append(time.perf_counter() - t0)
        new_compiles = executor_mod._PROGRAM_COMPILES.value - compiles0
        steady_compiles += new_compiles
        p50 = float(np.median(lat))
        step_bytes = tensors_per_step * elems * 4  # per-worker payload
        rows.append({
            "tensor_bytes": elems * 4,
            "p50_ms": round(p50 * 1e3, 3),
            "payload_gb_s": round(step_bytes / p50 / 1e9, 3),
            "timed_phase_compiles": new_compiles,
        })
        log(f"collectives {elems * 4}B/tensor: p50 {rows[-1]['p50_ms']} ms"
            f"  {rows[-1]['payload_gb_s']} GB/s"
            f"  compiles(timed)={new_compiles}")

    # Flight-recorder overhead (the recorder is on by default, so its cost
    # must be visible next to the latency it taxes): raw emit() throughput,
    # plus the added p50 step latency at pipeline depth 2 — the same fused
    # allreduce path timed with the recorder off, then on.
    from horovod_tpu import flight_recorder

    rec = flight_recorder.recorder()
    n_emit = 1_000 if tiny else 100_000
    t0 = time.perf_counter()
    for i in range(n_emit):
        rec.emit("bench_overhead", op=i)
    emit_per_sec = n_emit / (time.perf_counter() - t0)

    fr_elems = 4096
    fr_payload = rng.randn(world, fr_elems).astype(np.float32)

    def depth2_step(step):
        hs = [hvd.allreduce_async(
            hvd.stack_per_worker(list(fr_payload + np.float32(step))),
            name=f"bench/fr/t{j}") for j in range(2)]
        for h in hs:
            hvd.synchronize(h)

    for s in range(2 if tiny else 4):  # warm the fr-name buckets/programs
        depth2_step(1000 + s)
    # interleave recorder-off/on steps (A/B pairs) so dispatch-latency
    # drift does not masquerade as recorder overhead
    was_enabled = rec.enabled
    lat_off, lat_on = [], []
    for s in range(3 if tiny else 15):
        for enabled, lat in ((False, lat_off), (True, lat_on)):
            rec.enabled = enabled
            t0 = time.perf_counter()
            depth2_step(2000 + 2 * s + int(enabled))
            lat.append(time.perf_counter() - t0)
    rec.enabled = was_enabled
    p50_off = float(np.median(lat_off))
    p50_on = float(np.median(lat_on))
    fr_overhead = {
        "emit_events_per_sec": round(emit_per_sec),
        "p50_ms_depth2_recorder_off": round(p50_off * 1e3, 3),
        "p50_ms_depth2_recorder_on": round(p50_on * 1e3, 3),
        "added_p50_ms_depth2": round((p50_on - p50_off) * 1e3, 3),
        "overhead_pct": (round(100.0 * (p50_on - p50_off) / p50_off, 2)
                         if p50_off > 0 else None),
    }
    log("flight recorder: %d events/sec emit; depth-2 p50 %s -> %s ms "
        "(%s%% overhead)" % (
            fr_overhead["emit_events_per_sec"],
            fr_overhead["p50_ms_depth2_recorder_off"],
            fr_overhead["p50_ms_depth2_recorder_on"],
            fr_overhead["overhead_pct"]))
    result = {
        "metric": f"fused allreduce p50 latency, {tensors_per_step}-tensor "
                  f"cycle at {rows[-1]['tensor_bytes']}B/tensor "
                  f"(np={world}, pipelined data plane)",
        "value": rows[-1]["p50_ms"],
        "unit": "ms",
        "vs_baseline": None,
        "sizes": rows,
        "steady_state_compiles": steady_compiles,
        "program_compiles_total": executor_mod._PROGRAM_COMPILES.value,
        "program_cache_hits_total": executor_mod._PROGRAM_CACHE_HITS.value,
        "flight_recorder": fr_overhead,
        **comms_rows(),
        **goodput_rows(),
    }
    if tiny:
        result["tiny"] = True
    print(json.dumps(result), flush=True)
    return result


def integrity_main(tiny: bool = False):
    """Integrity-plane microbench (ISSUE 10): steady-state cost of the
    in-band collective digests on the fused allreduce path, at
    BERT-Large gradient shapes (one encoder layer's worth of kernels per
    step — the fusion buckets the flagship workload actually reduces).

    Three interleaved phases over identical named tensors so dispatch
    drift cannot masquerade as digest cost: integrity OFF (the pre-PR-10
    data plane), ON at the default ``HOROVOD_INTEGRITY_INTERVAL``
    (headline ``value``: added p50 step %, goal < 1%), and ON checking
    EVERY dispatch (the worst case, reported for context). Warmup runs
    with checks on every dispatch so the masked digest program compiles
    before timing starts; the timed phases must add ZERO new program
    compiles (same canary as --collectives).

    ``tiny`` (--tiny / the tier-1 smoke test): toy shapes + 2 steps."""
    hvd.init()
    from horovod_tpu import integrity as integ
    from horovod_tpu.integrity import digest as integ_digest
    from horovod_tpu.runtime import executor as executor_mod

    world = hvd.size()
    if tiny:
        shapes = [(256,), (64, 8)]
        warmup_steps, timed_steps = 3, 2
    else:
        # one BERT-Large encoder layer's gradient tensors (d=1024,
        # ff=4096): two attention kernels + the MLP pair + a layernorm
        shapes = [(1024, 1024), (1024, 1024), (1024, 4096), (4096, 1024),
                  (1024,)]
        warmup_steps, timed_steps = 6, 7
    rng = np.random.RandomState(0)
    payloads = [rng.randn(world, *s).astype(np.float32) for s in shapes]
    n_elems = sum(int(np.prod(s)) for s in shapes)
    log(f"integrity bench: {len(shapes)} tensors, "
        f"{n_elems * 4 / 1e6:.1f} MB/step/worker, np={world}"
        f"{' (tiny)' if tiny else ''}")

    def one_step(step):
        hs = [hvd.allreduce_async(
            hvd.stack_per_worker(list(payloads[j] + np.float32(step))),
            name=f"integ/t{j}") for j in range(len(shapes))]
        for h in hs:
            hvd.synchronize(h)

    saved = {k: os.environ.get(k)
             for k in ("HOROVOD_INTEGRITY", "HOROVOD_INTEGRITY_INTERVAL")}

    def set_phase(interval):
        if interval is None:
            os.environ.pop("HOROVOD_INTEGRITY", None)
            os.environ.pop("HOROVOD_INTEGRITY_INTERVAL", None)
        else:
            os.environ["HOROVOD_INTEGRITY"] = "1"
            os.environ["HOROVOD_INTEGRITY_INTERVAL"] = str(interval)

    default_iv = integ.DEFAULT_INTEGRITY_INTERVAL
    try:
        # warmup with checks on EVERY dispatch: compiles the fused
        # programs AND the masked digest program for every bucket
        set_phase(1)
        for s in range(warmup_steps):
            one_step(s)
        compiles0 = executor_mod._PROGRAM_COMPILES.value
        checks0 = integ_digest._CHECKS.value

        phases = {"off": (None, []), "default": (default_iv, []),
                  "every": (1, [])}
        for s in range(timed_steps):
            for name, (interval, lat) in phases.items():
                set_phase(interval)
                t0 = time.perf_counter()
                one_step(1000 + s * len(phases))
                lat.append(time.perf_counter() - t0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    steady_compiles = executor_mod._PROGRAM_COMPILES.value - compiles0
    checks = integ_digest._CHECKS.value - checks0
    p50 = {name: float(np.median(lat))
           for name, (_, lat) in phases.items()}

    def pct(on):
        return (round(100.0 * (p50[on] - p50["off"]) / p50["off"], 2)
                if p50["off"] > 0 else None)

    result = {
        "metric": f"integrity digest steady-state step overhead "
                  f"(in-band digests every {default_iv} dispatches, "
                  f"{'toy' if tiny else 'BERT-Large layer'} gradient "
                  f"shapes, np={world})",
        "value": pct("default"),
        "unit": "%",
        "goal": "< 1%",
        "p50_ms_integrity_off": round(p50["off"] * 1e3, 3),
        "p50_ms_default_interval": round(p50["default"] * 1e3, 3),
        "p50_ms_every_dispatch": round(p50["every"] * 1e3, 3),
        "every_dispatch_overhead_pct": pct("every"),
        "digest_interval": default_iv,
        "digest_checks_timed_phase": int(checks),
        "steady_state_compiles": int(steady_compiles),
    }
    if tiny:
        result["tiny"] = True
    log(f"integrity: p50 off {result['p50_ms_integrity_off']} ms, "
        f"default-interval {result['p50_ms_default_interval']} ms "
        f"({result['value']}%), every-dispatch "
        f"{result['p50_ms_every_dispatch']} ms "
        f"({result['every_dispatch_overhead_pct']}%); "
        f"compiles(timed)={steady_compiles}")
    print(json.dumps(result), flush=True)
    return result


def memory_main(tiny: bool = False):
    """Memory-plane microbench (ISSUE 13): steady-state cost of the
    tracker's push accounting + reconciliation sampler on the fused
    allreduce path, at BERT-Large gradient shapes.

    Two interleaved phases over identical named tensors (the
    --integrity protocol, so dispatch drift cannot masquerade as tracker
    cost): memory plane OFF (tracker disabled, no sampler thread) and ON
    with the sampler at a deliberately hostile cadence (50 ms — 200x the
    default) plus a per-step grads push. Headline ``value``: added p50
    step %, goal < 1%. Also reports the resulting ledger and the
    claimed-vs-actual reconciliation drift.

    ``tiny`` (--tiny / the tier-1 smoke test): toy shapes + 2 steps."""
    hvd.init()
    from horovod_tpu import memory

    world = hvd.size()
    if tiny:
        shapes = [(256,), (64, 8)]
        warmup_steps, timed_steps = 3, 2
    else:
        shapes = [(1024, 1024), (1024, 1024), (1024, 4096), (4096, 1024),
                  (1024,)]
        warmup_steps, timed_steps = 6, 7
    rng = np.random.RandomState(0)
    payloads = [rng.randn(world, *s).astype(np.float32) for s in shapes]
    n_elems = sum(int(np.prod(s)) for s in shapes)
    log(f"memory bench: {len(shapes)} tensors, "
        f"{n_elems * 4 / 1e6:.1f} MB/step/worker, np={world}"
        f"{' (tiny)' if tiny else ''}")

    t = memory.tracker()
    was_enabled = t.enabled

    def one_step(step, push):
        hs = [hvd.allreduce_async(
            hvd.stack_per_worker(list(payloads[j] + np.float32(step))),
            name=f"mem/t{j}") for j in range(len(shapes))]
        outs = [hvd.synchronize(h) for h in hs]
        if push:  # the eager-path per-step accounting under test
            t.note_tree_bytes("grads", outs)

    def set_phase(on):
        t.enabled = on
        if on:
            t.start(interval=0.05)  # hostile cadence: 200x the default
        else:
            t.stop()

    try:
        set_phase(True)
        for s in range(warmup_steps):
            one_step(s, push=True)

        phases = {"off": (False, []), "on": (True, [])}
        for s in range(timed_steps):
            for name, (on, lat) in phases.items():
                set_phase(on)
                t0 = time.perf_counter()
                one_step(1000 + s * len(phases), push=on)
                lat.append(time.perf_counter() - t0)

        set_phase(True)
        led = t.sample()  # one explicit reconcile for the report
    finally:
        t.stop()
        t.enabled = was_enabled
        if was_enabled:
            t.start()

    p50 = {name: float(np.median(lat)) for name, (_, lat) in phases.items()}
    overhead = (round(100.0 * (p50["on"] - p50["off"]) / p50["off"], 2)
                if p50["off"] > 0 else None)
    drift = led.get("reconcile_drift_ratio")
    result = {
        "metric": f"memory tracker steady-state step overhead "
                  f"(sampler at 50 ms + per-step push, "
                  f"{'toy' if tiny else 'BERT-Large layer'} gradient "
                  f"shapes, np={world})",
        "value": overhead,
        "unit": "%",
        "goal": "< 1%",
        "p50_ms_memory_off": round(p50["off"] * 1e3, 3),
        "p50_ms_memory_on": round(p50["on"] * 1e3, 3),
        "reconcile_drift_ratio": (round(drift, 4)
                                  if isinstance(drift, (int, float))
                                  else None),
        "bytes_per_chip": {
            name: int(rec["bytes"])
            for name, rec in led["subsystems"].items()
            if name != "host_rss" and rec["bytes"]},
        "peak_hbm_bytes": int(t.peak_hbm_bytes()),
        "samples_taken": len(t.samples()),
    }
    if tiny:
        result["tiny"] = True
    log(f"memory: p50 off {result['p50_ms_memory_off']} ms, "
        f"on {result['p50_ms_memory_on']} ms ({overhead}%); "
        f"drift={result['reconcile_drift_ratio']}")
    print(json.dumps(result), flush=True)
    return result


def comms_main(tiny: bool = False):
    """Comms-plane microbench (ISSUE 16): steady-state cost of the
    collective-transport observatory on the fused allreduce path, at
    BERT-Large gradient shapes.

    Two interleaved phases over identical named tensors (the --integrity
    protocol, so dispatch drift cannot masquerade as tracker cost):
    comms accounting OFF (tracker disabled — record() returns at the
    guard) and ON (every dispatch pays the algbw/busbw bookkeeping).
    Headline ``value``: added p50 step %, goal < 1%. The timed phases
    must add ZERO new XLA program compiles (the --collectives canary) —
    the observatory only ever watches the wire, never touches programs.

    ``tiny`` (--tiny / the tier-1 smoke test): toy shapes + 2 steps."""
    hvd.init()
    from horovod_tpu import comms
    from horovod_tpu.runtime import executor as executor_mod

    world = hvd.size()
    if tiny:
        shapes = [(256,), (64, 8)]
        warmup_steps, timed_steps = 3, 2
    else:
        shapes = [(1024, 1024), (1024, 1024), (1024, 4096), (4096, 1024),
                  (1024,)]
        warmup_steps, timed_steps = 6, 7
    rng = np.random.RandomState(0)
    payloads = [rng.randn(world, *s).astype(np.float32) for s in shapes]
    n_elems = sum(int(np.prod(s)) for s in shapes)
    log(f"comms bench: {len(shapes)} tensors, "
        f"{n_elems * 4 / 1e6:.1f} MB/step/worker, np={world}"
        f"{' (tiny)' if tiny else ''}")

    t = comms.tracker()
    was_enabled = t.enabled

    def one_step(step):
        hs = [hvd.allreduce_async(
            hvd.stack_per_worker(list(payloads[j] + np.float32(step))),
            name=f"comms/t{j}") for j in range(len(shapes))]
        for h in hs:
            hvd.synchronize(h)

    try:
        t.enabled = True
        for s in range(warmup_steps):
            one_step(s)

        compiles0 = executor_mod._PROGRAM_COMPILES.value
        phases = {"off": (False, []), "on": (True, [])}
        for s in range(timed_steps):
            for name, (on, lat) in phases.items():
                t.enabled = on
                t0 = time.perf_counter()
                one_step(1000 + s * len(phases))
                lat.append(time.perf_counter() - t0)
        steady_compiles = executor_mod._PROGRAM_COMPILES.value - compiles0
        t.enabled = True
        led = t.ledger()
    finally:
        t.enabled = was_enabled

    p50 = {name: float(np.median(lat)) for name, (_, lat) in phases.items()}
    overhead = (round(100.0 * (p50["on"] - p50["off"]) / p50["off"], 2)
                if p50["off"] > 0 else None)
    lanes = {name: rec["busbw_gbs"] for name, rec in led["lanes"].items()
             if rec.get("busbw_gbs")}
    result = {
        "metric": f"comms tracker steady-state step overhead "
                  f"(per-dispatch algbw/busbw accounting, "
                  f"{'toy' if tiny else 'BERT-Large layer'} gradient "
                  f"shapes, np={world})",
        "value": overhead,
        "unit": "%",
        "goal": "< 1%",
        "p50_ms_comms_off": round(p50["off"] * 1e3, 3),
        "p50_ms_comms_on": round(p50["on"] * 1e3, 3),
        "steady_state_compiles": int(steady_compiles),
        "lane_busbw_gbs": lanes,
        **comms_rows(),
        **goodput_rows(),
    }
    if tiny:
        result["tiny"] = True
    log(f"comms: p50 off {result['p50_ms_comms_off']} ms, "
        f"on {result['p50_ms_comms_on']} ms ({overhead}%); "
        f"compiles(timed)={steady_compiles}; lanes={lanes}")
    print(json.dumps(result), flush=True)
    return result


def goodput_main(tiny: bool = False):
    """Goodput-ledger microbench (ISSUE 19): steady-state cost of the
    productive-time accounting on the profiled step path, at BERT-Large
    gradient shapes.

    Two interleaved phases over identical named tensors (the --comms
    protocol, so dispatch drift cannot masquerade as tracker cost), each
    step bracketed by ``profiler.step`` so the goodput hook at the step
    boundary actually fires: ledger OFF (record_step returns at the
    guard) and ON (every step pays the category bookkeeping + fraction
    sample). Headline ``value``: added p50 step %, goal < 1%. The timed
    phases must add ZERO new XLA program compiles — the ledger only ever
    watches the clock, never touches programs.

    ``tiny`` (--tiny / the tier-1 smoke test): toy shapes + 2 steps."""
    hvd.init()
    from horovod_tpu import goodput, profiler
    from horovod_tpu.runtime import executor as executor_mod

    world = hvd.size()
    if tiny:
        shapes = [(256,), (64, 8)]
        warmup_steps, timed_steps = 3, 2
    else:
        shapes = [(1024, 1024), (1024, 1024), (1024, 4096), (4096, 1024),
                  (1024,)]
        warmup_steps, timed_steps = 6, 7
    rng = np.random.RandomState(0)
    payloads = [rng.randn(world, *s).astype(np.float32) for s in shapes]
    n_elems = sum(int(np.prod(s)) for s in shapes)
    log(f"goodput bench: {len(shapes)} tensors, "
        f"{n_elems * 4 / 1e6:.1f} MB/step/worker, np={world}"
        f"{' (tiny)' if tiny else ''}")

    t = goodput.tracker()
    was_enabled = t.enabled
    prof = profiler._profiler
    prof_was_enabled = prof.enabled
    prof.enabled = True  # the goodput step hook rides the profiler

    def one_step(step):
        with profiler.step(f"goodput/s{step}"):
            hs = [hvd.allreduce_async(
                hvd.stack_per_worker(list(payloads[j] + np.float32(step))),
                name=f"goodput/t{j}") for j in range(len(shapes))]
            for h in hs:
                hvd.synchronize(h)

    try:
        t.enabled = True
        t.start_epoch()
        for s in range(warmup_steps):
            one_step(s)

        compiles0 = executor_mod._PROGRAM_COMPILES.value
        phases = {"off": (False, []), "on": (True, [])}
        for s in range(timed_steps):
            for name, (on, lat) in phases.items():
                t.enabled = on
                t0 = time.perf_counter()
                one_step(1000 + s * len(phases))
                lat.append(time.perf_counter() - t0)
        steady_compiles = executor_mod._PROGRAM_COMPILES.value - compiles0
        t.enabled = True
        led = t.ledger()
    finally:
        t.enabled = was_enabled
        prof.enabled = prof_was_enabled

    p50 = {name: float(np.median(lat)) for name, (_, lat) in phases.items()}
    overhead = (round(100.0 * (p50["on"] - p50["off"]) / p50["off"], 2)
                if p50["off"] > 0 else None)
    result = {
        "metric": f"goodput tracker steady-state step overhead "
                  f"(per-step productive-time accounting, "
                  f"{'toy' if tiny else 'BERT-Large layer'} gradient "
                  f"shapes, np={world})",
        "value": overhead,
        "unit": "%",
        "goal": "< 1%",
        "p50_ms_goodput_off": round(p50["off"] * 1e3, 3),
        "p50_ms_goodput_on": round(p50["on"] * 1e3, 3),
        "steady_state_compiles": int(steady_compiles),
        "steps_productive": led["steps_productive"],
        "goodput_fraction": led["goodput_fraction"],
    }
    if tiny:
        result["tiny"] = True
    log(f"goodput: p50 off {result['p50_ms_goodput_off']} ms, "
        f"on {result['p50_ms_goodput_on']} ms ({overhead}%); "
        f"compiles(timed)={steady_compiles}; "
        f"fraction={led['goodput_fraction']}")
    print(json.dumps(result), flush=True)
    return result


def _bert_large_param_shapes():
    """BERT-Large parameter shapes (L=24, d=1024, ff=4096, vocab 30522,
    seq 512) as a flat dict — ~335M params, the flagship workload's
    optimizer-state footprint without building the model."""
    shapes = {
        "embed/token": (30522, 1024), "embed/pos": (512, 1024),
        "embed/type": (2, 1024),
        "embed/ln_scale": (1024,), "embed/ln_bias": (1024,),
        "pooler/kernel": (1024, 1024), "pooler/bias": (1024,),
    }
    for i in range(24):
        p = "layer%02d/" % i
        shapes.update({
            p + "q_kernel": (1024, 1024), p + "q_bias": (1024,),
            p + "k_kernel": (1024, 1024), p + "k_bias": (1024,),
            p + "v_kernel": (1024, 1024), p + "v_bias": (1024,),
            p + "o_kernel": (1024, 1024), p + "o_bias": (1024,),
            p + "mlp_in_kernel": (1024, 4096), p + "mlp_in_bias": (4096,),
            p + "mlp_out_kernel": (4096, 1024), p + "mlp_out_bias": (1024,),
            p + "ln1_scale": (1024,), p + "ln1_bias": (1024,),
            p + "ln2_scale": (1024,), p + "ln2_bias": (1024,),
        })
    return shapes


def sharded_optimizer_main(tiny: bool = False):
    """ZeRO sharded-training microbench: the optimizer UPDATE phase
    (gradient reduction + AdamW + new params on every chip) at the
    BERT-Large parameter shape, replicated vs sharded stages 1/2/3.

    Replicated: ``allreduce_gradients`` + jitted f32 optax adamw —
    every chip holds the full mu/nu. Stage 1: ``hvd.sharded_adamw`` —
    reduce-scatter, fused flat-buffer AdamW on the local fp32
    master/moment shards, allgather. Stage 2: gradients pre-scattered
    (``hvd.scatter_gradients``), so only the scatter half of the
    allreduce rides the wire. Stage 3: params sharded at rest
    (``hvd.shard_params``) and re-gathered bucket-by-bucket with the
    prefetch window, as a forward pass would. Each stage reports p50
    update ms, ``bytes_per_chip`` for params/grads/optimizer state,
    gradient and total wire bytes per step, and the steady-state
    program-build count over the timed phase (must be zero — same
    invariant as the data-plane microbench).

    ``tiny`` (--tiny / the tier-1 smoke test): a toy shape + 2 steps."""
    import optax as _optax

    from horovod_tpu.parallel import zero as zero_mod
    from horovod_tpu.parallel.dp import allreduce_gradients

    hvd.init()
    world = hvd.size()
    if tiny:
        shapes = {"w0": (256, 64), "b0": (64,), "w1": (1000,),
                  "emb": (128, 32)}
        warmup_steps, timed_steps = 1, 2
    else:
        shapes = _bert_large_param_shapes()
        warmup_steps, timed_steps = 2, 8
    rng = np.random.RandomState(0)
    params = {k: jnp.asarray(rng.standard_normal(v).astype(np.float32)
                             * 0.02)
              for k, v in shapes.items()}
    grads = {k: jnp.asarray(rng.standard_normal(v).astype(np.float32))
             for k, v in shapes.items()}
    n_params = sum(int(np.prod(v)) for v in shapes.values())
    log(f"sharded-optimizer bench: {n_params / 1e6:.0f}M params, "
        f"np={world}{' (tiny)' if tiny else ''}")

    def _tree_bytes(tree):
        return sum(x.nbytes for x in jax.tree_util.tree_leaves(tree)
                   if hasattr(x, "nbytes"))

    def _metric_value(name, default=None):
        m = hvd.metrics().get(name)
        if not m or not m.get("values"):
            return default
        return m["values"][0]["value"]

    # --- replicated baseline: allreduce + full-state adamw on every chip
    inner = _optax.adamw(1e-4)
    rep_state = inner.init(params)
    rep_bytes = _tree_bytes(rep_state)

    @jax.jit
    def rep_step(g, s, p):
        upd, s = inner.update(g, s, p)
        return _optax.apply_updates(p, upd), s

    def replicated_update(p, s, g):
        g = allreduce_gradients(g, average=True)
        return rep_step(g, s, p)

    lat_rep = []
    p, s = params, rep_state
    for step in range(warmup_steps + timed_steps):
        t0 = time.perf_counter()
        p, s = replicated_update(p, s, grads)
        jax.block_until_ready(jax.tree_util.tree_leaves(p)[0])
        if step >= warmup_steps:
            lat_rep.append(time.perf_counter() - t0)

    # --- sharded: RS + fused flat AdamW on the local shard + AG
    sopt = hvd.sharded_adamw(1e-4)
    sh_state = sopt.init(params)
    lat_sh = []
    builds_before = None
    p = params
    for step in range(warmup_steps + timed_steps):
        if step == warmup_steps:
            builds_before = _metric_value(
                "horovod_sharded_program_builds_total", 0)
        t0 = time.perf_counter()
        p, sh_state = sopt.apply(p, sh_state, grads)
        jax.block_until_ready(jax.tree_util.tree_leaves(p)[0])
        if step >= warmup_steps:
            lat_sh.append(time.perf_counter() - t0)
    steady_builds = (_metric_value("horovod_sharded_program_builds_total",
                                   0) - builds_before)
    sharded_bytes = _metric_value("horovod_sharded_state_bytes",
                                  _tree_bytes(sh_state))

    # --- per-stage rows: stage 2 (grads pre-scattered) and stage 3
    # (params sharded at rest + bucket-wise prefetched gather), with wire
    # bytes per step read off the zero-lane RS/AG counters
    _RS = "horovod_sharded_reducescatter_bytes_total"
    _AG = "horovod_sharded_allgather_bytes_total"

    def _spec_shard_bytes(spec):
        return sum(g.shard_elems * np.dtype(g.dtype).itemsize
                   for g in spec.groups)

    def _timed_stage(step_fn, p0, s0):
        lat, marks = [], None
        p_, s_ = p0, s0
        for step in range(warmup_steps + timed_steps):
            if step == warmup_steps:
                marks = (_metric_value(
                    "horovod_sharded_program_builds_total", 0),
                    _metric_value(_RS, 0), _metric_value(_AG, 0))
            t0 = time.perf_counter()
            p_, s_ = step_fn(p_, s_)
            jax.block_until_ready(jax.tree_util.tree_leaves(p_)[0])
            if step >= warmup_steps:
                lat.append(time.perf_counter() - t0)
        builds = (_metric_value("horovod_sharded_program_builds_total", 0)
                  - marks[0])
        rs = (_metric_value(_RS, 0) - marks[1]) / timed_steps
        ag = (_metric_value(_AG, 0) - marks[2]) / timed_steps
        return float(np.median(lat)), rs, ag, builds, s_

    params_full = _tree_bytes(params)
    grads_full = _tree_bytes(grads)

    def _stage_row(p50_s, rs, ag, builds, pbytes, gbytes):
        return {
            "update_p50_ms": round(p50_s * 1e3, 2),
            "bytes_per_chip": {
                "params": int(pbytes), "grads": int(gbytes),
                "optimizer_state": int(sharded_bytes)},
            "grad_wire_bytes_per_step": int(rs),
            "wire_bytes_per_step": int(rs + ag),
            "steady_state_builds": int(builds),
        }

    # stage 2: scatter each step's gradients, feed the shard to the
    # partition-aligned optimizer; the trailing AG rebuilds full params
    sopt2 = hvd.sharded_adamw(1e-4)
    s2_state = sopt2.init(params)

    def _step2(p_, s_):
        sg = zero_mod.scatter_gradients(grads, spec=s_.spec)
        return sopt2.apply(p_, s_, sg)

    p50_s2, rs2, ag2, builds2, s2_state = _timed_stage(
        _step2, params, s2_state)
    grad_shard_bytes = _spec_shard_bytes(s2_state.spec)
    stage2 = _stage_row(p50_s2, rs2, ag2, builds2,
                        params_full, grad_shard_bytes)

    # stage 3: params sharded at rest; the update keeps them sharded and
    # each step re-gathers bucket-by-bucket under the prefetch window,
    # standing in for the forward pass's on-demand consumption
    sopt3 = hvd.sharded_adamw(1e-4)
    sp3 = hvd.shard_params(params)
    s3_state = sopt3.init(sp3)
    param_shard_bytes = _spec_shard_bytes(sp3.spec)

    def _step3(p_, s_):
        sg = zero_mod.scatter_gradients(grads, spec=s_.spec)
        p_, s_ = sopt3.apply(p_, s_, sg)
        for _gi, _bucket in hvd.iter_param_buckets(p_):
            pass
        return p_, s_

    p50_s3, rs3, ag3, builds3, _ = _timed_stage(_step3, sp3, s3_state)
    stage3 = _stage_row(p50_s3, rs3, ag3, builds3,
                        param_shard_bytes, grad_shard_bytes)
    stage3["gather_hidden_fraction"] = round(
        zero_mod.gather_hidden_fraction(), 4)

    p50_rep = float(np.median(lat_rep))
    p50_sh = float(np.median(lat_sh))
    stage1 = {
        "update_p50_ms": round(p50_sh * 1e3, 2),
        "bytes_per_chip": {
            "params": int(params_full), "grads": int(grads_full),
            "optimizer_state": int(sharded_bytes)},
        # stage 1 exchanges the full gradient: RS + AG = one allreduce
        "grad_wire_bytes_per_step": int(rs2 + ag2),
        "wire_bytes_per_step": int(rs2 + ag2),
        "steady_state_builds": int(steady_builds),
    }
    result = {
        "metric": f"sharded optimizer update p50 (ZeRO-1 fused AdamW, "
                  f"BERT-Large shape {n_params / 1e6:.0f}M params, "
                  f"np={world})",
        "value": round(p50_sh * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(p50_rep / p50_sh, 3) if p50_sh > 0 else None,
        "replicated_p50_ms": round(p50_rep * 1e3, 2),
        "sharded_p50_ms": round(p50_sh * 1e3, 2),
        "opt_state_bytes_per_chip": {
            "replicated": int(rep_bytes),
            "sharded": int(sharded_bytes),
        },
        "state_bytes_reduction_x": (
            round(rep_bytes / sharded_bytes, 2) if sharded_bytes else None),
        "steady_state_program_builds": int(steady_builds),
        "stages": {"stage1": stage1, "stage2": stage2, "stage3": stage3},
        **memory_rows(),
        **comms_rows(),
        **goodput_rows(),
    }
    if tiny:
        result["tiny"] = True
    log(f"update p50: replicated {result['replicated_p50_ms']} ms, "
        f"sharded {result['sharded_p50_ms']} ms; state bytes/chip "
        f"{rep_bytes} -> {sharded_bytes} "
        f"({result['state_bytes_reduction_x']}x); steady-state program "
        f"builds {steady_builds}")
    for sname, row in result["stages"].items():
        log(f"  {sname}: update p50 {row['update_p50_ms']} ms, "
            f"bytes/chip params={row['bytes_per_chip']['params']} "
            f"grads={row['bytes_per_chip']['grads']} "
            f"opt={row['bytes_per_chip']['optimizer_state']}, grad wire "
            f"{row['grad_wire_bytes_per_step']} B/step, total wire "
            f"{row['wire_bytes_per_step']} B/step, steady-state builds "
            f"{row['steady_state_builds']}")
    print(json.dumps(result), flush=True)
    return result


def checkpoint_main(tiny: bool = False):
    """Crash-consistent checkpoint microbench: commit latency, inline
    (snapshot-to-slab) cost, bytes/rank, and the derived steady-state
    step overhead of periodic async commits at the BERT-Large optimizer
    footprint (params + fp32 Adam moments, ~4 GB/rank at np=1).

    The training step proxy is the jitted full-state AdamW update at the
    same shape — the commit's inline cost amortized over a realistic
    checkpoint interval (every 100 steps), divided by that step time, is
    the headline ``value`` (goal: < 2%). Commits use the same zero-copy
    handoff as the elastic integration (``copy=False`` — the trees are
    an immutable snapshot, so the slab copy is skipped). Also measured directly: one
    step timed WHILE the background writer drains, so compute/IO
    contention shows up as ``contended_step_slowdown_pct`` rather than
    being assumed away.

    ``tiny`` (--tiny / the tier-1 smoke test): toy shapes, one commit."""
    import shutil
    import tempfile

    import optax as _optax

    from horovod_tpu import ckpt as _ckpt
    from horovod_tpu.ckpt import stats as _ckpt_stats

    hvd.init()
    if tiny:
        shapes = {"w0": (256, 64), "b0": (64,), "emb": (128, 32)}
        warmup_steps, timed_steps, n_commits, interval = 1, 2, 1, 100
    else:
        shapes = _bert_large_param_shapes()
        warmup_steps, timed_steps, n_commits, interval = 1, 3, 2, 100
    rng = np.random.RandomState(0)
    params = {k: jnp.asarray(rng.standard_normal(v).astype(np.float32)
                             * 0.02)
              for k, v in shapes.items()}
    grads = {k: jnp.asarray(rng.standard_normal(v).astype(np.float32))
             for k, v in shapes.items()}
    n_params = sum(int(np.prod(v)) for v in shapes.values())
    log(f"checkpoint bench: {n_params / 1e6:.0f}M params"
        f"{' (tiny)' if tiny else ''}")

    inner = _optax.adamw(1e-4)
    opt_state = inner.init(params)

    @jax.jit
    def train_step(g, s, p):
        upd, s = inner.update(g, s, p)
        return _optax.apply_updates(p, upd), s

    # baseline: the update step alone
    p, s = params, opt_state
    lat_step = []
    for step in range(warmup_steps + timed_steps):
        t0 = time.perf_counter()
        p, s = train_step(grads, s, p)
        jax.block_until_ready(jax.tree_util.tree_leaves(p)[0])
        if step >= warmup_steps:
            lat_step.append(time.perf_counter() - t0)
    t_step = float(np.median(lat_step))

    directory = tempfile.mkdtemp(prefix="hvd-bench-ckpt-")
    mgr = _ckpt.CheckpointManager(directory, async_write=True, keep=1)
    trees = {"params": p, "opt": jax.device_get(s)}
    lat_inline, lat_e2e, lat_contended = [], [], []
    bytes_rank = 0
    try:
        for i in range(n_commits):
            t0 = time.perf_counter()
            # copy=False mirrors the elastic integration: the trees are
            # an immutable snapshot (jax arrays; rebound, never mutated)
            mgr.commit(trees, step=i + 1, rank=0, world=1, copy=False)
            lat_inline.append(time.perf_counter() - t0)
            # one step racing the background serialize+write: real
            # contention, not an assumption
            tc = time.perf_counter()
            p, s = train_step(grads, s, p)
            jax.block_until_ready(jax.tree_util.tree_leaves(p)[0])
            lat_contended.append(time.perf_counter() - tc)
            mgr.wait()
            lat_e2e.append(time.perf_counter() - t0)
        latest = _ckpt.latest_step(directory)
        from horovod_tpu.ckpt import manifest as _manifest
        mf = _manifest.load_manifest(directory, latest)
        bytes_rank = int(mf["shards"][0]["bytes"])
    finally:
        mgr.close()
        shutil.rmtree(directory, ignore_errors=True)

    t_inline = float(np.median(lat_inline))
    t_e2e = float(np.median(lat_e2e))
    t_contended = float(np.median(lat_contended))
    overhead_pct = 100.0 * t_inline / (t_inline + interval * t_step) \
        if t_step > 0 else None
    contention_pct = (100.0 * (t_contended - t_step) / t_step
                      if t_step > 0 else None)
    result = {
        "metric": f"checkpoint steady-state step overhead (async commit "
                  f"every {interval} steps, "
                  f"{'toy shape' if tiny else 'BERT-Large shape'} "
                  f"{n_params / 1e6:.0f}M params + fp32 Adam moments)",
        "value": round(overhead_pct, 3) if overhead_pct is not None
        else None,
        "unit": "%",
        "goal": "< 2%",
        "commit_inline_p50_ms": round(t_inline * 1e3, 2),
        "commit_e2e_p50_ms": round(t_e2e * 1e3, 2),
        "step_p50_ms": round(t_step * 1e3, 2),
        "contended_step_slowdown_pct": (
            round(contention_pct, 1) if contention_pct is not None
            else None),
        "bytes_per_rank": bytes_rank,
        "checkpoint_interval_steps": interval,
        "commits_abandoned": int(
            _ckpt_stats.COMMITS_ABANDONED.value
            if hasattr(_ckpt_stats.COMMITS_ABANDONED, "value") else 0),
    }
    if tiny:
        result["tiny"] = True
    log(f"commit inline p50 {result['commit_inline_p50_ms']} ms, e2e "
        f"{result['commit_e2e_p50_ms']} ms, {bytes_rank} bytes/rank; "
        f"step {result['step_p50_ms']} ms -> "
        f"{result['value']}% overhead at every-{interval}-steps "
        f"(contended step +{result['contended_step_slowdown_pct']}%)")
    print(json.dumps(result), flush=True)
    return result


def serve_main(tiny: bool = False, prefix_heavy: bool = False):
    """``--serve``: load-generate Poisson traffic against an in-process
    continuous-batching replica set (serve/; docs/inference.md) and
    report the serving headline — p50/p99 request latency, tokens/s/chip
    and batch occupancy — plus the zero-steady-state-compiles canary:
    after one warmup prefill per prompt-length bucket per replica, the
    measured window must compile NOTHING (the fixed-shape decode program
    and the bucketed prefill programs are already hot).

    ``--prefix-heavy`` switches the traffic to the shared-system-prompt
    shape (every request opens with the same long prefix, RAG/chat
    style) and runs it twice on one paged replica set — unshared
    baseline first, shared second — so the headline carries the prefix-
    cache effect as a pair: ``p50_ttft_ms`` vs ``p50_ttft_ms_no_share``
    and the token-weighted ``prefix_hit_rate``. Forces
    ``HOROVOD_SERVE_PAGED`` semantics (serve/paging.py); the remaining
    paging knobs still come from the environment.

    ``--tiny`` shrinks to a toy model + 16 requests for the tier-1 smoke
    (tests/test_bench_smoke.py); numbers are then meaningless."""
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import GPT2Small, Transformer
    from horovod_tpu.serve import prompt_bucket, serve as hvd_serve

    if tiny:
        model = Transformer(vocab_size=128, d_model=32, num_layers=2,
                            num_heads=2, d_ff=64, max_seq=96, causal=True,
                            dtype=jnp.float32)
        replicas, slots, n_requests = 2, 4, 16
        rate_rps, max_new = 400.0, 8
        prompt_choices = (4, 9, 17, 33)
        prefix_len, tail_len = 48, 5
    else:
        # "GPT-small" replica set: the GPT-2 shape at a serving-friendly
        # context length
        model = GPT2Small(vocab_size=50304, max_seq=512)
        replicas, slots, n_requests = 2, 8, 200
        rate_rps, max_new = 40.0, 32
        prompt_choices = (24, 56, 100, 180, 250)
        prefix_len, tail_len = 192, 12

    log(f"serve: initializing {replicas} replica(s) "
        f"(slots={slots}, max_new={max_new}"
        f"{', prefix-heavy' if prefix_heavy else ''})")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    overrides = dict(slots=slots, max_new_tokens=max_new,
                     admission_ms=25.0, decode_block=4,
                     max_batch_tokens=4096)
    if prefix_heavy:
        overrides["paged"] = True   # prefix reuse needs the paged cache
    handle = hvd_serve(model, params, replicas=replicas, **overrides)
    try:
        # warmup: hit every prompt-length bucket on EVERY replica's own
        # program cache (replicas compile independently), plus one
        # decode step each — all while the queue is idle, so the replica
        # threads never race these direct engine calls. Warmup prompts
        # are DISTINCT per bucket ([b]*b): under HOROVOD_SERVE_PAGED a
        # repeated prompt would prefix-hit the previous bucket's pages,
        # shrink the computed suffix, and leave the larger prefill
        # program cold — a steady-state compile later.
        warm_lens = list(prompt_choices)
        if prefix_heavy:
            # the shared-prefix phase prefills the full prompt once,
            # then only the post-hit tail — warm both bucket shapes
            warm_lens += [tail_len, prefix_len + tail_len]
        buckets = sorted({prompt_bucket(p, model.max_seq)
                          for p in warm_lens})
        for replica in handle._replicas:
            for b in buckets:
                replica.engine.prefill(0, [b % model.vocab_size] * b)
            replica.engine.decode([0], [1], [0])
        warm_compiles = handle.compiles_total()
        warm_steps = sum(r.engine.decode_steps for r in handle._replicas)
        log(f"serve: warm ({warm_compiles} compiles across "
            f"{len(buckets)} buckets x {replicas} replicas)")

        rng = np.random.RandomState(0)

        def run_phase(prompts):
            """Poisson-offered load; returns (completions, elapsed_s)."""
            uids = []
            t_phase = time.perf_counter()
            for prompt in prompts:
                time.sleep(rng.exponential(1.0 / rate_rps))
                uids.append(handle.submit(prompt))
            phase_outs = [handle.result(u, timeout=300.0) for u in uids]
            return phase_outs, time.perf_counter() - t_phase

        def random_prompt(length):
            return rng.randint(1, model.vocab_size, length).tolist()

        ttft_no_share_ms = None
        t0 = time.perf_counter()
        if prefix_heavy:
            # phase A — unshared baseline: same lengths, same load, no
            # common prefix, so every prefill computes the full prompt
            base_outs, _ = run_phase(
                [random_prompt(prefix_len + tail_len)
                 for _ in range(n_requests)])
            ttft_no_share_ms = sorted(o.ttft_s * 1000.0
                                      for o in base_outs)
            # phase B — shared system prompt + short unique tails; every
            # 4th request repeats a tail so the exact-replay path (a
            # whole-prompt hit: zero prefill compute) is exercised too
            shared = random_prompt(prefix_len)
            tails = [random_prompt(tail_len) for _ in range(n_requests)]
            for i in range(3, n_requests, 4):
                tails[i] = tails[i - 2]
            reused0 = sum(r.engine.reused_tokens
                          for r in handle._replicas)
            computed0 = sum(r.engine.computed_tokens
                            for r in handle._replicas)
            t0 = time.perf_counter()
            outs, elapsed = run_phase([shared + t for t in tails])
        else:
            outs, elapsed = run_phase(
                [random_prompt(int(rng.choice(prompt_choices)))
                 for _ in range(n_requests)])

        latencies_ms = sorted(o.latency_s * 1000.0 for o in outs)
        ttft_ms = sorted(o.ttft_s * 1000.0 for o in outs)
        decode_tokens = sum(len(o.tokens) for o in outs)
        steps = (sum(r.engine.decode_steps for r in handle._replicas)
                 - warm_steps)
        occ = sum(r.occupancy_sum for r in handle._replicas)

        # interleaved A/B overhead probe: decode-path cost with the
        # tracing plane off vs on, doing exactly the per-step work the
        # replica loop does — a block-step counter increment per step
        # and ONE span record per decode block (the handle runs
        # decode_block=4). Arms interleave so clock drift and cache
        # effects cancel; runs on the hot decode program with the queue
        # idle, so it must also compile nothing.
        from horovod_tpu import tracing as tracing_mod

        probe_engine = handle._replicas[0].engine
        n_probe = 60 if tiny else 200
        tracer = tracing_mod.tracer()
        was_enabled = tracer.enabled
        off_s, on_s = [], []
        block_steps, block_t0 = 0, time.time()
        for i in range(2 * n_probe):
            trace_on = i % 2 == 1
            tracer.enabled = trace_on
            t_probe = time.perf_counter()
            probe_engine.decode([0], [1], [0])
            if trace_on:
                block_steps += 1
                if block_steps >= handle.policy.decode_block:
                    t1 = time.time()
                    tracing_mod.record(
                        "request.decode_block", block_t0, t1 - block_t0,
                        trace_id="bench-ab", tokens=block_steps)
                    block_t0, block_steps = t1, 0
            (on_s if trace_on else off_s).append(
                time.perf_counter() - t_probe)
        tracer.enabled = was_enabled
        p50_off = float(np.percentile(off_s, 50))
        p50_on = float(np.percentile(on_s, 50))
        tracing_overhead_pct = (100.0 * (p50_on - p50_off) / p50_off
                                if p50_off > 0 else 0.0)
        log(f"serve: tracing A/B decode p50 off={p50_off * 1e3:.3f} ms "
            f"on={p50_on * 1e3:.3f} ms ({tracing_overhead_pct:+.2f}%)")

        # measured AFTER the probe: the tracing arm must not have
        # compiled anything either
        steady_compiles = handle.compiles_total() - warm_compiles
        slo = tracing_mod.slo_state()
        result = {
            "bench": "serve",
            "metric": "serving decode throughput (Poisson load, "
                      "continuous batching)",
            "value": round(decode_tokens / elapsed / replicas, 2),
            "unit": "tokens/sec/chip",
            "replicas": replicas,
            "requests": n_requests,
            "offered_rps": rate_rps,
            "p50_latency_ms": round(
                float(np.percentile(latencies_ms, 50)), 3),
            "p99_latency_ms": round(
                float(np.percentile(latencies_ms, 99)), 3),
            "p50_ttft_ms": round(float(np.percentile(ttft_ms, 50)), 3),
            "p99_ttft_ms": round(float(np.percentile(ttft_ms, 99)), 3),
            "avg_batch_occupancy": round(occ / max(steps, 1), 3),
            "steady_state_compiles": steady_compiles,
            "warmup_compiles": warm_compiles,
            "served_by": sorted({o.rank for o in outs}),
            # KV bytes/chip next to tokens/s/chip (docs/memory.md): the
            # replica stats carry per-replica cache bytes + slot-
            # occupancy-weighted utilization
            "kv_cache_bytes_per_chip": int(
                sum(r.stats()["kv_cache_bytes"]
                    for r in handle._replicas) / max(replicas, 1)),
            "kv_utilization": round(
                sum(r.stats()["kv_utilization"]
                    for r in handle._replicas) / max(replicas, 1), 3),
            "paged": bool(handle.policy.paged),
            # SLO plane (tracing.py; docs/tracing.md): per-objective
            # burn rate + remaining error budget over the run, and the
            # decode-path cost of having the plane on at all
            "tracing_overhead_pct": round(tracing_overhead_pct, 2),
            "spans_recorded": tracing_mod.tracer().spans_recorded(),
            "slo_requests_scored": slo["requests_scored"],
            "slo_burn_rate": {
                obj: slo["slo"][obj]["burn_rate"]
                for obj in ("ttft", "latency", "availability")},
            "slo_error_budget_remaining": {
                obj: slo["slo"][obj]["error_budget_remaining"]
                for obj in ("ttft", "latency", "availability")},
            "tiny": tiny,
            **memory_rows(params),
            **comms_rows(),
            **goodput_rows(),
        }
        if handle.policy.paged:
            # paged-cache headline (serve/paging.py): pool occupancy per
            # decode step, token-weighted prefix reuse, and the
            # admission pressure valves actually firing
            stats = [r.stats() for r in handle._replicas]
            result["page_utilization"] = round(
                sum(s["page_utilization"] for s in stats)
                / max(replicas, 1), 3)
            result["prefix_hit_rate"] = round(
                sum(s["prefix_hit_rate"] for s in stats)
                / max(replicas, 1), 3)
            result["preemptions"] = sum(s["preemptions"] for s in stats)
            result["cow_copies"] = sum(s["pages"]["cow_copies"]
                                       for s in stats)
        if prefix_heavy:
            result["prefix_heavy"] = True
            result["p50_ttft_ms_no_share"] = round(
                float(np.percentile(ttft_no_share_ms, 50)), 3)
            # hit rate over the SHARED phase only — the baseline phase
            # computes everything and would dilute the headline
            reused = (sum(r.engine.reused_tokens
                          for r in handle._replicas) - reused0)
            computed = (sum(r.engine.computed_tokens
                            for r in handle._replicas) - computed0)
            result["prefix_hit_rate"] = round(
                reused / max(reused + computed, 1), 3)
            log(f"serve: prefix-heavy p50 ttft shared "
                f"{result['p50_ttft_ms']} ms vs unshared "
                f"{result['p50_ttft_ms_no_share']} ms, hit rate "
                f"{result['prefix_hit_rate']}")
    finally:
        handle.close()
    print(json.dumps(result), flush=True)
    return result


def tiny_main():
    """Bare ``--tiny``: a toy flagship headline through the REAL measured
    path — DistributedOptimizer + make_train_round + the step profiler —
    in seconds on any backend. The tier-1 smoke for the step_breakdown /
    comm_hidden_fraction fields; the numbers are meaningless."""
    import flax.linen as nn

    class TinyNet(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            return nn.Dense(10)(nn.relu(nn.Dense(32)(x)))

    hvd.init()
    n_chips = hvd.size()
    batch_per_chip, steps_per_round, rounds = 8, 2, 3
    global_batch = batch_per_chip * n_chips
    model = TinyNet()
    optimizer = hvd.DistributedOptimizer(optax.sgd(0.01 * n_chips))
    state = training.create_train_state(model, optimizer, (1, 8, 8, 3))
    round_fn, batch_sharding = training.make_train_round(
        model, optimizer, steps=steps_per_round)
    rng = np.random.RandomState(0)
    images = jax.device_put(
        rng.uniform(-1, 1, (global_batch, 8, 8, 3)).astype(np.float32),
        batch_sharding)
    labels = jax.device_put(
        rng.randint(0, 10, (global_batch,)).astype(np.int32),
        batch_sharding)
    params, stats, opt_state = (state.params, state.batch_stats,
                                state.opt_state)
    loss, params, stats, opt_state = round_fn(params, stats, opt_state,
                                              images, labels)  # warmup
    jax.block_until_ready(loss)
    # ~2x3e4 MACs/image through the two dense layers; fwd+bwd ≈ 3x
    flops_per_image = 3 * 2 * (8 * 8 * 3 * 32 + 32 * 10)
    enable_profiler(batch_per_chip * steps_per_round * flops_per_image)
    rates = []
    for r in range(rounds):
        t0 = time.perf_counter()
        with hvd.profiler.step(f"tiny round {r}"):
            loss, params, stats, opt_state = round_fn(
                params, stats, opt_state, images, labels)
            jax.block_until_ready(loss)
        rates.append(global_batch * steps_per_round
                     / (time.perf_counter() - t0))
    breakdown, hidden_fraction, hidden_bytes = step_profile(rounds)
    per_chip = float(np.median(rates)) / n_chips
    result = {
        "metric": "images/sec/chip (tiny MLP smoke, synthetic)",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "mfu": mfu(per_chip * flops_per_image),
        "step_breakdown": breakdown,
        "comm_hidden_fraction": hidden_fraction,
        "comm_hidden_fraction_bytes": hidden_bytes,
        "tiny": True,
        **memory_rows(params),
        **comms_rows(),
        **goodput_rows(),
    }
    print(json.dumps(result), flush=True)
    return result


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default=None,
                        choices=["resnet50", "inception", "vgg", "bert",
                                 "bert-large", "gpt2"],
                        help="run ONE model headline; default (no flags) "
                             "runs every headline plus the control-plane "
                             "lines")
    parser.add_argument("--all", action="store_true",
                        help="emit every model headline + the "
                             "control-plane lines (same as no flags; "
                             "kept for compatibility with r3 scripts)")
    parser.add_argument("--control-plane", action="store_true",
                        help="benchmark the control plane (negotiation/"
                             "cache/fusion/autotune) at np=4 on host")
    parser.add_argument("--hierarchy", action="store_true",
                        help="A/B flat vs hierarchical host collectives "
                             "(group size 2) with/without a throttled "
                             "cross-group hop and the fp16 slow-hop "
                             "codec, at np=4 on host; full mode adds "
                             "the autotuner convergence ratio")
    parser.add_argument("--collectives", action="store_true",
                        help="microbench the data plane: steady-state "
                             "fused allreduce latency vs payload size + "
                             "XLA compile count (one JSON line)")
    parser.add_argument("--integrity", action="store_true",
                        help="microbench the numerical-integrity plane: "
                             "in-band digest overhead vs interval at "
                             "BERT-Large gradient shapes + compile-count "
                             "canary (one JSON line)")
    parser.add_argument("--sharded-optimizer", action="store_true",
                        help="microbench the ZeRO-1 sharded optimizer "
                             "update phase (replicated vs sharded AdamW "
                             "at the BERT-Large shape; one JSON line)")
    parser.add_argument("--checkpoint", action="store_true",
                        help="microbench crash-consistent checkpointing: "
                             "async commit inline/e2e latency, bytes/rank "
                             "and the derived steady-state step overhead "
                             "at the BERT-Large shape (one JSON line)")
    parser.add_argument("--serve", action="store_true",
                        help="benchmark the online serving plane: Poisson "
                             "arrivals against a GPT-small continuous-"
                             "batching replica set — p50/p99 latency, "
                             "tokens/s/chip, batch occupancy and the "
                             "zero-steady-state-compiles canary (one "
                             "JSON line)")
    parser.add_argument("--prefix-heavy", action="store_true",
                        help="with --serve: shared-system-prompt traffic "
                             "on a paged replica set, run unshared then "
                             "shared — headline adds prefix_hit_rate and "
                             "p50_ttft_ms_no_share (serve/paging.py)")
    parser.add_argument("--memory", action="store_true",
                        help="microbench the memory telemetry plane: "
                             "tracker push + reconciliation sampler "
                             "overhead at BERT-Large gradient shapes, "
                             "interleaved A/B, plus the ledger and "
                             "claimed-vs-actual drift (one JSON line)")
    parser.add_argument("--comms", action="store_true",
                        help="microbench the collective-transport "
                             "observatory: per-dispatch algbw/busbw "
                             "accounting overhead at BERT-Large gradient "
                             "shapes, interleaved A/B + compile-count "
                             "canary (one JSON line)")
    parser.add_argument("--goodput", action="store_true",
                        help="microbench the goodput ledger: per-step "
                             "productive-time accounting overhead at "
                             "BERT-Large gradient shapes, interleaved "
                             "A/B + compile-count canary (one JSON "
                             "line)")
    parser.add_argument("--tiny", action="store_true",
                        help="toy sizes + a couple of steps for "
                             "--collectives/--sharded-optimizer/"
                             "--checkpoint/--serve, or (with "
                             "no workload flag) a toy flagship headline "
                             "with step_breakdown/comm_hidden_fraction — "
                             "the tier-1 smoke-test mode; numbers are "
                             "meaningless")
    parser.add_argument("--budget-seconds", type=float, default=None,
                        help="wall-clock budget for the no-flag sweep; "
                             "bonus workloads are trimmed or skipped "
                             "(loudly) once it would be exceeded "
                             "(default: BENCH_TIME_BUDGET env, 660)")
    cli = parser.parse_args()
    if cli.serve:
        serve_main(tiny=cli.tiny, prefix_heavy=cli.prefix_heavy)
    elif cli.memory:
        memory_main(tiny=cli.tiny)
    elif cli.comms:
        comms_main(tiny=cli.tiny)
    elif cli.goodput:
        goodput_main(tiny=cli.tiny)
    elif cli.collectives:
        collectives_main(tiny=cli.tiny)
    elif cli.integrity:
        integrity_main(tiny=cli.tiny)
    elif cli.checkpoint:
        checkpoint_main(tiny=cli.tiny)
    elif cli.sharded_optimizer:
        sharded_optimizer_main(tiny=cli.tiny)
    elif cli.control_plane:
        control_plane_main()
    elif cli.hierarchy:
        hierarchy_main(tiny=cli.tiny)
    elif cli.model is not None and not cli.all:
        if cli.model in ("bert", "bert-large", "gpt2"):
            transformer_main(cli.model)
        else:
            main(cli.model)
    elif cli.tiny:
        tiny_main()
    else:
        # No flags (or --all) = the full perf picture in one run (VERDICT
        # r3 ask 2): the driver's artifact then carries every headline,
        # not just ResNet. Failures are per-line — one model crashing
        # (e.g. an OOM on a smaller chip) must not blank the whole
        # artifact. Env overrides are ignored here (see main()).
        #   Ordering (r5): BERT-Large FIRST — it is the flagship number,
        # and r4's alphabetical-ish order let the driver timeout cut it
        # (BENCH_r04.json rc=124, parsed=GPT-2). Everything after the
        # first line is gravy if the window closes early.
        import traceback
        results = []

        def emit_summary():
            # Cumulative summary after EVERY workload: the driver records
            # the LAST parsed JSON line, and its window may close mid-run
            # (BENCH_r04 rc=124) — so the artifact's tail must always be
            # a summary of everything completed SO FAR. value/unit mirror
            # the flagship (BERT-Large) row; "results" holds every line.
            flagship = results[0]
            print(json.dumps({
                "metric": "summary — all headlines (flagship: "
                          + flagship["metric"] + ")",
                "value": flagship["value"], "unit": flagship["unit"],
                "vs_baseline": flagship.get("vs_baseline"),
                "mfu": flagship.get("mfu"),
                "results": results,
            }), flush=True)

        # Time budget: the driver kills a run that overstays its window
        # (BENCH_r04 rc=124), and rc=0 with the four core rows beats
        # rc=124 with everything. Core workloads always run; each bonus
        # workload runs only if its rough cost still fits (skips are
        # LOUD — a silent cap would read as "covered everything").
        t_start = time.perf_counter()
        budget = (cli.budget_seconds if cli.budget_seconds is not None
                  else float(os.environ.get("BENCH_TIME_BUDGET", "660")))
        sweep = [
            # (fn, arg, core?, rough cold-cache cost s, micro-step cap)
            # caps keep rounds in the 10-20 s fidelity band (long enough
            # that the tunnel's ~150 ms dispatch is <2%, short enough to
            # fit): bert-large 256 at accum 16 -> 16-update ~16 s
            # rounds; bert 128 at batch 48 -> 32-update ~17 s rounds
            (transformer_main, "bert-large", True, 160, 256),
            (main, "resnet50", True, 45, None),
            (transformer_main, "bert", True, 140, 128),
            (transformer_main, "gpt2", True, 90, 128),
            (main, "inception", False, 85, None),
            (main, "vgg", False, 95, None),
            (sharded_optimizer_main, "sharded-optimizer", False, 60,
             None),
            (memory_main, "memory", False, 40, None),
            (checkpoint_main, "checkpoint", False, 90, None),
            (control_plane_main, None, False, 150, None),
        ]
        for fn, arg, core, est, cap in sweep:
            elapsed = time.perf_counter() - t_start
            trimmed = False
            if not core and elapsed + est > budget:
                if fn is control_plane_main:
                    # never silently drop the control-plane rows: a
                    # trimmed np=2 fast probe (~40 s) still measures the
                    # protocol's byte/step counters
                    trimmed = True
                    log(f"TRIMMED control-plane: {elapsed:.0f}s elapsed "
                        f"+ ~{est}s would exceed the {budget:.0f}s "
                        f"budget (--budget-seconds/BENCH_TIME_BUDGET); "
                        f"running the np=2 fast probe instead — run "
                        f"`python bench.py --control-plane` for the "
                        f"full protocol")
                elif fn is sharded_optimizer_main:
                    trimmed = True
                    log(f"TRIMMED sharded-optimizer: over the "
                        f"{budget:.0f}s budget; running --tiny probe — "
                        f"run `python bench.py --sharded-optimizer` "
                        f"for the real row")
                elif fn is memory_main:
                    trimmed = True
                    log(f"TRIMMED memory: over the {budget:.0f}s budget; "
                        f"running --tiny probe — run "
                        f"`python bench.py --memory` for the real row")
                elif fn is checkpoint_main:
                    trimmed = True
                    log(f"TRIMMED checkpoint: over the {budget:.0f}s "
                        f"budget; running --tiny probe — run "
                        f"`python bench.py --checkpoint` for the real "
                        f"row")
                else:
                    log(f"SKIPPED {arg}: {elapsed:.0f}s elapsed + "
                        f"~{est}s would exceed the {budget:.0f}s budget "
                        f"(--budget-seconds/BENCH_TIME_BUDGET); run "
                        f"`python bench.py --model {arg}` for this row")
                    continue
            try:
                if fn is transformer_main:
                    results.append(fn(arg, allow_env=False,
                                      micro_step_cap=cap))
                elif (fn is sharded_optimizer_main
                        or fn is checkpoint_main or fn is memory_main):
                    results.append(fn(tiny=trimmed))
                elif fn is control_plane_main:
                    results.extend(control_plane_main(
                        fast=True, np_override=2 if trimmed else None))
                else:
                    results.append(fn(arg, allow_env=False))
            except Exception:
                traceback.print_exc(file=sys.stderr)
            if results:
                emit_summary()
        if not results:
            # every headline failed: the artifact is empty — a driver/CI
            # must see a failure, not a green run with no JSON lines
            sys.exit(1)
