#!/usr/bin/env python
"""Synthetic ResNet-50 training benchmark — the headline perf harness.

TPU-native port of the reference's measurement harness (reference:
examples/pytorch_synthetic_benchmark.py:37-110,
examples/tensorflow2_synthetic_benchmark.py:72-132): ResNet-50 forward +
backward + optimizer update on synthetic ImageNet-shaped data. Each timed
round is ONE compiled program running BENCH_BATCHES_PER_ROUND (default 20)
train steps via lax.scan — host dispatch latency is excluded, which is the
XLA-native reading of the reference's multi-batch rounds. Warmup runs
ceil(BENCH_WARMUP / BENCH_BATCHES_PER_ROUND) rounds first; reports
images/sec over BENCH_ROUNDS rounds.

Baseline for ``vs_baseline``: the reference's only published absolute
number — 1656.82 images/sec on 16 GPUs (ResNet-101, batch 64, 4xP100
servers; reference: docs/benchmarks.rst:32-43) = 103.55 images/sec/GPU.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import horovod_tpu as hvd
from horovod_tpu.models.resnet import ResNet50
from horovod_tpu import training

REFERENCE_IMAGES_PER_SEC_PER_CHIP = 1656.82 / 16  # docs/benchmarks.rst:32-43

BATCH_PER_CHIP = int(os.environ.get("BENCH_BATCH", "128"))
IMAGE_SIZE = int(os.environ.get("BENCH_IMAGE_SIZE", "224"))
WARMUP_ITERS = int(os.environ.get("BENCH_WARMUP", "20"))
TIMED_ROUNDS = int(os.environ.get("BENCH_ROUNDS", "10"))
BATCHES_PER_ROUND = int(os.environ.get("BENCH_BATCHES_PER_ROUND", "20"))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    hvd.init()
    n_chips = hvd.size()
    global_batch = BATCH_PER_CHIP * n_chips
    log(f"devices: {jax.devices()}  global_batch={global_batch}")

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    optimizer = hvd.DistributedOptimizer(
        optax.sgd(0.01 * n_chips, momentum=0.9))

    state = training.create_train_state(
        model, optimizer, (1, IMAGE_SIZE, IMAGE_SIZE, 3))
    # One compiled program per round (lax.scan over the batches) so host
    # dispatch latency stays out of the steady-state measurement.
    round_fn, batch_sharding = training.make_train_round(
        model, optimizer, steps=BATCHES_PER_ROUND)

    rng = np.random.RandomState(0)
    images = jax.device_put(
        rng.uniform(-1, 1, (global_batch, IMAGE_SIZE, IMAGE_SIZE, 3)).astype(np.float32),
        batch_sharding)
    labels = jax.device_put(
        rng.randint(0, 1000, (global_batch,)).astype(np.int32),
        batch_sharding)

    params, stats, opt_state = state.params, state.batch_stats, state.opt_state

    log("compiling + warmup...")
    t0 = time.perf_counter()
    warmup_rounds = max(1, -(-WARMUP_ITERS // BATCHES_PER_ROUND))
    for _ in range(warmup_rounds):
        loss, params, stats, opt_state = round_fn(params, stats, opt_state,
                                                  images, labels)
    jax.block_until_ready(loss)
    log(f"warmup done in {time.perf_counter() - t0:.1f}s "
        f"(loss={float(loss):.3f})")

    rates = []
    for r in range(TIMED_ROUNDS):
        t0 = time.perf_counter()
        loss, params, stats, opt_state = round_fn(params, stats, opt_state,
                                                  images, labels)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        rates.append(global_batch * BATCHES_PER_ROUND / dt)
        log(f"round {r}: {rates[-1]:.1f} img/s")

    imgs_per_sec = float(np.mean(rates))
    per_chip = imgs_per_sec / n_chips
    result = {
        "metric": "images/sec/chip (ResNet-50 synthetic, bf16, "
                  f"batch {BATCH_PER_CHIP}/chip)",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / REFERENCE_IMAGES_PER_SEC_PER_CHIP, 3),
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
