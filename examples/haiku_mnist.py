"""MNIST with dm-haiku — the framework-agnostic JAX surface.

The reference binds each framework separately (TF/torch/MXNet/Keras);
here the primary surface is JAX itself, so any JAX model library works
unmodified. This example drives a haiku ``transform`` through the same
canonical pattern as every other example (reference: SURVEY.md §2.8):
init → scale LR by size → wrap the optimizer → broadcast initial
parameters → shard the batch → train.

Run single-host:     python examples/haiku_mnist.py
Run under tpurun:    tpurun -np 4 python examples/haiku_mnist.py
"""

import argparse

import haiku as hk
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd


def net_fn(images):
    x = images.reshape((images.shape[0], -1))
    return hk.Sequential([
        hk.Linear(256), jax.nn.relu,
        hk.Linear(128), jax.nn.relu,
        hk.Linear(10),
    ])(x)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=64,
                        help="per-worker batch size")
    parser.add_argument("--lr", type=float, default=0.001)
    args = parser.parse_args()

    hvd.init()
    net = hk.without_apply_rng(hk.transform(net_fn))
    opt = hvd.DistributedOptimizer(optax.adam(args.lr * hvd.size()))

    rng = np.random.RandomState(1234)
    images = rng.rand(2048, 28, 28, 1).astype(np.float32)
    labels = rng.randint(0, 10, (2048,)).astype(np.int32)

    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = opt.init(params)

    mesh = hvd.mesh()
    batch_sharding = NamedSharding(mesh, P(hvd.GLOBAL_AXES))
    repl = NamedSharding(mesh, P())

    def train_step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = net.apply(p, xb)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    step = jax.jit(train_step,
                   in_shardings=(repl, repl, batch_sharding, batch_sharding),
                   out_shardings=(repl, repl, repl),
                   donate_argnums=(0, 1))

    global_batch = args.batch_size * hvd.size()
    sampler = hvd.data.ShardedSampler(len(images), 1, 0, seed=0)
    for epoch in range(args.epochs):
        sampler.set_epoch(epoch)
        idx = np.asarray(list(sampler))
        losses = []

        def batches():
            for i in range(0, len(idx) - global_batch + 1, global_batch):
                take = idx[i:i + global_batch]
                yield images[take], labels[take]

        for xb, yb in hvd.data.prefetch_to_device(
                batches(), size=2, sharding=batch_sharding):
            loss, params, opt_state = step(params, opt_state, xb, yb)
            losses.append(float(loss))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {np.mean(losses):.4f}")


if __name__ == "__main__":
    main()
