"""BERT masked-LM pretraining (BASELINE config #5's model shape).

Dense-gradient BERT MLM: the tied-embedding transformer differentiates
through both the lookup and the output projection, so the table gradient
is inherently dense here and rides the ordinary allreduce. For the
*sparse* allgather embedding-gradient path the reference's IndexedSlices
machinery maps to (reference: horovod/tensorflow/__init__.py:64-75), see
``examples/jax_sparse_embedding.py`` — that workload uses an untied table
through ``hvd.with_sparse_embedding_grad``.

    python examples/jax_bert_mlm.py --model base --seq 128
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.transformer import (
    BertBase, BertLarge, masked_lm_loss, random_tokens)

VOCAB = 30522
MASK_ID = 103  # [MASK]


def mask_batch(rng, tokens, rate=0.15):
    mask = rng.rand(*tokens.shape) < rate
    inputs = np.where(mask, MASK_ID, tokens)
    return inputs.astype(np.int32), mask.astype(np.int32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="base",
                        choices=["tiny", "base", "large"],
                        help="'tiny' is a 2-layer smoke config for "
                             "CPU-mesh development runs")
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=8,
                        help="per-chip batch size")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--lr", type=float, default=1e-4)
    args = parser.parse_args()

    hvd.init()
    if args.model == "tiny":
        from functools import partial

        from horovod_tpu.models.transformer import Transformer

        cls = partial(Transformer, d_model=64, num_layers=2, num_heads=4,
                      d_ff=128, causal=False)
    else:
        cls = BertBase if args.model == "base" else BertLarge
    model = cls(vocab_size=VOCAB, max_seq=args.seq)

    opt = hvd.DistributedOptimizer(optax.adamw(args.lr * hvd.size()))
    tokens0 = jnp.zeros((1, args.seq), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens0, train=False)
    params = hvd.broadcast_parameters(variables["params"])
    opt_state = opt.init(params)

    mesh = hvd.mesh()
    sharding = NamedSharding(mesh, P(hvd.GLOBAL_AXES))
    repl = NamedSharding(mesh, P())

    def loss_fn(params, inputs, labels, mask):
        logits = model.apply({"params": params}, inputs, train=True)
        return masked_lm_loss(logits, labels, mask)

    @jax.jit
    def step(params, opt_state, inputs, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, inputs, labels,
                                                  mask)
        updates, opt_state = opt.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    rng = np.random.RandomState(0)
    global_batch = args.batch_size * hvd.size()
    t0 = time.time()
    for i in range(args.steps):
        labels = random_tokens(np.random.default_rng(i), global_batch,
                               args.seq, VOCAB)
        inputs, mask = mask_batch(rng, labels)
        loss, params, opt_state = step(
            params, opt_state,
            jax.device_put(inputs, sharding),
            jax.device_put(labels.astype(np.int32), sharding),
            jax.device_put(mask, sharding))
        if hvd.rank() == 0:
            print(f"step {i}: mlm loss {float(loss):.4f}")
    if hvd.rank() == 0:
        dt = time.time() - t0
        rate = global_batch * args.seq * args.steps / dt
        print(f"{rate:.0f} tokens/sec total")


if __name__ == "__main__":
    main()
