"""BERT masked-LM pretraining (BASELINE config #5's model shape).

Dense-gradient BERT MLM: the tied-embedding transformer differentiates
through both the lookup and the output projection, so the table gradient
is inherently dense here and rides the ordinary allreduce. For the
*sparse* allgather embedding-gradient path the reference's IndexedSlices
machinery maps to (reference: horovod/tensorflow/__init__.py:64-75), see
``examples/jax_sparse_embedding.py`` — that workload uses an untied table
through ``hvd.with_sparse_embedding_grad``.

    python examples/jax_bert_mlm.py --model base --seq 128

``--gathered --accum 8`` is the round-4 headline recipe
(docs/perf_experiments.md): the MLM head projects only the masked
positions (the (batch, seq, vocab) f32 logits tensor never exists) and
micro-batches accumulate at the activation sweet spot so the
batch-independent adamw pass amortizes — +10.8% tokens/s on BERT-Large
at the bench shapes.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.transformer import (
    BertBase, BertLarge, masked_lm_loss, masked_lm_loss_gathered,
    random_tokens, sample_masked_positions)

VOCAB = 30522
MASK_ID = 103  # [MASK]


def mask_batch(rng, tokens, rate=0.15):
    mask = rng.rand(*tokens.shape) < rate
    inputs = np.where(mask, MASK_ID, tokens)
    return inputs.astype(np.int32), mask.astype(np.int32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="base",
                        choices=["tiny", "base", "large"],
                        help="'tiny' is a 2-layer smoke config for "
                             "CPU-mesh development runs")
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=8,
                        help="per-chip batch size")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--lr", type=float, default=1e-4)
    parser.add_argument("--gathered", action="store_true",
                        help="project only the masked positions through "
                             "the tied vocab matrix (r4 headline path)")
    parser.add_argument("--accum", type=int, default=1,
                        help="micro-batches accumulated per optimizer "
                             "update (effective batch = accum x "
                             "batch-size)")
    args = parser.parse_args()

    hvd.init()
    if args.model == "tiny":
        from functools import partial

        from horovod_tpu.models.transformer import Transformer

        cls = partial(Transformer, d_model=64, num_layers=2, num_heads=4,
                      d_ff=128, causal=False)
    else:
        cls = BertBase if args.model == "base" else BertLarge
    model = cls(vocab_size=VOCAB, max_seq=args.seq)

    opt = hvd.DistributedOptimizer(optax.adamw(args.lr * hvd.size()))
    tokens0 = jnp.zeros((1, args.seq), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens0, train=False)
    params = hvd.broadcast_parameters(variables["params"])
    opt_state = opt.init(params)

    mesh = hvd.mesh()
    # leading accum axis replicated, rows data-parallel
    micro_sharding = NamedSharding(mesh, P(None, hvd.GLOBAL_AXES))

    n_pred = max(1, round(0.15 * args.seq))

    if args.gathered:
        def loss_fn(params, inputs, positions, lab_g):
            hidden = model.apply({"params": params}, inputs, train=True,
                                 output="hidden")
            emb = params["token_embed"]["embedding"]
            return masked_lm_loss_gathered(hidden, emb, positions, lab_g)
    else:
        def loss_fn(params, inputs, labels, mask):
            logits = model.apply({"params": params}, inputs, train=True)
            return masked_lm_loss(logits, labels, mask)

    @jax.jit
    def step(params, opt_state, data):
        # micro-batches scan over the leading accum axis (the r4
        # headline accumulation recipe). With --gathered the mean grad
        # EXACTLY equals one accum*batch step (fixed n_pred masked
        # positions per row); the random-mask path is a mean-of-means
        # (each micro normalizes by its own mask count), the usual
        # approximation when examples per micro-batch vary.
        def micro(g_sum, mb):
            loss, g = jax.value_and_grad(loss_fn)(params, *mb)
            return jax.tree_util.tree_map(jnp.add, g_sum, g), loss

        g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
        grads, losses = jax.lax.scan(micro, g0, data)
        grads = jax.tree_util.tree_map(lambda a: a / args.accum, grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return losses.mean(), optax.apply_updates(params, updates), \
            opt_state

    rng = np.random.RandomState(0)
    global_batch = args.batch_size * hvd.size()
    rows = global_batch * args.accum

    def shard(a):
        a = a.reshape((args.accum, global_batch) + a.shape[1:])
        return jax.device_put(a, micro_sharding)

    t0 = time.time()
    for i in range(args.steps):
        if i == 1:
            # step 0 pays the jit compile (tens of seconds with the accum
            # scan); restart the clock so short runs report steady-state
            t0 = time.time()
        labels = random_tokens(np.random.default_rng(i), rows,
                               args.seq, VOCAB)
        if args.gathered:
            positions = sample_masked_positions(
                np.random.default_rng(1000 + i), rows, args.seq, n_pred)
            lab_g = np.take_along_axis(labels, positions, axis=1)
            mask = np.zeros_like(labels, np.int32)
            np.put_along_axis(mask, positions, 1, axis=1)
            inputs = np.where(mask, MASK_ID, labels).astype(np.int32)
            data = (shard(inputs), shard(positions), shard(lab_g))
        else:
            inputs, mask = mask_batch(rng, labels)
            data = (shard(inputs), shard(labels.astype(np.int32)),
                    shard(mask))

        loss, params, opt_state = step(params, opt_state, data)
        if hvd.rank() == 0:
            print(f"step {i}: mlm loss {float(loss):.4f}")
    if hvd.rank() == 0:
        dt = time.time() - t0
        timed_steps = max(args.steps - 1, 1)  # step 0 = compile warmup
        rate = rows * args.seq * timed_steps / dt
        print(f"{rate:.0f} tokens/sec total")


if __name__ == "__main__":
    main()
