"""ImageNet-style ResNet-50 training with LR warmup + gradient accumulation.

TPU-native analogue of the reference's flagship real-data example
(reference: examples/pytorch_imagenet_resnet50.py): linear learning-rate
warmup scaled by world size, per-epoch schedule, gradient accumulation
(``backward_passes_per_step``), bf16 wire compression, rank-0 checkpointing
with resume-epoch broadcast. Data here is synthetic unless a data loader is
plugged in (zero-egress environments).
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import callbacks, checkpoint, training
from horovod_tpu.models.resnet import ResNet50


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--base-lr", type=float, default=0.0125,
                        help="per-worker base lr (scaled by world size)")
    parser.add_argument("--warmup-epochs", type=float, default=5.0)
    parser.add_argument("--batches-per-allreduce", type=int, default=1)
    parser.add_argument("--fp16-allreduce", action="store_true")
    parser.add_argument("--ckpt-dir", default="./checkpoints-resnet50")
    parser.add_argument("--steps-per-epoch", type=int, default=8)
    args = parser.parse_args()

    hvd.init()

    # LR schedule: warmup from base_lr to base_lr*size over warmup_epochs,
    # then the standard /10 step decay at epochs 30/60/80 (reference:
    # examples/pytorch_imagenet_resnet50.py adjust_learning_rate).
    def decay(epoch):
        return jnp.where(epoch < 30, 1.0,
                         jnp.where(epoch < 60, 0.1,
                                   jnp.where(epoch < 80, 0.01, 0.001)))

    schedule = callbacks.warmup_scaled_schedule(
        base_lr=args.base_lr, warmup_epochs=args.warmup_epochs,
        steps_per_epoch=args.steps_per_epoch, after=decay)

    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    opt = hvd.DistributedOptimizer(
        optax.sgd(schedule, momentum=0.9),
        compression=compression,
        backward_passes_per_step=args.batches_per_allreduce)

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    state = training.create_train_state(model, opt, (1, 224, 224, 3))
    tree = {"params": state.params, "batch_stats": state.batch_stats,
            "opt_state": state.opt_state}
    tree, resume = checkpoint.restore_latest(args.ckpt_dir, tree)
    start_epoch = (resume + 1) if resume is not None else 0

    step, sharding = training.make_train_step(model, opt)
    global_batch = args.batch_size * hvd.size()
    rng = np.random.RandomState(0)
    params, stats, opt_state = (tree["params"], tree["batch_stats"],
                                tree["opt_state"])

    def synthetic_batches(n):
        for _ in range(n):
            yield (rng.rand(global_batch, 224, 224, 3).astype(np.float32),
                   rng.randint(0, 1000, (global_batch,)).astype(np.int32))

    for epoch in range(start_epoch, args.epochs):
        losses = []
        # host batches stream to HBM a couple of steps ahead (the loader-
        # worker overlap the reference gets from framework data loaders)
        for images, labels in hvd.data.prefetch_to_device(
                synthetic_batches(args.steps_per_epoch), size=2,
                sharding=sharding):
            loss, params, stats, opt_state = step(
                params, stats, opt_state, images, labels)
            losses.append(float(loss))
        metrics = callbacks.average_metrics({"loss": np.mean(losses)})
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {metrics['loss']:.4f}")
        checkpoint.save(
            args.ckpt_dir,
            {"params": params, "batch_stats": stats, "opt_state": opt_state},
            step=epoch, keep=3)


if __name__ == "__main__":
    main()
