"""Long-context training with ring / Ulysses sequence parallelism.

TPU-first extension workload (the reference has no sequence parallelism —
SURVEY.md §5.7): a causal transformer whose attention runs over a sequence
sharded across the mesh, via ring attention (ppermute rotation) or Ulysses
(all-to-all head exchange), composed with data parallelism on the cross
axis.

    python examples/jax_long_context.py --strategy ring --seq 4096
"""

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.transformer import Transformer, causal_lm_loss

VOCAB = 32000


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--strategy", default="ring",
                        choices=["ring", "ulysses"])
    parser.add_argument("--seq", type=int, default=4096,
                        help="global sequence length (sharded over 'local')")
    parser.add_argument("--batch-size", type=int, default=1)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--steps", type=int, default=5)
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.mesh()
    sp_axis = hvd.LOCAL_AXIS  # sequence over ICI; cross axis stays DP
    n_sp = mesh.shape[sp_axis]

    # per-device attention closure injected into the model
    if args.strategy == "ring":
        def attn(q, k, v, causal):
            return hvd.ring_attention(q, k, v, sp_axis, causal)
    else:
        def attn(q, k, v, causal):
            return hvd.ulysses_attention(q, k, v, sp_axis, causal=causal)

    model = Transformer(
        vocab_size=VOCAB, d_model=args.d_model, num_layers=args.layers,
        num_heads=args.heads, d_ff=4 * args.d_model, max_seq=args.seq,
        causal=True, attention_fn=attn)

    # Gradients are averaged over BOTH axes: the loss is a mean over the
    # full (batch, sequence) grid, so each device's contribution weights
    # equally (sequence shards behave like extra data shards here).
    opt = hvd.DistributedOptimizer(optax.adamw(1e-4))

    def init_fn(tokens):
        return model.init(jax.random.PRNGKey(0), tokens, train=False)["params"]

    tokens_sh = NamedSharding(mesh, P(hvd.CROSS_AXIS, hvd.LOCAL_AXIS))
    repl = NamedSharding(mesh, P())

    init_sm = jax.jit(jax.shard_map(
        init_fn, mesh=mesh,
        in_specs=P(hvd.CROSS_AXIS, hvd.LOCAL_AXIS),
        out_specs=P(), check_vma=False),
        out_shardings=repl)
    global_tokens = np.zeros(
        (args.batch_size * mesh.shape[hvd.CROSS_AXIS], args.seq), np.int32)
    params = init_sm(jax.device_put(global_tokens, tokens_sh))
    opt_state = jax.jit(opt.init, out_shardings=repl)(params)

    def per_device(params, opt_state, tokens):
        # global position of this device's sequence shard: pos embeddings
        # and the ring's causal mask both work on global positions.
        off = jax.lax.axis_index(sp_axis) * tokens.shape[1]

        def loss_of(p):
            logits = model.apply({"params": p}, tokens, train=True,
                                 pos_offset=off)
            # next-token loss within the local shard (the one cross-shard
            # boundary pair per device is skipped)
            return causal_lm_loss(logits, tokens)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    step = jax.jit(jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P(), P(hvd.CROSS_AXIS, hvd.LOCAL_AXIS)),
        out_specs=(P(), P(), P()), check_vma=False),
        donate_argnums=(0, 1))

    rng = np.random.RandomState(0)
    for i in range(args.steps):
        tokens = jax.device_put(
            rng.randint(0, VOCAB, global_tokens.shape).astype(np.int32),
            tokens_sh)
        t0 = time.time()
        loss, params, opt_state = step(params, opt_state, tokens)
        jax.block_until_ready(loss)
        if hvd.rank() == 0:
            print(f"step {i}: loss {float(loss):.4f} "
                  f"({time.time() - t0:.2f}s, seq {args.seq} over "
                  f"{n_sp} devices, {args.strategy})")


if __name__ == "__main__":
    main()
