"""MNIST training — the canonical usage pattern.

TPU-native analogue of the reference's MNIST examples (reference:
examples/pytorch_mnist.py, examples/tensorflow2_mnist.py): init → scale the
learning rate by world size → wrap the optimizer → broadcast initial state
from rank 0 → train → rank-0-only checkpointing.

Run single-host:     python examples/jax_mnist.py
Run under tpurun:    tpurun -np 4 python examples/jax_mnist.py
Synthetic data is used when no dataset is available (zero-egress CI).
"""

import argparse
import os

import jax
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import checkpoint, training
from horovod_tpu.models.mnist import MnistConvNet


def load_data(n=2048):
    """MNIST if torchvision has it cached, else synthetic digits."""
    try:
        from torchvision import datasets  # noqa: F401

        raise ImportError  # zero-egress: skip download path entirely
    except ImportError:
        rng = np.random.RandomState(1234)
        images = rng.rand(n, 28, 28, 1).astype(np.float32)
        labels = rng.randint(0, 10, (n,)).astype(np.int32)
        return images, labels


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64,
                        help="per-worker batch size")
    parser.add_argument("--lr", type=float, default=0.001)
    parser.add_argument("--ckpt-dir", default="./checkpoints-mnist")
    args = parser.parse_args()

    # 1. initialize the framework (mesh over all local/global devices)
    hvd.init()

    # 2. scale the learning rate by the number of workers
    opt = hvd.DistributedOptimizer(optax.adam(args.lr * hvd.size()))

    # 3. build model + state; create_train_state broadcasts from rank 0
    model = MnistConvNet()
    state = training.create_train_state(model, opt, (1, 28, 28, 1))

    # 4. resume from the latest checkpoint if one exists (rank-0 wrote it;
    #    restore broadcasts so all workers agree)
    tree = {"params": state.params, "batch_stats": state.batch_stats,
            "opt_state": state.opt_state}
    tree, resume_epoch = checkpoint.restore_latest(args.ckpt_dir, tree)
    start_epoch = (resume_epoch + 1) if resume_epoch is not None else 0

    step, batch_sharding = training.make_train_step(model, opt)
    images, labels = load_data()
    global_batch = args.batch_size * hvd.size()
    params, stats, opt_state = (tree["params"], tree["batch_stats"],
                                tree["opt_state"])

    for epoch in range(start_epoch, args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(images))
        losses = []
        for i in range(0, len(images) - global_batch + 1, global_batch):
            idx = perm[i:i + global_batch]
            xb = jax.device_put(images[idx], batch_sharding)
            yb = jax.device_put(labels[idx], batch_sharding)
            loss, params, stats, opt_state = step(params, stats, opt_state,
                                                  xb, yb)
            losses.append(float(loss))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {np.mean(losses):.4f}")
            # 5. rank-0-only checkpointing
        checkpoint.save(args.ckpt_dir,
                        {"params": params, "batch_stats": stats,
                         "opt_state": opt_state},
                        step=epoch, keep=3)


if __name__ == "__main__":
    main()
