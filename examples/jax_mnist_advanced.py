"""MNIST with the callback suite: broadcast, metric averaging, LR warmup.

Analogue of the reference's advanced Keras example (reference:
examples/keras_mnist_advanced.py): BroadcastGlobalVariablesCallback,
MetricAverageCallback and LearningRateWarmupCallback orchestrated around an
explicit training loop.
"""

import argparse

import jax
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import callbacks, training
from horovod_tpu.models.mnist import MnistConvNet


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--base-lr", type=float, default=0.001)
    parser.add_argument("--warmup-epochs", type=float, default=1.0)
    args = parser.parse_args()

    hvd.init()

    rng = np.random.RandomState(1234)
    images = rng.rand(1024, 28, 28, 1).astype(np.float32)
    labels = rng.randint(0, 10, (1024,)).astype(np.int32)
    global_batch = args.batch_size * hvd.size()
    steps_per_epoch = len(images) // global_batch

    # in-jit LR schedule version of LearningRateWarmupCallback
    schedule = callbacks.warmup_scaled_schedule(
        base_lr=args.base_lr, warmup_epochs=args.warmup_epochs,
        steps_per_epoch=steps_per_epoch)
    opt = hvd.DistributedOptimizer(optax.adam(schedule))

    model = MnistConvNet()
    state = training.create_train_state(model, opt, (1, 28, 28, 1))
    step, sharding = training.make_train_step(model, opt)

    cbs = [
        callbacks.BroadcastGlobalVariablesCallback(root_rank=0),
        callbacks.MetricAverageCallback(),
    ]
    train_state = {"params": state.params, "batch_stats": state.batch_stats,
                   "opt_state": state.opt_state}
    for cb in cbs:
        train_state = cb.on_train_begin(train_state)
    params, stats, opt_state = (train_state["params"],
                                train_state["batch_stats"],
                                train_state["opt_state"])

    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(images))
        losses = []
        for cb in cbs:
            train_state = cb.on_epoch_begin(epoch, train_state)
        for i in range(steps_per_epoch):
            idx = perm[i * global_batch:(i + 1) * global_batch]
            xb = jax.device_put(images[idx], sharding)
            yb = jax.device_put(labels[idx], sharding)
            loss, params, stats, opt_state = step(params, stats, opt_state,
                                                  xb, yb)
            losses.append(float(loss))
        metrics = {"loss": float(np.mean(losses))}
        for cb in cbs:
            train_state, metrics = cb.on_epoch_end(epoch, train_state,
                                                   metrics)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(metrics['loss']):.4f} "
                  f"(lr {float(schedule(epoch * steps_per_epoch)):.5f})")


if __name__ == "__main__":
    main()
