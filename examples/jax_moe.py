"""Mixture-of-experts training with expert parallelism.

TPU-first extension workload: a Switch-style MoE block whose experts live
one-per-device on the mesh's local axis, trained end to end with the
load-balance auxiliary loss — token routing rides two all_to_alls over
ICI per step (see docs/expert_parallelism.md).

    python examples/jax_moe.py --steps 50
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--tokens-per-device", type=int, default=128)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument("--aux-weight", type=float, default=0.01)
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.mesh()
    n_exp = mesh.shape[hvd.LOCAL_AXIS]
    d = args.d_model
    capacity = hvd.default_capacity(args.tokens_per_device, n_exp)

    rng = np.random.RandomState(0)
    params = {
        "experts": hvd.stack_stage_params([
            {"wi": jnp.asarray(rng.randn(d, 4 * d).astype(np.float32)
                               * 0.1),
             "wo": jnp.asarray(rng.randn(4 * d, d).astype(np.float32)
                               * 0.1)}
            for _ in range(n_exp)]),
        "gate": jnp.asarray(rng.randn(d, n_exp).astype(np.float32) * 0.1),
    }

    def expert_fn(p, h):
        return jax.nn.gelu(h @ p["wi"]) @ p["wo"]

    def loss_fn(params, x, target):
        def inner(experts, gate, x, target):
            y, probs = hvd.switch_moe(x, x @ gate, expert_fn, experts,
                                      hvd.LOCAL_AXIS, capacity)
            mse = jnp.mean((y - target) ** 2)
            aux = hvd.load_balance_loss(probs, axis_name=hvd.LOCAL_AXIS)
            return (jax.lax.pmean(mse, hvd.LOCAL_AXIS)
                    + args.aux_weight * aux)

        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(hvd.LOCAL_AXIS), P(), P(hvd.LOCAL_AXIS),
                      P(hvd.LOCAL_AXIS)),
            out_specs=P(), check_vma=False)(
            params["experts"], params["gate"], x, target)

    opt = optax.adam(args.lr)
    state = opt.init(params)

    @jax.jit
    def step(params, state, x, target):
        loss, g = jax.value_and_grad(loss_fn)(params, x, target)
        updates, state = opt.update(g, state, params)
        return loss, optax.apply_updates(params, updates), state

    total_tokens = n_exp * args.tokens_per_device
    x = jnp.asarray(rng.randn(total_tokens, d).astype(np.float32))
    target = jnp.asarray(np.tanh(rng.randn(total_tokens, d))
                         .astype(np.float32))
    for i in range(args.steps):
        loss, params, state = step(params, state, x, target)
        if hvd.rank() == 0 and i % 10 == 0:
            print(f"step {i}: loss {float(loss):.4f} "
                  f"({n_exp} experts, capacity {capacity})")
    if hvd.rank() == 0:
        print(f"final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
