"""Sparse embedding-gradient training — the allgather/sparse path.

The acceptance workload for the sparse exchange (reference:
horovod/tensorflow/__init__.py:64-75 — IndexedSlices gradients go
allgather(values)+allgather(indices) instead of densify-then-allreduce):
a large embedding table trained through ``hvd.with_sparse_embedding_grad``
so each step exchanges only the touched rows. ``--sparse-as-dense``
switches to the densify-first path (reference:
tensorflow/__init__.py:200-203) for comparison.

    python examples/jax_sparse_embedding.py --vocab 100000 --steps 30
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--vocab", type=int, default=100_000)
    parser.add_argument("--dim", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=64,
                        help="examples per worker")
    parser.add_argument("--ids-per-example", type=int, default=32)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--sparse-as-dense", action="store_true")
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.mesh()
    rng = np.random.RandomState(0)
    table = jnp.zeros((args.vocab, args.dim), jnp.float32)
    # fixed targets per id so the table can memorize them exactly
    target_table = jnp.asarray(
        rng.rand(args.vocab, args.dim).astype(np.float32))
    opt = hvd.DistributedOptimizer(optax.sgd(args.lr),
                                   sparse_as_dense=args.sparse_as_dense)
    opt_state = opt.init(table)

    def loss(rows, labels):
        # sum (not mean): each touched row's gradient is 2*(row - target)
        # per occurrence, independent of the batch element count — rows
        # move at a constant rate no matter how large the batch is
        return jnp.sum((rows - labels) ** 2)

    def per_device(table, opt_state, ids, labels):
        l, sg = hvd.with_sparse_embedding_grad(loss)(table, ids, labels)
        # sg is a SparseGrad: only the touched rows cross the wire
        updates, opt_state = opt.update(sg, opt_state, table)
        return l, optax.apply_updates(table, updates), opt_state

    step = jax.jit(jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P(), P(hvd.GLOBAL_AXES), P(hvd.GLOBAL_AXES)),
        out_specs=(P(), P(), P()), check_vma=False))

    global_batch = args.batch_size * hvd.size()
    nnz = args.batch_size * args.ids_per_example
    if hvd.rank() == 0:
        mode = "sparse_as_dense" if args.sparse_as_dense else "allgather"
        print(f"table {args.vocab}x{args.dim} "
              f"({args.vocab * args.dim * 4 / 2**20:.0f} MB); "
              f"{nnz} touched rows/worker/step "
              f"({nnz * args.dim * 4 / 2**20:.1f} MB on the wire, "
              f"{mode} path)")
    t0 = time.time()
    for i in range(args.steps):
        ids = jax.device_put(
            rng.randint(0, args.vocab,
                        (global_batch, args.ids_per_example))
            .astype(np.int32))
        labels = target_table[ids]
        l, table, opt_state = step(table, opt_state, ids, labels)
        per_elem = float(l) / (args.batch_size * args.ids_per_example
                               * args.dim)
        if hvd.rank() == 0 and i % 10 == 0:
            print(f"step {i}: loss/elem {per_elem:.5f}")
    if hvd.rank() == 0:
        print(f"final loss/elem {per_elem:.5f} "
              f"({(time.time() - t0) / args.steps * 1e3:.0f} ms/step)")


if __name__ == "__main__":
    main()
