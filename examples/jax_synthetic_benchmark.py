"""Synthetic ResNet benchmark — the measurement harness.

TPU-native analogue of the reference's synthetic benchmarks (reference:
examples/pytorch_synthetic_benchmark.py:37-110,
examples/tensorflow2_synthetic_benchmark.py:72-132): ResNet fwd+bwd+update
on synthetic ImageNet-shaped data, 10 warmup batches, then num-iters rounds
of num-batches-per-iter batches; reports images/sec and images/sec/chip.

    python examples/jax_synthetic_benchmark.py --model ResNet50 --batch-size 128
"""

import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import models, training


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="ResNet50",
                        choices=["ResNet18", "ResNet34", "ResNet50",
                                 "ResNet101", "ResNet152",
                                 "VGG16", "InceptionV3"])
    parser.add_argument("--batch-size", type=int, default=128,
                        help="per-chip batch size")
    parser.add_argument("--num-warmup-batches", type=int, default=10)
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--fp16-allreduce", action="store_true",
                        help="bf16 wire compression for gradient exchange")
    parser.add_argument("--image-size", type=int, default=None,
                        help="override input resolution (CI smoke runs)")
    parser.add_argument("--json", action="store_true",
                        help="rank 0 prints one JSON line with "
                             "imgs_per_sec / n_chips / scaling_efficiency "
                             "(the reference's headline metric, "
                             "docs/benchmarks.rst:16-64)")
    parser.add_argument("--one-chip-rate", type=float,
                        default=float(os.environ.get(
                            "BENCH_ONE_CHIP_IMGS_PER_SEC", "0")) or None,
                        help="stored 1-chip imgs/sec (run once with -np 1) "
                             "for the scaling_efficiency denominator; also "
                             "via BENCH_ONE_CHIP_IMGS_PER_SEC")
    parser.add_argument("--platform", default=None,
                        help="force a jax platform (e.g. 'cpu' for "
                             "virtual-device CI runs; overrides site "
                             "config, must run before first device use)")
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    hvd.init()
    model = getattr(models, args.model)(num_classes=1000,
                                        dtype=jnp.bfloat16)
    image_size = args.image_size or (
        299 if args.model == "InceptionV3" else 224)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    opt = hvd.DistributedOptimizer(
        optax.sgd(0.01 * hvd.size(), momentum=0.9), compression=compression)

    state = training.create_train_state(
        model, opt, (1, image_size, image_size, 3))
    step, batch_sharding = training.make_train_step(model, opt)

    global_batch = args.batch_size * hvd.size()
    rng = np.random.RandomState(0)
    images = jax.device_put(
        rng.rand(global_batch, image_size, image_size, 3).astype(np.float32),
        batch_sharding)
    labels = jax.device_put(
        rng.randint(0, 1000, (global_batch,)).astype(np.int32),
        batch_sharding)

    params, stats, opt_state = (state.params, state.batch_stats,
                                state.opt_state)

    def run_batch():
        nonlocal params, stats, opt_state
        loss, params, stats, opt_state = step(params, stats, opt_state,
                                              images, labels)
        return loss

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch size {args.batch_size}/chip, "
              f"{hvd.size()} chips")
    loss = run_batch()  # compile
    for _ in range(args.num_warmup_batches):
        loss = run_batch()
    float(loss)  # host sync — block_until_ready alone can be a no-op on
    # remote-dispatch platforms

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.time()
        for _ in range(max(args.num_batches_per_iter, 1)):
            loss = run_batch()
        float(loss)
        dt = time.time() - t0
        rate = global_batch * args.num_batches_per_iter / dt
        img_secs.append(rate)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate:.1f} img/sec total")

    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec total: {mean:.1f} +- {conf:.1f}")
        print(f"Img/sec per chip: {mean / hvd.size():.1f}")
        if args.json:
            import json

            n = hvd.size()
            efficiency = (round(mean / (n * args.one_chip_rate), 4)
                          if args.one_chip_rate else None)
            print(json.dumps({
                "imgs_per_sec": round(float(mean), 1),
                "n_chips": n,
                "scaling_efficiency": efficiency,
            }), flush=True)


if __name__ == "__main__":
    main()
