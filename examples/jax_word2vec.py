"""Skip-gram word2vec with negative sampling — embedding-heavy workload.

Maps the reference's word2vec example (reference:
examples/tensorflow_word2vec.py: skip-gram pairs from a sliding window,
NCE-style sampled loss, LR scaled by size, DistributedOptimizer, rank-0
reporting) onto the TPU-native stack. The text8 download is replaced by a
self-contained Zipf-distributed synthetic corpus with planted co-occurrence
structure (words 2k and 2k+1 co-occur), so the embeddings have something
learnable and the script runs with zero egress.

Both embedding tables produce :class:`hvd.SparseGrad` gradients — each step
exchanges only the touched rows via allgather (reference:
horovod/tensorflow/__init__.py:64-75), which is the whole point of the
word2vec workload for a data-parallel framework: V×d allreduce would dwarf
the compute.

    python examples/jax_word2vec.py --vocab 5000 --steps 800
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel.sparse import SparseGrad


def synth_corpus(rng, vocab, length):
    """Zipf-ish token stream emitted in (2k, 2k+1) pairs, planting
    co-occurrence structure that skip-gram can learn: each draw k puts
    word 2k and its partner 2k+1 adjacent."""
    base = rng.zipf(1.3, size=length // 2) % (vocab // 2)
    stream = np.empty(2 * len(base), np.int32)
    stream[0::2] = 2 * base
    stream[1::2] = 2 * base + 1
    return stream


def skipgram_batches(rng, corpus, batch, window, negatives, vocab, steps):
    # negatives ~ freq^0.75, word2vec's noise distribution — uniform
    # sampling leaves the frequent-word bias uncorrected
    freq = np.bincount(corpus, minlength=vocab).astype(np.float64) ** 0.75
    cdf = np.cumsum(freq / freq.sum())
    for _ in range(steps):
        centers_pos = rng.randint(window, len(corpus) - window, size=batch)
        offsets = rng.randint(1, window + 1, size=batch) * \
            rng.choice([-1, 1], size=batch)
        centers = corpus[centers_pos]
        contexts = corpus[centers_pos + offsets]
        negs = np.searchsorted(
            cdf, rng.rand(batch, negatives)).astype(np.int32)
        yield centers, contexts, negs


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--vocab", type=int, default=5000)
    parser.add_argument("--dim", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=256,
                        help="skip-gram pairs per worker per step")
    parser.add_argument("--window", type=int, default=2)
    parser.add_argument("--negatives", type=int, default=8)
    parser.add_argument("--corpus-tokens", type=int, default=200_000)
    parser.add_argument("--steps", type=int, default=800)
    parser.add_argument("--lr", type=float, default=0.5)
    parser.add_argument("--sparse-as-dense", action="store_true")
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.mesh()
    # reference scales the SGD learning rate by the world size
    # (tensorflow_word2vec.py:178)
    opt = hvd.DistributedOptimizer(optax.sgd(args.lr * hvd.size()),
                                   sparse_as_dense=args.sparse_as_dense)

    rng = np.random.RandomState(1234 + hvd.rank())  # per-worker sampling
    corpus = synth_corpus(np.random.RandomState(7), args.vocab,
                          args.corpus_tokens)

    init_rng = jax.random.PRNGKey(0)  # same everywhere = broadcast-free init
    k1, k2 = jax.random.split(init_rng)
    params = {
        "emb_in": jax.random.uniform(k1, (args.vocab, args.dim),
                                     jnp.float32, -0.5, 0.5) / args.dim,
        "emb_out": jnp.zeros((args.vocab, args.dim), jnp.float32),
    }
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = opt.init(params)

    def rows_loss(c_rows, x_rows, n_rows):
        """Negative-sampling loss on gathered rows (the sampled-softmax
        stand-in for the reference's NCE loss)."""
        pos = jax.nn.log_sigmoid(jnp.sum(c_rows * x_rows, axis=-1))
        neg = jax.nn.log_sigmoid(
            -jnp.einsum("bd,bkd->bk", c_rows, n_rows))
        return -(jnp.sum(pos) + jnp.sum(neg)) / c_rows.shape[0]

    def per_device(params, opt_state, centers, contexts, negs):
        c_rows = jnp.take(params["emb_in"], centers, axis=0)
        x_rows = jnp.take(params["emb_out"], contexts, axis=0)
        n_rows = jnp.take(params["emb_out"], negs.reshape(-1),
                          axis=0).reshape(negs.shape + (args.dim,))
        loss, (gc, gx, gn) = jax.value_and_grad(
            rows_loss, argnums=(0, 1, 2))(c_rows, x_rows, n_rows)
        # both tables' gradients stay sparse: only touched rows cross ICI
        grads = {
            "emb_in": SparseGrad(centers, gc, args.vocab),
            "emb_out": SparseGrad(
                jnp.concatenate([contexts, negs.reshape(-1)]),
                jnp.concatenate([gx, gn.reshape(-1, args.dim)]),
                args.vocab),
        }
        updates, opt_state = opt.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    step_fn = jax.jit(jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P(), P(hvd.GLOBAL_AXES), P(hvd.GLOBAL_AXES),
                  P(hvd.GLOBAL_AXES)),
        out_specs=(P(), P(), P()), check_vma=False))

    world_batch = args.batch_size * hvd.size()
    batches = skipgram_batches(rng, corpus, world_batch, args.window,
                               args.negatives, args.vocab, args.steps)
    t0 = time.time()
    loss = None
    for step, (centers, contexts, negs) in enumerate(batches):
        loss, params, opt_state = step_fn(
            params, opt_state, jnp.asarray(centers), jnp.asarray(contexts),
            jnp.asarray(negs))
        if hvd.rank() == 0 and (step + 1) % 50 == 0:
            print(f"step {step + 1}: loss {float(loss):.4f} "
                  f"({world_batch * (step + 1) / (time.time() - t0):.0f} "
                  f"pairs/sec)")

    if hvd.rank() == 0:
        # planted structure check: the most-predicted context of word 2k
        # should be its planted partner 2k+1 (the reference prints nearest
        # neighbours of sample words, tensorflow_word2vec.py:230-239;
        # skip-gram directly optimizes emb_in·emb_out for co-occurring
        # pairs, so the probe scores emb_in against the context table)
        emb_in = np.asarray(params["emb_in"])
        emb_out = np.asarray(params["emb_out"])
        hits1 = hits5 = 0
        # probe the 20 most frequent planted pairs (rare words see too few
        # updates in a short run to place their partner top-1)
        counts = np.bincount(corpus[corpus % 2 == 0], minlength=args.vocab)
        probes = list(np.argsort(-counts)[:20])
        for w in probes:
            sims = emb_out @ emb_in[w]
            sims[w] = -np.inf
            top5 = np.argsort(-sims)[:5]
            hits1 += int(top5[0] == w + 1)
            hits5 += int(w + 1 in top5)
        print(f"final loss {float(loss):.4f}; planted partner is "
              f"top-1 for {hits1}/{len(probes)} probe words, "
              f"top-5 for {hits5}/{len(probes)}")


if __name__ == "__main__":
    main()
