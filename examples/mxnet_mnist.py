"""MNIST through the MXNet-shaped binding.

Maps the reference's mxnet example (reference: examples/mxnet_mnist.py:
DistributedOptimizer wrapping an MXNet optimizer, rescale_grad folding,
broadcast_parameters after init) onto the TPU-native stack. With real
MXNet installed the ops take mx.nd.NDArrays; without it (the TPU image)
the same API runs on mutable numpy arrays — this example uses the
protocol form so it runs anywhere.

Run single-host:     python examples/mxnet_mnist.py
Run under tpurun:    tpurun -np 4 python examples/mxnet_mnist.py
"""

import argparse

import numpy as np

import horovod_tpu.mxnet as hvd


class SGD:
    """MXNet optimizer protocol: rescale_grad + update(index, w, g, state)
    (what mx.optimizer.SGD exposes; DistributedOptimizer folds the world
    average into rescale_grad, reference: horovod/mxnet/__init__.py:44-46).
    """

    def __init__(self, learning_rate, rescale_grad=1.0):
        self.lr = learning_rate
        self.rescale_grad = rescale_grad

    def update(self, index, weight, grad, state):
        if isinstance(index, (tuple, list)):
            for w, g in zip(weight, grad):
                w -= self.lr * self.rescale_grad * g
        else:
            weight -= self.lr * self.rescale_grad * grad

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def create_state_multi_precision(self, index, weight):
        return None

    def set_learning_rate(self, lr):
        self.lr = lr


def softmax(z):
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    args = parser.parse_args()

    hvd.init()

    rng = np.random.RandomState(1234)
    images = rng.rand(1024, 784).astype(np.float32)
    labels = rng.randint(0, 10, (1024,))

    # two-layer MLP held as plain mutable arrays (the NDArray stand-in)
    params = {
        "w1": (rng.randn(784, 128) * 0.05).astype(np.float32),
        "b1": np.zeros(128, np.float32),
        "w2": (rng.randn(128, 10) * 0.05).astype(np.float32),
        "b2": np.zeros(10, np.float32),
    }
    hvd.broadcast_parameters(params, root_rank=0)

    # reference pattern: scale LR by size, wrap, let rescale_grad average
    opt = hvd.DistributedOptimizer(SGD(args.lr * hvd.size()))

    from horovod_tpu.data import ShardedSampler

    sampler = ShardedSampler(len(images), seed=0)
    names = sorted(params)
    for epoch in range(args.epochs):
        sampler.set_epoch(epoch)
        idx = np.asarray(list(sampler))
        losses = []
        for i in range(0, len(idx), args.batch_size):
            take = idx[i:i + args.batch_size]
            x, y = images[take], labels[take]
            # forward
            h_pre = x @ params["w1"] + params["b1"]
            h = np.maximum(h_pre, 0.0)
            logits = h @ params["w2"] + params["b2"]
            p = softmax(logits)
            onehot = np.eye(10, dtype=np.float32)[y]
            losses.append(-np.log(p[np.arange(len(y)), y] + 1e-9).mean())
            # backward
            dlogits = (p - onehot) / len(y)
            grads = {
                "w2": h.T @ dlogits,
                "b2": dlogits.sum(0),
            }
            dh = (dlogits @ params["w2"].T) * (h_pre > 0)
            grads["w1"] = x.T @ dh
            grads["b1"] = dh.sum(0)
            # one update call with list indices: gradients are enqueued
            # together, negotiated + fused in one runtime cycle
            opt.update_multi_precision(
                list(range(len(names))),
                [params[n] for n in names],
                [grads[n] for n in names],
                [None] * len(names))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {np.mean(losses):.4f}")


if __name__ == "__main__":
    main()
