#!/usr/bin/env python
"""BERT-Large through the torch binding with the sparse embedding path.

BASELINE progression config #5: "BERT-Large-style allgather/sparse" —
the model family trained through the framework's torch API with the
token-embedding gradient exchanged SPARSELY (allgather of values+
indices, summed on coalesce) instead of densified, the way the reference
exchanges tf.IndexedSlices (reference: horovod/tensorflow/__init__.py:
64-75; examples/pytorch_synthetic_benchmark.py is the harness shape).

Torch executes on CPU in this stack (the TPU compute path is JAX — for
the chip-rate BERT-Large headline run ``python bench.py --model
bert-large``); this example demonstrates config #5's *exchange
semantics* end-to-end under the launcher:

    tpurun -np 2 python examples/pytorch_bert_large_sparse.py \
        --layers 2 --seq 32 --batch 4 --steps 2   # CI-sized
    tpurun -np 8 python examples/pytorch_bert_large_sparse.py  # full

Prints per-rank tokens/s and verifies all ranks hold identical weights
after training (the lockstep invariant).
"""

import argparse
import time

import numpy as np
import torch

import horovod_tpu.torch as hvd

VOCAB = 30522


class BertLarge(torch.nn.Module):
    """BERT-Large-shaped encoder MLM (d=1024, 16 heads, ff 4096; layer
    count configurable for CI). The token embedding is sparse=True so
    its gradient takes the allgather/sparse path."""

    def __init__(self, layers=24, d_model=1024, heads=16, seq=512):
        super().__init__()
        self.tok = torch.nn.Embedding(VOCAB, d_model, sparse=True)
        self.pos = torch.nn.Embedding(seq, d_model)
        layer = torch.nn.TransformerEncoderLayer(
            d_model, heads, dim_feedforward=4 * d_model,
            batch_first=True, norm_first=True)
        self.encoder = torch.nn.TransformerEncoder(layer, layers)
        self.head = torch.nn.Linear(d_model, VOCAB)

    def forward(self, ids):
        x = self.tok(ids) + self.pos.weight[None, : ids.shape[1]]
        return self.head(self.encoder(x))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--layers", type=int, default=24)
    parser.add_argument("--seq", type=int, default=512)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--steps", type=int, default=3)
    args = parser.parse_args()

    hvd.init()
    torch.manual_seed(1234 + hvd.rank())  # different init; broadcast fixes
    model = BertLarge(layers=args.layers, seq=args.seq)

    # sparse-compatible optimizer (momentum densifies); the wrapper
    # exchanges the embedding grad by allgather, everything else by
    # allreduce
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size()),
        named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    rng = np.random.RandomState(100 + hvd.rank())  # different data
    loss_fn = torch.nn.CrossEntropyLoss()
    tokens_done = 0
    t0 = time.perf_counter()
    for step in range(args.steps):
        ids = torch.from_numpy(
            rng.randint(0, VOCAB, (args.batch, args.seq)))
        logits = model(ids)
        loss = loss_fn(logits.reshape(-1, VOCAB), ids.reshape(-1))
        loss.backward()
        opt.step()
        opt.zero_grad()
        tokens_done += args.batch * args.seq
        if hvd.rank() == 0:
            print(f"step {step}: loss {loss.item():.3f}", flush=True)
    dt = time.perf_counter() - t0

    # lockstep invariant: every rank holds identical weights
    digest = hvd.allgather(
        torch.cat([p.detach().reshape(-1)[:512]
                   for p in model.parameters()]).reshape(1, -1),
        name="bert/weights")
    for r in range(1, hvd.size()):
        assert torch.equal(digest[0], digest[r]), "ranks diverged"

    print(f"rank {hvd.rank()}: {tokens_done / dt:.1f} tokens/s "
          f"(torch CPU; chip headline: bench.py --model bert-large) — "
          f"lockstep OK", flush=True)


if __name__ == "__main__":
    main()
