"""Distributed training on Spark executors.

Analogue of the reference's Spark usage (reference:
horovod/spark/__init__.py:100, examples/keras_spark_rossmann.py): a
training function handed to ``horovod_tpu.spark.run`` executes once per
rank inside the Spark executors, with the framework environment set up by
the driver. Requires a running SparkSession (pyspark).

    spark-submit examples/spark_run.py
"""


def train(epochs: int = 1):
    import jax
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.models.mnist import MnistConvNet

    hvd.init()
    opt = hvd.DistributedOptimizer(optax.adam(0.001 * hvd.size()))
    model = MnistConvNet()
    state = training.create_train_state(model, opt, (1, 28, 28, 1))
    step, sharding = training.make_train_step(model, opt)

    rng = np.random.RandomState(hvd.rank())
    params, stats, opt_state = (state.params, state.batch_stats,
                                state.opt_state)
    loss = None
    for _ in range(epochs * 4):
        xb = jax.device_put(rng.rand(32, 28, 28, 1).astype(np.float32),
                            sharding)
        yb = jax.device_put(rng.randint(0, 10, (32,)).astype(np.int32),
                            sharding)
        loss, params, stats, opt_state = step(params, stats, opt_state,
                                              xb, yb)
    return float(loss)


def main():
    from pyspark.sql import SparkSession

    import horovod_tpu.spark as hvd_spark

    spark = (SparkSession.builder.master("local[2]")
             .appName("horovod_tpu-spark-example").getOrCreate())
    try:
        losses = hvd_spark.run(train, args=(1,), num_proc=2)
        print("per-rank final losses:", losses)
    finally:
        spark.stop()


if __name__ == "__main__":
    main()
