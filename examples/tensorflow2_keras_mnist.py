#!/usr/bin/env python
"""tf.keras model.fit MNIST with the distributed callback suite.

Port of the reference's Keras example (reference:
examples/tensorflow2_keras_mnist.py, keras_mnist_advanced.py):
``DistributedOptimizer`` wraps the Keras optimizer, and the callback
trio does the distributed choreography — broadcast-on-start, cross-rank
metric averaging, gradual LR warmup. Rank 0 saves; ``load_model``
rewraps the restored optimizer.

Run:  tpurun -np 2 python examples/tensorflow2_keras_mnist.py --epochs 2
"""

import argparse
import os
import sys
import tempfile

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow.keras as hvd

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from tensorflow2_mnist import synthetic_digits  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--samples", type=int, default=1024)
    args = parser.parse_args()

    hvd.init()
    rng = np.random.RandomState(42 + hvd.rank())
    images, labels = synthetic_digits(args.samples, rng)

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation="relu",
                               input_shape=(28, 28, 1)),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(0.05 * hvd.size()))
    model.compile(
        optimizer=opt,
        loss=tf.keras.losses.SparseCategoricalCrossentropy(
            from_logits=True),
        metrics=["accuracy"])

    steps_per_epoch = args.samples // args.batch
    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            warmup_epochs=2, steps_per_epoch=steps_per_epoch, verbose=1),
    ]
    history = model.fit(images, labels, batch_size=args.batch,
                        epochs=args.epochs, callbacks=callbacks,
                        verbose=2 if hvd.rank() == 0 else 0)

    losses = history.history["loss"]
    assert losses[-1] < losses[0], (losses[0], losses[-1])

    # rank-0 checkpoint + rewrapping restore
    if hvd.rank() == 0:
        path = os.path.join(tempfile.mkdtemp(), "mnist.keras")
        model.save(path)
        restored = hvd.load_model(path)
        assert restored.optimizer is not None
        print(f"done: loss {losses[0]:.4f} -> {losses[-1]:.4f}; "
              f"checkpoint + rewrap OK", flush=True)


if __name__ == "__main__":
    main()
