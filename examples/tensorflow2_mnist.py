#!/usr/bin/env python
"""TF2 eager MNIST through the TensorFlow binding.

Port of the reference's flagship TF2 example (reference:
examples/tensorflow2_mnist.py): ``hvd.init()`` → scale the LR by world
size → ``DistributedGradientTape`` averages gradients →
``broadcast_variables`` after the first step aligns initial state →
rank 0 checkpoints. Synthetic digits when no dataset is cached
(zero-egress CI).

Run single-host:   python examples/tensorflow2_mnist.py
Under the launcher: tpurun -np 2 python examples/tensorflow2_mnist.py --steps 20
"""

import argparse

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def synthetic_digits(n, rng):
    """Blurry class-coded blobs — learnable structure, no download."""
    labels = rng.randint(0, 10, n).astype(np.int64)
    images = rng.rand(n, 28, 28, 1).astype(np.float32) * 0.1
    for i, y in enumerate(labels):
        images[i, 2 + 2 * (y % 5): 6 + 2 * (y % 5),
               4 + 2 * (y // 5): 10 + 2 * (y // 5), 0] += 0.9
    return images, labels


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch", type=int, default=64)
    args = parser.parse_args()

    hvd.init()

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation="relu",
                               input_shape=(28, 28, 1)),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)
    # LR scales with world size — the canonical recipe
    opt = tf.keras.optimizers.SGD(0.05 * hvd.size())

    # each rank sees its own shard (different seed = different data)
    rng = np.random.RandomState(42 + hvd.rank())
    images, labels = synthetic_digits(args.batch * args.steps, rng)

    first_loss = last_loss = None
    for step in range(args.steps):
        xb = images[step * args.batch:(step + 1) * args.batch]
        yb = labels[step * args.batch:(step + 1) * args.batch]
        with tf.GradientTape() as tape:
            loss = loss_fn(yb, model(xb, training=True))
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if step == 0:
            # after the first step (variables now exist), align every
            # rank to rank 0 (reference: tensorflow2_mnist.py step hook)
            hvd.broadcast_variables(
                model.variables + hvd.optimizer_variables(opt), root_rank=0)
            first_loss = float(loss)
        last_loss = float(loss)
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step}: loss {float(loss):.4f}", flush=True)

    # loss must have improved, and ranks must agree on the weights
    digest = hvd.allgather(
        tf.reshape(tf.concat(
            [tf.reshape(v, [-1])[:64] for v in model.trainable_variables],
            axis=0), [1, -1]))
    for r in range(1, hvd.size()):
        np.testing.assert_array_equal(digest[0].numpy(),
                                      digest[r].numpy(),
                                      err_msg="ranks diverged")
    if hvd.rank() == 0:
        print(f"done: loss {first_loss:.4f} -> {last_loss:.4f}, "
              f"ranks in lockstep OK", flush=True)


if __name__ == "__main__":
    main()
