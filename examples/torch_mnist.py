"""PyTorch MNIST through the torch binding.

Direct analogue of the reference's example (reference:
examples/pytorch_mnist.py): the training script is ordinary PyTorch; the
framework provides init, LR scaling, the hook-driven DistributedOptimizer,
and the rank-0 broadcast convention — gradients ride the XLA data plane.
"""

import argparse
import os

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = nn.Linear(320, 50)
        self.fc2 = nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.view(-1, 320)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()

    hvd.init()
    torch.manual_seed(42)

    model = Net()
    # scale LR by world size; wrap with the hook-driven optimizer
    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.lr * hvd.size(), momentum=0.5)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    # broadcast initial parameters + optimizer state from rank 0
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    rng = np.random.RandomState(1234)
    images = torch.tensor(rng.rand(1024, 1, 28, 28), dtype=torch.float32)
    labels = torch.tensor(rng.randint(0, 10, (1024,)), dtype=torch.long)

    # Shard the dataset by rank, the reference's input convention
    # (reference: examples/pytorch_mnist.py DistributedSampler with
    # num_replicas=hvd.size(), rank=hvd.rank()). Torch data parallelism
    # here is one worker per LAUNCHED PROCESS (tpurun); a single process
    # — whatever its device count — is one data-parallel worker, so don't
    # shard by device count in that case.
    multiproc = os.environ.get("HOROVOD_RANK") is not None
    data_world = hvd.size() if multiproc else 1
    data_rank = hvd.rank() if multiproc else 0
    dataset = torch.utils.data.TensorDataset(images, labels)
    sampler = torch.utils.data.distributed.DistributedSampler(
        dataset, num_replicas=data_world, rank=data_rank)
    loader = torch.utils.data.DataLoader(
        dataset, batch_size=args.batch_size, sampler=sampler)

    for epoch in range(args.epochs):
        model.train()
        sampler.set_epoch(epoch)
        losses = []
        for xb, yb in loader:
            optimizer.zero_grad()
            output = model(xb)
            loss = F.nll_loss(output, yb)
            loss.backward()
            optimizer.step()
            losses.append(loss.item())
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {np.mean(losses):.4f}")


if __name__ == "__main__":
    main()
