"""PyTorch synthetic benchmark through the torch binding.

Analogue of the reference's harness (reference:
examples/pytorch_synthetic_benchmark.py:37-110) with the same measurement
protocol: warmup batches, then timed rounds, imgs/sec with 95% confidence.
Model runs on CPU torch; gradient exchange rides the XLA data plane.
"""

import argparse
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class SmallConvNet(nn.Module):
    """Compact stand-in for torchvision resnet50 (CPU-friendly)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 32, 3, stride=2, padding=1)
        self.conv2 = nn.Conv2d(32, 64, 3, stride=2, padding=1)
        self.fc = nn.Linear(64, num_classes)

    def forward(self, x):
        x = F.relu(self.conv1(x))
        x = F.relu(self.conv2(x))
        x = F.adaptive_avg_pool2d(x, 1).flatten(1)
        return self.fc(x)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-warmup-batches", type=int, default=3)
    parser.add_argument("--num-batches-per-iter", type=int, default=3)
    parser.add_argument("--num-iters", type=int, default=3)
    parser.add_argument("--image-size", type=int, default=64)
    parser.add_argument("--fp16-allreduce", action="store_true")
    args = parser.parse_args()

    hvd.init()
    model = SmallConvNet()
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size())
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    data = torch.randn(args.batch_size, 3, args.image_size, args.image_size)
    target = torch.randint(0, 1000, (args.batch_size,))

    def benchmark_step():
        optimizer.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        optimizer.step()

    if hvd.rank() == 0:
        print(f"Batch size: {args.batch_size}, workers: {hvd.size()}")
    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.time()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        dt = time.time() - t0
        rate = args.batch_size * args.num_batches_per_iter * hvd.size() / dt
        img_secs.append(rate)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate:.1f} img/sec total")

    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec total: {mean:.1f} +- {conf:.1f}")


if __name__ == "__main__":
    main()
