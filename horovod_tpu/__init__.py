"""horovod_tpu — TPU-native distributed training framework.

A ground-up, TPU-first implementation of the capability surface of the
reference data-parallel framework (Horovod v0.18.1, surveyed in SURVEY.md):
wrap your optimizer, and named gradient tensors are averaged across workers
with bandwidth-optimal collectives — here XLA collectives
(``psum``/``all_gather``/``ppermute``) over ICI/DCN on a
``jax.sharding.Mesh``, instead of NCCL/MPI rings over GPUs.

Canonical usage (mirrors reference: examples/*.py):

    import horovod_tpu as hvd

    hvd.init()
    # scale learning rate by number of workers
    opt = hvd.DistributedOptimizer(optax.sgd(0.01 * hvd.size()))
    params = hvd.broadcast_parameters(params, root_rank=0)
    ...
    if hvd.rank() == 0:
        save_checkpoint(...)
"""

from horovod_tpu.version import __version__

# JAX API-drift shims (jax.shard_map spelling, lax.axis_size) — must be
# in place before any data-plane module is imported.
from horovod_tpu.utils import compat as _compat

_compat.install()

# Load the metrics submodule BEFORE binding the hvd.metrics() API below:
# the first import of a submodule sets it as a package attribute, which
# would clobber the function whenever internal code lazily imported the
# module later. Loaded up front, the module sits in sys.modules (where
# `from horovod_tpu.metrics import ...` resolves it) and the function
# binding below stays the package attribute.
import horovod_tpu.metrics  # noqa: F401

from horovod_tpu.core.basics import (
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    mesh,
    metrics,
    is_homogeneous,
    mpi_built,
    gloo_built,
    nccl_built,
    ddl_built,
    mlsl_built,
    xla_built,
    mpi_enabled,
    mpi_threads_supported,
)
from horovod_tpu.core.mesh import CROSS_AXIS, GLOBAL_AXES, LOCAL_AXIS
from horovod_tpu.ops.collectives import (
    Average,
    Sum,
    Min,
    Max,
    Product,
    Handle,
    OrderedLaneError,
    allreduce,
    allreduce_async,
    assert_collective_lane_clear,
    allgather,
    allgather_async,
    alltoall,
    broadcast,
    broadcast_async,
    grouped_allreduce,
    grouped_allreduce_async,
    poll,
    reducescatter,
    stack_per_worker,
    synchronize,
)
from horovod_tpu.compression import Compression
from horovod_tpu.parallel.dp import (
    DistributedOptimizer,
    DistributedGradientTape,
    allreduce_gradients,
    broadcast_parameters,
    broadcast_optimizer_state,
    broadcast_object,
)
from horovod_tpu.parallel.buckets import GradReleasePlan
from horovod_tpu.parallel.zero import (
    FlatAdamState,
    ShardedGrads,
    ShardedOptState,
    ShardedParams,
    gather_params,
    iter_param_buckets,
    scatter_gradients,
    shard_params,
    sharded_adamw,
    sharded_update,
)
from horovod_tpu.parallel.sparse import (
    SparseGrad,
    sparse_allgather,
    with_sparse_embedding_grad,
)
from horovod_tpu.parallel.ring import ring_attention
from horovod_tpu.parallel.ulysses import ulysses_attention
from horovod_tpu.parallel.tp import (
    params_shardings,
    tp_train_step,
    transformer_tp_rules,
    xla_attention,
)
from horovod_tpu.parallel.pp import (
    last_stage_value,
    pipeline_apply,
    stack_stage_params,
)
from horovod_tpu.parallel.ep import (
    default_capacity,
    load_balance_loss,
    switch_moe,
)
from horovod_tpu.ops.pallas import flash_attention
from horovod_tpu.flight_recorder import dump_debug_state
from horovod_tpu import profiler
from horovod_tpu import tracing
from horovod_tpu import checkpoint
from horovod_tpu import ckpt
from horovod_tpu import data
from horovod_tpu import elastic
from horovod_tpu import integrity
# `hvd.serve(model, params, ...)` is the API; the module stays reachable
# as `horovod_tpu.serve` via sys.modules for internal imports.
from horovod_tpu.serve import ServePolicy, serve
from horovod_tpu.exceptions import (
    CheckpointCorruptError,
    CollectiveIntegrityError,
    HorovodInternalError,
    HostsUpdatedInterrupt,
    NumericalError,
    WorkersDownError,
    WorkerLostError,
    WorkerStallError,
)

__all__ = [
    "__version__",
    # lifecycle / topology
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "mesh", "metrics", "is_homogeneous", "dump_debug_state", "profiler",
    "tracing",
    "CROSS_AXIS", "LOCAL_AXIS", "GLOBAL_AXES",
    # capability probes
    "mpi_built", "gloo_built", "nccl_built", "ddl_built", "mlsl_built",
    "xla_built", "mpi_enabled", "mpi_threads_supported",
    # collectives
    "Average", "Sum", "Min", "Max", "Product",
    "allreduce", "allreduce_async", "grouped_allreduce",
    "grouped_allreduce_async",
    "allgather", "allgather_async", "broadcast", "broadcast_async",
    "reducescatter", "alltoall", "stack_per_worker",
    "Handle", "poll", "synchronize",
    "OrderedLaneError", "assert_collective_lane_clear",
    # data-parallel API
    "DistributedOptimizer", "DistributedGradientTape", "allreduce_gradients",
    "broadcast_parameters", "broadcast_optimizer_state", "broadcast_object",
    "Compression",
    # bucket-wise gradient release (overlap allreduce with backward)
    "GradReleasePlan",
    # ZeRO-1/2/3 sharded training (TPU-first extension)
    "sharded_update", "sharded_adamw", "ShardedOptState", "FlatAdamState",
    "ShardedGrads", "ShardedParams", "scatter_gradients", "shard_params",
    "gather_params", "iter_param_buckets",
    # sparse/embedding gradients
    "SparseGrad", "sparse_allgather", "with_sparse_embedding_grad",
    # long-context / sequence parallelism (TPU-first extensions)
    "flash_attention", "ring_attention", "ulysses_attention",
    # tensor parallelism (TPU-first extension)
    "transformer_tp_rules", "params_shardings", "tp_train_step",
    "xla_attention",
    # pipeline parallelism (TPU-first extension)
    "pipeline_apply", "last_stage_value", "stack_stage_params",
    # expert parallelism / MoE (TPU-first extension)
    "switch_moe", "load_balance_loss", "default_capacity",
    # checkpoint / resume (rank-0 save + broadcast restore)
    "checkpoint",
    # crash-consistent sharded checkpointing (two-phase commit + replicas)
    "ckpt", "CheckpointCorruptError",
    "data",
    # elastic fault tolerance (reference: horovod.elastic)
    "elastic",
    "HorovodInternalError", "HostsUpdatedInterrupt",
    "WorkersDownError", "WorkerLostError", "WorkerStallError",
    # numerical integrity plane (digests / guards / rollback-and-replay)
    "integrity", "NumericalError", "CollectiveIntegrityError",
    # online serving plane (continuous batching; docs/inference.md)
    "serve", "ServePolicy",
]
