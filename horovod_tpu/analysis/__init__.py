"""Static + runtime concurrency and collective-safety analysis.

Six PRs grew the runtime into a genuinely concurrent system — a background
cycle thread, pipelined pending-op dispatch/drain, fusion-buffer leases,
elastic generation reforms — with invariants (lease released on every
path, collectives issued in identical order on every rank, no blocking
call under the cycle lock) that nothing proved. This package is the
correctness backstop:

* :mod:`lockgraph` — AST analyzer over the package: extracts every lock
  acquisition, builds the lock-order graph, reports order-inversion
  cycles, blocking calls made while a lock is held, and mutations of
  ``# guarded-by:``-annotated shared attributes outside their lock.
* :mod:`divergence` — SPMD collective-divergence linter: collective calls
  reachable only under rank-/size-conditional control flow, or carrying
  non-deterministic ``name=`` arguments, diverge the cross-rank program
  order — the silent-deadlock class negotiation can't always catch.
* :mod:`witness` — runtime deadlock witness (``HOROVOD_DEBUG_LOCKS=1``):
  a drop-in lock wrapper used by the runtime's own locks in debug mode
  that records per-thread acquisition order, detects inversions,
  waits-for deadlock cycles and over-threshold hold times live, and
  emits ``lock_acquire``/``lock_hold`` events into the flight recorder.
* :mod:`baseline` — checked-in accepted-findings file
  (``tools/analysis_baseline.json``): new violations fail CI, reviewed
  pre-existing ones are suppressed and enumerated.

CLI: ``python tools/hvd_analyze.py`` (tier-1 enforced by
tests/test_analysis.py). Docs: docs/analysis.md.
"""

from horovod_tpu.analysis.report import Finding  # noqa: F401
from horovod_tpu.analysis import baseline  # noqa: F401
from horovod_tpu.analysis import divergence  # noqa: F401
from horovod_tpu.analysis import lockgraph  # noqa: F401
from horovod_tpu.analysis import witness  # noqa: F401


def run_static_passes(paths, root=None):
    """Run every static pass over ``paths`` (files or directories).
    Returns (findings, lock_order_edges) — the edges feed the runtime
    witness's static-order assertion."""
    lg = lockgraph.analyze_paths(paths, root=root)
    dv = divergence.analyze_paths(paths, root=root)
    return lg.findings + dv, lg.edges
