"""Accepted-findings baseline (tools/analysis_baseline.json).

The baseline is a reviewed suppression list, not a dumping ground: every
entry carries a ``reason`` string explaining why the finding is accepted
rather than fixed. ``compare()`` splits a run's findings into *new*
(fail CI) and *suppressed* (enumerated), and reports *stale* suppressions
whose code no longer trips the analyzer so the file shrinks as fixes land.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

from horovod_tpu.analysis.report import Finding, sort_findings

SCHEMA = "hvd-analyze-baseline-v1"


def load(path: str) -> Dict[str, Dict[str, object]]:
    """Return {fingerprint: suppression-entry}. Missing file → empty."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    if data.get("schema") != SCHEMA:
        raise ValueError(f"{path}: unknown baseline schema {data.get('schema')!r}")
    out = {}
    for entry in data.get("suppressions", []):
        fp = entry.get("fingerprint")
        if not fp:
            raise ValueError(f"{path}: suppression missing fingerprint: {entry}")
        if not entry.get("reason"):
            raise ValueError(f"{path}: suppression {fp} has no reason string")
        out[fp] = entry
    return out


def compare(
    findings: List[Finding], baseline: Dict[str, Dict[str, object]]
) -> Tuple[List[Finding], List[Finding], List[Dict[str, object]]]:
    """Split findings into (new, suppressed) and list stale suppressions."""
    new, suppressed = [], []
    seen = set()
    for f in sort_findings(findings):
        seen.add(f.fingerprint)
        (suppressed if f.fingerprint in baseline else new).append(f)
    stale = [e for fp, e in sorted(baseline.items()) if fp not in seen]
    return new, suppressed, stale


def write(path: str, findings: List[Finding], reasons: Dict[str, str] | None = None) -> None:
    """Write a baseline accepting every finding in ``findings``.

    ``reasons`` maps fingerprints to reason strings; entries without one
    get a placeholder that a human must replace (load() accepts it — the
    review gate is code review, not the loader).
    """
    reasons = reasons or {}
    sup = []
    for f in sort_findings(findings):
        sup.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "file": f.file,
            "symbol": f.symbol,
            "message": f.message,
            "reason": reasons.get(f.fingerprint, "TODO: reviewed-by a human — explain why this is accepted"),
        })
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"schema": SCHEMA, "suppressions": sup}, f, indent=2, sort_keys=False)
        f.write("\n")
