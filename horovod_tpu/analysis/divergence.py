"""SPMD collective-divergence linter.

Collectives must be issued in identical order with identical names on
every rank; a collective reachable only on some ranks deadlocks the rest
silently (the negotiation stall warning fires minutes later with no
culprit). Three rules:

* ``rank-conditional-collective`` — a collective call lexically inside a
  rank-conditional branch (``rank``/``local_rank``/``process_index``/
  ``is_coordinator`` … in the test) whose sibling branch does not issue
  the same collective. Symmetric patterns — the same collective name in
  both arms, or in a terminal (return/raise) arm and the fall-through
  code — are accepted: those keep cross-rank order aligned.
* ``size-conditional-collective`` — same, for world-size conditionals
  (``size``/``world_size``/``num_processes`` …). Lower confidence:
  size is uniform across ranks, so this diverges *configurations* rather
  than ranks (the classic "works at N=1, hangs at N=8" bug). Early-exit
  ``if size <= 1: return`` guards are not flagged — only collectives
  *inside* a size branch.
* ``nondeterministic-collective-name`` — a collective whose ``name=``
  argument embeds ``id()``/``uuid*``/time/random calls (directly or via
  f-string interpolation): ranks disagree on the name and never match.

The matcher covers the public lanes (``allreduce*``, ``allgather*``,
``broadcast*``/``bcast*``, ``reducescatter``/``reduce_scatter``,
``alltoall*``, ``psum*``/``pmean``/``pmin``/``pmax``, ``barrier``,
``grouped_*``, ``sharded_*``) by callee-name prefix; the numpy/jax shape
utilities ``broadcast_to``/``broadcast_arrays``/``broadcast_shapes`` are
explicitly excluded (same prefix, no cross-rank traffic).
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Sequence, Set

from horovod_tpu.analysis.report import Finding, sort_findings
from horovod_tpu.analysis.lockgraph import _iter_py_files, _rel, _call_name

COLLECTIVE_RE = re.compile(
    r"^(allreduce|allgather|all_gather|alltoall|all_to_all|broadcast|bcast"
    r"|reducescatter|reduce_scatter|psum|pmean|pmin|pmax|barrier"
    r"|grouped_|sharded_)"
)

RANK_TOKENS = {
    "rank", "local_rank", "cross_rank", "process_index", "launch_rank",
    "is_coordinator", "rank0", "is_root", "is_leader",
}
# root_rank/rank counts as uniform when it's a *parameter* compared against
# a constant — but st.rank/hvd.rank() in the test is per-rank. We exclude
# only the conventional uniform parameter name.
UNIFORM_NAMES = {"root_rank"}

SIZE_TOKENS = {
    "size", "world_size", "local_size", "cross_size", "num_processes",
    "process_count", "nproc", "world",
}

NONDET_CALLS = {
    "id", "uuid1", "uuid4", "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns", "random", "randint", "randrange",
    "getrandbits", "token_hex", "token_urlsafe", "urandom", "getpid",
}


# numpy/jax shape utilities that share the broadcast* prefix but move no
# data between ranks
NOT_COLLECTIVES = {"broadcast_to", "broadcast_arrays", "broadcast_shapes"}


def is_collective_name(name: Optional[str]) -> bool:
    if not name or name in NOT_COLLECTIVES:
        return False
    return bool(COLLECTIVE_RE.match(name))


def _test_tokens(test: ast.expr) -> Set[str]:
    toks: Set[str] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Name):
            toks.add(node.id)
        elif isinstance(node, ast.Attribute):
            toks.add(node.attr)
        elif isinstance(node, ast.Call):
            n = _call_name(node.func)
            if n:
                toks.add(n)
    return toks


def _classify_test(test: ast.expr) -> Optional[str]:
    toks = _test_tokens(test) - UNIFORM_NAMES
    if toks & RANK_TOKENS:
        return "rank"
    if toks & SIZE_TOKENS:
        return "size"
    return None


def _collectives_in(stmts: Sequence[ast.stmt]) -> List[ast.Call]:
    out = []
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, ast.Call) and is_collective_name(_call_name(node.func)):
                out.append(node)
            # Nested defs run later on their own schedule — skip.
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break
    return out


def _is_terminal(stmts: Sequence[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(stmts[-1], (ast.Return, ast.Raise,
                                                  ast.Continue, ast.Break))


def _nondet_name_expr(expr: ast.expr) -> Optional[str]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            n = _call_name(node.func)
            if n in NONDET_CALLS:
                return n
    return None


class _Linter(ast.NodeVisitor):
    def __init__(self, rel: str):
        self.rel = rel
        self.findings: List[Finding] = []
        self.symbol_stack: List[str] = []

    def _symbol(self) -> str:
        return ".".join(self.symbol_stack) if self.symbol_stack else "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.symbol_stack.append(node.name)
        self.generic_visit(node)
        self.symbol_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.symbol_stack.append(node.name)
        for block in self._blocks_under(node):
            self._check_body_block(block)
        self.generic_visit(node)
        self.symbol_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    @staticmethod
    def _blocks_under(fn: ast.FunctionDef) -> List[Sequence[ast.stmt]]:
        """Every statement block in the function (body, branch arms, loop
        bodies, try arms) — but not blocks of nested function defs."""
        blocks: List[Sequence[ast.stmt]] = []
        stack: List[ast.stmt] = list(fn.body)
        blocks.append(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(node, attr, None)
                if sub:
                    blocks.append(sub)
                    stack.extend(sub)
            for h in getattr(node, "handlers", []) or []:
                blocks.append(h.body)
                stack.extend(h.body)
        return blocks

    # --- rule: conditional collectives ----------------------------------
    def visit_If(self, node: ast.If) -> None:
        kind = _classify_test(node.test)
        if kind == "rank" and not node.orelse and _is_terminal(node.body):
            # `if rank…: return/raise` — an early exit, not a branch pair.
            # _check_body_block compares the exiting arm against the
            # fall-through code so symmetric patterns like
            # `if rank == 0: return bcast(x)` / `return bcast(None)` pass.
            kind = None
        if kind is not None:
            body_calls = _collectives_in(node.body)
            else_calls = _collectives_in(node.orelse)
            body_names = {_call_name(c.func) for c in body_calls}
            else_names = {_call_name(c.func) for c in else_calls}
            for call in body_calls:
                if _call_name(call.func) not in else_names:
                    self._flag_conditional(kind, call, node, side="then")
            for call in else_calls:
                if _call_name(call.func) not in body_names:
                    self._flag_conditional(kind, call, node, side="else")
        self.generic_visit(node)

    def _flag_conditional(self, kind: str, call: ast.Call, ifnode: ast.If,
                          side: str) -> None:
        n = _call_name(call.func)
        rule = f"{kind}-conditional-collective"
        self.findings.append(Finding(
            rule=rule, file=self.rel, line=call.lineno, symbol=self._symbol(),
            message=(f"collective {n}() reachable only under {kind}-conditional "
                     f"branch ({side}-arm of if at line {ifnode.lineno}) with no "
                     f"matching collective on the other arm"),
            detail=f"{n} in {side}-arm {kind}-cond within {self._symbol()}",
        ))

    # --- rule: early-exit divergence ------------------------------------
    def _check_body_block(self, stmts: Sequence[ast.stmt]) -> None:
        """Rank-conditional early exits: ``if rank != 0: return`` followed by
        collectives in the fall-through makes the collective rank-gated.
        Symmetric early returns (the terminal arm issues the same
        collectives as the fall-through) are accepted."""
        for i, s in enumerate(stmts):
            if not isinstance(s, ast.If) or s.orelse:
                continue
            if _classify_test(s.test) != "rank":
                continue
            if not _is_terminal(s.body):
                continue
            arm_names = {_call_name(c.func) for c in _collectives_in(s.body)}
            rest = stmts[i + 1:]
            for call in _collectives_in(rest):
                n = _call_name(call.func)
                if n not in arm_names:
                    self.findings.append(Finding(
                        rule="rank-conditional-collective",
                        file=self.rel, line=call.lineno, symbol=self._symbol(),
                        message=(f"collective {n}() only reachable past the "
                                 f"rank-conditional early exit at line {s.lineno}"),
                        detail=f"{n} after rank early-exit in {self._symbol()}",
                    ))
            for call in _collectives_in(s.body):
                n = _call_name(call.func)
                rest_names = {_call_name(c.func) for c in _collectives_in(rest)}
                if n not in rest_names:
                    self.findings.append(Finding(
                        rule="rank-conditional-collective",
                        file=self.rel, line=call.lineno, symbol=self._symbol(),
                        message=(f"collective {n}() issued only on the exiting "
                                 f"side of the rank conditional at line {s.lineno}"),
                        detail=f"{n} in rank early-exit arm in {self._symbol()}",
                    ))

    # --- rule: nondeterministic names -----------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        n = _call_name(node.func)
        if is_collective_name(n):
            for kw in node.keywords:
                if kw.arg and kw.arg.endswith("name"):
                    bad = _nondet_name_expr(kw.value)
                    if bad:
                        self.findings.append(Finding(
                            rule="nondeterministic-collective-name",
                            file=self.rel, line=node.lineno, symbol=self._symbol(),
                            message=(f"collective {n}() name= embeds {bad}() — "
                                     f"ranks will disagree on the tensor name"),
                            detail=f"{n} name embeds {bad} in {self._symbol()}",
                        ))
        self.generic_visit(node)


def analyze_paths(paths: Sequence[str], root: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in _iter_py_files(list(paths)):
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue  # lockgraph reports parse errors
        linter = _Linter(_rel(path, root))
        linter.visit(tree)
        findings.extend(linter.findings)
    return sort_findings(findings)
