"""AST concurrency analyzer: lock-order graph, blocking-under-lock,
guarded-by annotation checking.

What it understands (documented honestly in docs/analysis.md):

* Lock definitions: ``self.X = threading.Lock()/RLock()/Condition()``,
  module-level equivalents, ``dataclasses.field(default_factory=
  threading.RLock)`` on a class attribute, and ``make_lock("Name", ...)``
  from the runtime witness — in which case the *string argument* becomes
  the lock's id, so static ids and witness ids agree by construction.
  Other locks get ``Class.attr`` / ``module.attr`` ids.
* Acquisitions: ``with self.X:`` (including multi-item ``with``). Bare
  ``.acquire()`` calls are not tracked for ordering (the runtime witness
  covers them); they don't appear in this codebase outside the witness.
* Lock-order edges: lock A held (lexically or via the interprocedural
  closure below) while lock B is acquired → edge A→B. Cycles in the
  resulting graph are reported as ``lock-order-cycle``.
* Interprocedural closure: per-function summaries (locks acquired,
  blocking calls, callees) are joined to a fixpoint. Call resolution is
  deliberately conservative: bare names resolve to same-module functions
  or classes (→ ``__init__``), ``self.m()`` to methods of the enclosing
  class. Unresolvable calls contribute nothing — except the project's
  known network verbs (``bcast_blob``, ``barrier``, ``probe_and_seed``,
  …) which are treated as blocking wherever they appear.
* Blocking calls: ``.get()``/``.join()``/``.wait()`` with no positional
  args and no ``timeout=``/``block=`` kwarg, ``.recv``/``.recv_into``/
  ``.accept``/``.connect`` (no ``timeout=``), ``block_until_ready``,
  ``time.sleep``, ``urlopen``, and the network verbs above.
* ``# guarded-by: <lock>`` trailing an ``self.X = …`` assignment declares
  that every mutation of ``self.X`` outside ``__init__`` must hold the
  named lock (an attr name of a lock in the same class, or a full lock
  id). ``# guarded-by: <something-in-angle-brackets>`` declares thread
  confinement instead: mutations through non-``self`` expressions from
  other classes are flagged, in-class mutations are trusted.
  ``# holds-lock: <lock>`` trailing a ``def`` line declares a caller-side
  precondition the analyzer assumes (and propagates) inside that method.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from horovod_tpu.analysis.report import Finding, sort_findings

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

# Calls that block indefinitely regardless of signature.
ALWAYS_BLOCKING_ATTRS = {
    "recv", "recv_into", "accept", "connect", "block_until_ready",
    "serve_forever", "communicate",
}
# Project-specific network verbs (socket controller / rendezvous / host
# collectives): blocking wherever they appear, held lock or not — the
# finding fires only when a lock is held.
NETWORK_VERBS = {
    "bcast_blob", "bcast", "barrier", "gatherv", "bit_and_or",
    "probe_and_seed", "blocking_key_value_get", "allreduce", "allgatherv",
    "urlopen", "compute_response_list",
}
# Zero-positional-arg calls that block without a timeout kwarg.
TIMEOUT_GATED_ATTRS = {"get", "join", "wait"}

MUTATOR_METHODS = {
    "append", "extend", "insert", "pop", "popitem", "popleft", "appendleft",
    "remove", "clear", "update", "setdefault", "add", "discard", "sort",
    "reverse", "move_to_end",
}

GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(<[^>]+>|[\w.]+)")
HOLDS_LOCK_RE = re.compile(r"#\s*holds-lock:\s*([\w.]+)")
ATTR_ASSIGN_RE = re.compile(r"self\.(\w+)\s*(?::[^=]+)?=")


@dataclasses.dataclass
class LockDef:
    lock_id: str     # "Class.attr", "module.attr", or make_lock name
    file: str
    line: int
    cls: Optional[str]   # owning class name, if any
    attr: str            # final attribute / variable name


@dataclasses.dataclass
class FuncSummary:
    key: str             # "file::Class.meth" or "file::func"
    file: str
    symbol: str          # "Class.meth" / "func"
    line: int
    # locks this function may acquire (transitively filled by fixpoint)
    acquires: Set[str] = dataclasses.field(default_factory=set)
    # (desc, line) blocking calls made directly in this function
    blocking: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    # resolved callee keys with the locks held at the call site
    calls: List[Tuple[str, int, Tuple[str, ...]]] = dataclasses.field(default_factory=list)
    # does this function (transitively) block?
    blocks: bool = False
    # representative blocking description for transitive reporting
    blocks_via: str = ""


@dataclasses.dataclass
class GuardRule:
    cls: str
    attr: str
    guard: str           # lock id, or "<token>" for confinement
    file: str
    line: int

    @property
    def confined(self) -> bool:
        return self.guard.startswith("<")


@dataclasses.dataclass
class Analysis:
    findings: List[Finding]
    edges: List[Tuple[str, str]]          # deduped lock-order edges
    locks: Dict[str, LockDef]
    guards: List[GuardRule]


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d not in ("__pycache__",))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif p.endswith(".py"):
            out.append(p)
    return out


def _rel(path: str, root: Optional[str]) -> str:
    if root:
        try:
            return os.path.relpath(path, root).replace(os.sep, "/")
        except ValueError:
            pass
    return path.replace(os.sep, "/")


def _call_name(node: ast.expr) -> Optional[str]:
    """Final identifier of a call target: f() → f, a.b.c() → c."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_lock_factory(call: ast.Call) -> bool:
    name = _call_name(call.func)
    return name in LOCK_FACTORIES


def _make_lock_name(call: ast.Call) -> Optional[str]:
    """make_lock("Name", ...) → "Name"."""
    if _call_name(call.func) == "make_lock" and call.args:
        a = call.args[0]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return None


def _dataclass_field_lock(call: ast.Call) -> bool:
    """dataclasses.field(default_factory=threading.RLock) and friends."""
    if _call_name(call.func) != "field":
        return False
    for kw in call.keywords:
        if kw.arg == "default_factory":
            v = kw.value
            if isinstance(v, (ast.Name, ast.Attribute)) and _call_name(v) in LOCK_FACTORIES:
                return True
            if isinstance(v, ast.Lambda):
                b = v.body
                if isinstance(b, ast.Call) and (_is_lock_factory(b) or _make_lock_name(b)):
                    return True
                if isinstance(b, ast.Call) and _call_name(b.func) == "make_lock":
                    return True
    return False


def _dataclass_field_make_lock_name(call: ast.Call) -> Optional[str]:
    if _call_name(call.func) != "field":
        return None
    for kw in call.keywords:
        if kw.arg == "default_factory" and isinstance(kw.value, ast.Lambda):
            b = kw.value.body
            if isinstance(b, ast.Call):
                return _make_lock_name(b)
    return None


class _ModuleIndex:
    """Per-file: classes, functions, lock defs, guard rules."""

    def __init__(self, path: str, rel: str, tree: ast.Module, source_lines: List[str]):
        self.path = path
        self.rel = rel
        self.tree = tree
        self.lines = source_lines
        self.classes: Dict[str, ast.ClassDef] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}  # module-level only
        self.methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
        self.locks: Dict[str, LockDef] = {}
        self.guards: List[GuardRule] = []
        self._index()

    def _index(self) -> None:
        modname = os.path.splitext(os.path.basename(self.path))[0]
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.methods[(node.name, sub.name)] = sub
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node

        # Lock defs: module-level assigns, class-body AnnAssigns (dataclass
        # fields), and self.X = Lock() inside any method.
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                self._maybe_register(node.targets, node.value, cls=None,
                                     modname=modname, line=node.lineno)
        for cname, cnode in self.classes.items():
            for sub in cnode.body:
                if isinstance(sub, ast.AnnAssign) and isinstance(sub.value, ast.Call):
                    if isinstance(sub.target, ast.Name):
                        call = sub.value
                        name = (_make_lock_name(call)
                                or _dataclass_field_make_lock_name(call))
                        if name is None and (_is_lock_factory(call)
                                             or _dataclass_field_lock(call)):
                            name = f"{cname}.{sub.target.id}"
                        if name is not None:
                            self._register(name, cname, sub.target.id, sub.lineno)
                elif isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                    self._maybe_register(sub.targets, sub.value, cls=cname,
                                         modname=modname, line=sub.lineno)
            for (mc, _mn), m in list(self.methods.items()):
                if mc != cname:
                    continue
                for stmt in ast.walk(m):
                    if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                        for t in stmt.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                call = stmt.value
                                name = _make_lock_name(call)
                                if name is None and _is_lock_factory(call):
                                    name = f"{cname}.{t.attr}"
                                if name is not None:
                                    self._register(name, cname, t.attr, stmt.lineno)

        # guarded-by annotations: comment on the same line as a self.X assign.
        class_ranges = [(c.lineno, c.end_lineno or c.lineno, c.name)
                        for c in self.classes.values()]
        for i, text in enumerate(self.lines, start=1):
            gm = GUARDED_BY_RE.search(text)
            if not gm:
                continue
            am = ATTR_ASSIGN_RE.search(text)
            if not am:
                continue
            cls = None
            for lo, hi, cname in class_ranges:
                if lo <= i <= hi:
                    cls = cname
                    break
            if cls is None:
                continue
            self.guards.append(GuardRule(cls=cls, attr=am.group(1), guard=gm.group(1),
                                         file=self.rel, line=i))

    def _maybe_register(self, targets, call: ast.Call, cls: Optional[str],
                        modname: str, line: int) -> None:
        name = _make_lock_name(call)
        is_lock = name is not None or _is_lock_factory(call) or _dataclass_field_lock(call)
        if not is_lock:
            return
        for t in targets:
            if isinstance(t, ast.Name):
                lock_id = name or (f"{cls}.{t.id}" if cls else f"{modname}.{t.id}")
                self._register(lock_id, cls, t.id, line)
            elif (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                  and t.value.id == "self" and cls):
                self._register(name or f"{cls}.{t.attr}", cls, t.attr, line)

    def _register(self, lock_id: str, cls: Optional[str], attr: str, line: int) -> None:
        self.locks[lock_id] = LockDef(lock_id=lock_id, file=self.rel, line=line,
                                      cls=cls, attr=attr)

    def holds_lock_annotation(self, fn: ast.FunctionDef) -> List[str]:
        if 1 <= fn.lineno <= len(self.lines):
            m = HOLDS_LOCK_RE.search(self.lines[fn.lineno - 1])
            if m:
                return [m.group(1)]
        return []


class _FunctionWalker(ast.NodeVisitor):
    """Walks one function body tracking held locks; fills a FuncSummary
    and emits direct findings (blocking-under-lock, unguarded mutation)."""

    def __init__(self, analyzer: "_Analyzer", mod: _ModuleIndex,
                 cls: Optional[str], fn: ast.FunctionDef, summary: FuncSummary,
                 initial_holds: List[str]):
        self.a = analyzer
        self.mod = mod
        self.cls = cls
        self.fn = fn
        self.s = summary
        self.held: List[str] = list(initial_holds)
        self.findings: List[Finding] = []

    # --- lock resolution -------------------------------------------------
    def _resolve_lock(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            for lid, d in self.mod.locks.items():
                if d.cls is None and d.attr == expr.id:
                    return lid
            return self.a.unique_lock_by_attr(expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" and self.cls:
                for lid, d in self.a.locks.items():
                    if d.cls == self.cls and d.attr == expr.attr:
                        return lid
            return self.a.unique_lock_by_attr(expr.attr)
        return None

    def _acquire(self, lock_id: str, line: int) -> None:
        for held in self.held:
            if held != lock_id:
                self.a.add_edge(held, lock_id, self.mod.rel, self.s.symbol, line)
        self.s.acquires.add(lock_id)
        self.held.append(lock_id)

    # --- visitors --------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            expr = item.context_expr
            # `with self._lock:` or `with lock:`; `with self._cv:` too.
            lid = self._resolve_lock(expr)
            if lid is None and isinstance(expr, ast.Call):
                # with self._lock.acquire_timeout(...) style — resolve receiver
                if isinstance(expr.func, ast.Attribute):
                    lid = self._resolve_lock(expr.func.value)
            if lid is not None:
                self._acquire(lid, node.lineno)
                acquired.append(lid)
            else:
                self.visit(expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # Nested defs (incl. closures handed to threads) are walked with a
        # fresh held-set: they run later, not under the current locks.
        self.a.walk_function(self.mod, self.cls, node,
                             symbol=f"{self.s.symbol}.<{node.name}>", nested=True)

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = lambda self, node: None  # noqa: E731 — skip lambda bodies

    def visit_Call(self, node: ast.Call) -> None:
        desc = self._blocking_desc(node)
        if desc is not None:
            self.s.blocking.append((desc, node.lineno))
            if self.held:
                self.findings.append(Finding(
                    rule="blocking-under-lock",
                    file=self.mod.rel, line=node.lineno, symbol=self.s.symbol,
                    message=f"blocking call {desc} while holding {', '.join(self.held)}",
                    detail=f"{desc} under {'+'.join(sorted(set(self.held)))}",
                ))
        callee = self._resolve_callee(node)
        if callee is not None:
            self.s.calls.append((callee, node.lineno, tuple(self.held)))
        # guarded-by: mutating method calls like self.X.append(...)
        self._check_mutator_call(node)
        self.generic_visit(node)

    def _blocking_desc(self, node: ast.Call) -> Optional[str]:
        kwnames = {kw.arg for kw in node.keywords if kw.arg}
        fname = _call_name(node.func)
        if fname is None:
            return None
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if fname == "sleep" and isinstance(recv, ast.Name) and recv.id == "time":
                return "time.sleep"
            if fname in ALWAYS_BLOCKING_ATTRS and "timeout" not in kwnames:
                return f".{fname}()"
            if fname in NETWORK_VERBS and "timeout" not in kwnames:
                return f".{fname}()"
            if (fname in TIMEOUT_GATED_ATTRS and not node.args
                    and not kwnames & {"timeout", "block"}):
                return f".{fname}() without timeout"
        elif isinstance(node.func, ast.Name):
            if fname == "sleep":
                return "sleep"
            if fname in {"urlopen", "probe_and_seed"}:
                return fname
        return None

    def _resolve_callee(self, node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in self.mod.functions:
                return f"{self.mod.rel}::{f.id}"
            if f.id in self.mod.classes and (f.id, "__init__") in self.mod.methods:
                return f"{self.mod.rel}::{f.id}.__init__"
            return None
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and self.cls):
            if (self.cls, f.attr) in self.mod.methods:
                return f"{self.mod.rel}::{self.cls}.{f.attr}"
        return None

    # --- guarded-by ------------------------------------------------------
    def _guard_for(self, attr: str) -> Optional[GuardRule]:
        if not self.cls:
            return None
        for g in self.a.guards:
            if g.cls == self.cls and g.attr == attr:
                return g
        return None

    def _guard_lock_id(self, g: GuardRule) -> Optional[str]:
        if g.confined:
            return None
        if "." in g.guard:
            return g.guard
        for lid, d in self.a.locks.items():
            if d.cls == g.cls and d.attr == g.guard:
                return lid
        return g.guard  # unresolved name — compare literally

    def _flag_mutation(self, attr: str, line: int, how: str) -> None:
        if self.fn.name == "__init__":
            return
        g = self._guard_for(attr)
        if g is None:
            return
        if g.confined:
            return  # in-class mutations trusted under confinement
        lid = self._guard_lock_id(g)
        if lid is not None and lid not in self.held:
            held = ", ".join(self.held) if self.held else "no lock"
            self.findings.append(Finding(
                rule="unguarded-mutation",
                file=self.mod.rel, line=line, symbol=self.s.symbol,
                message=f"{how} of self.{attr} (guarded-by {lid}) while holding {held}",
                detail=f"self.{attr} guarded-by {lid}",
            ))

    def _self_attr(self, expr: ast.expr) -> Optional[str]:
        if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return expr.attr
        return None

    def _check_store_target(self, t: ast.expr, line: int) -> None:
        attr = self._self_attr(t)
        if attr is not None:
            self._flag_mutation(attr, line, "assignment")
            return
        if isinstance(t, ast.Subscript):
            attr = self._self_attr(t.value)
            if attr is not None:
                self._flag_mutation(attr, line, "item store")
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._check_store_target(e, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_store_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_store_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._check_store_target(t, node.lineno)
        self.generic_visit(node)

    def _check_mutator_call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATOR_METHODS:
            attr = self._self_attr(f.value)
            if attr is not None:
                self._flag_mutation(attr, node.lineno, f".{f.attr}()")


class _Analyzer:
    def __init__(self, root: Optional[str]):
        self.root = root
        self.modules: List[_ModuleIndex] = []
        self.locks: Dict[str, LockDef] = {}
        self.guards: List[GuardRule] = []
        self.summaries: Dict[str, FuncSummary] = {}
        self.findings: List[Finding] = []
        # edge -> (file, symbol, line) of first sighting
        self.edges: Dict[Tuple[str, str], Tuple[str, str, int]] = {}
        self._attr_index: Dict[str, List[str]] = {}

    # -- setup ------------------------------------------------------------
    def load(self, paths: Sequence[str]) -> None:
        for path in _iter_py_files(paths):
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:
                self.findings.append(Finding(
                    rule="parse-error", file=_rel(path, self.root),
                    line=e.lineno or 0, symbol="<module>",
                    message=f"syntax error: {e.msg}", detail=str(e.msg)))
                continue
            mod = _ModuleIndex(path, _rel(path, self.root), tree, src.splitlines())
            self.modules.append(mod)
        for mod in self.modules:
            self.locks.update(mod.locks)
            self.guards.extend(mod.guards)
        self._attr_index.clear()
        for lid, d in self.locks.items():
            self._attr_index.setdefault(d.attr, []).append(lid)

    def unique_lock_by_attr(self, attr: str) -> Optional[str]:
        cands = self._attr_index.get(attr, [])
        return cands[0] if len(cands) == 1 else None

    def add_edge(self, a: str, b: str, file: str, symbol: str, line: int) -> None:
        self.edges.setdefault((a, b), (file, symbol, line))

    # -- function walking -------------------------------------------------
    def walk_function(self, mod: _ModuleIndex, cls: Optional[str],
                      fn: ast.FunctionDef, symbol: Optional[str] = None,
                      nested: bool = False) -> FuncSummary:
        symbol = symbol or (f"{cls}.{fn.name}" if cls else fn.name)
        key = f"{mod.rel}::{symbol}"
        if key in self.summaries:
            return self.summaries[key]
        s = FuncSummary(key=key, file=mod.rel, symbol=symbol, line=fn.lineno)
        self.summaries[key] = s
        holds = []
        for name in mod.holds_lock_annotation(fn):
            lid = name if "." in name else None
            if lid is None and cls:
                for cand, d in self.locks.items():
                    if d.cls == cls and d.attr == name:
                        lid = cand
                        break
            holds.append(lid or name)
        w = _FunctionWalker(self, mod, cls, fn, s, holds)
        for stmt in fn.body:
            w.visit(stmt)
        self.findings.extend(w.findings)
        if s.blocking:
            s.blocks = True
            s.blocks_via = s.blocking[0][0]
        return s

    def run(self) -> None:
        for mod in self.modules:
            for fname, fn in mod.functions.items():
                self.walk_function(mod, None, fn)
            for (cls, _m), fn in mod.methods.items():
                self.walk_function(mod, cls, fn)
        self._fixpoint()
        self._find_cycles()

    # -- interprocedural closure -----------------------------------------
    def _fixpoint(self) -> None:
        # Propagate (a) blocking-ness and (b) acquired locks up the call
        # graph, adding edges/findings at call sites that hold locks.
        changed = True
        reported: Set[Tuple[str, str, int]] = set()
        while changed:
            changed = False
            for s in self.summaries.values():
                for callee_key, line, held in s.calls:
                    callee = self.summaries.get(callee_key)
                    if callee is None:
                        continue
                    # transitive lock acquisition → order edges from held locks
                    for lid in callee.acquires:
                        if lid not in s.acquires:
                            s.acquires.add(lid)
                            changed = True
                        for h in held:
                            if h != lid and (h, lid) not in self.edges:
                                self.add_edge(h, lid, s.file, s.symbol, line)
                                changed = True
                    # transitive blocking under a held lock
                    if callee.blocks:
                        if not s.blocks:
                            s.blocks = True
                            s.blocks_via = f"{callee.symbol} → {callee.blocks_via}"
                            changed = True
                        if held:
                            sig = (s.key, callee_key, line)
                            if sig not in reported:
                                reported.add(sig)
                                self.findings.append(Finding(
                                    rule="blocking-under-lock",
                                    file=s.file, line=line, symbol=s.symbol,
                                    message=(f"call to {callee.symbol} (blocks via "
                                             f"{callee.blocks_via}) while holding "
                                             f"{', '.join(held)}"),
                                    detail=(f"{callee.symbol} under "
                                            f"{'+'.join(sorted(set(held)))}"),
                                ))

    # -- cycle detection --------------------------------------------------
    def _find_cycles(self) -> None:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        # Tarjan SCC
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        sccs: List[List[str]] = []

        def strong(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w_ in sorted(graph[v]):
                if w_ not in index:
                    strong(w_)
                    low[v] = min(low[v], low[w_])
                elif w_ in on:
                    low[v] = min(low[v], index[w_])
            if low[v] == index[v]:
                comp = []
                while True:
                    w_ = stack.pop()
                    on.discard(w_)
                    comp.append(w_)
                    if w_ == v:
                        break
                sccs.append(comp)

        for v in sorted(graph):
            if v not in index:
                strong(v)
        for comp in sccs:
            cyclic = len(comp) > 1 or (comp[0] in graph[comp[0]])
            if not cyclic:
                continue
            comp = sorted(comp)
            sites = []
            for (a, b), (file, sym, line) in sorted(self.edges.items()):
                if a in comp and b in comp:
                    sites.append((file, sym, line, a, b))
            file, sym, line = (sites[0][:3] if sites else ("<graph>", "<graph>", 0))
            edge_desc = "; ".join(f"{a}→{b} at {f}:{ln} ({s})" for f, s, ln, a, b in sites)
            self.findings.append(Finding(
                rule="lock-order-cycle", file=file, line=line, symbol=sym,
                message=f"lock-order cycle between {', '.join(comp)}: {edge_desc}",
                detail="cycle:" + "|".join(comp),
            ))


def analyze_paths(paths: Sequence[str], root: Optional[str] = None) -> Analysis:
    a = _Analyzer(root=root)
    a.load(list(paths))
    a.run()
    return Analysis(findings=sort_findings(a.findings),
                    edges=sorted(a.edges),
                    locks=a.locks,
                    guards=a.guards)
