"""Finding model shared by every analysis pass.

A finding's *fingerprint* deliberately excludes line numbers: it hashes
(rule, file, symbol, detail) so a baseline suppression survives unrelated
edits that shift lines, but goes stale the moment the offending code (or
its enclosing symbol) actually changes.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List


@dataclasses.dataclass
class Finding:
    rule: str       # e.g. "blocking-under-lock", "lock-order-cycle"
    file: str       # repo-relative posix path
    line: int       # 1-based; informational only (not fingerprinted)
    symbol: str     # enclosing "Class.method" / "function" / "<module>"
    message: str    # human-readable one-liner
    detail: str = ""  # stable discriminator (lock ids, callee, edge list)

    @property
    def fingerprint(self) -> str:
        blob = "|".join((self.rule, self.file, self.symbol, self.detail or self.message))
        return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "detail": self.detail,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.symbol}: {self.message}"


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.file, f.line, f.rule, f.detail))
