"""Runtime deadlock witness (``HOROVOD_DEBUG_LOCKS=1``).

``make_lock(name)`` is a drop-in replacement for ``threading.Lock()`` /
``threading.RLock()`` used by the runtime's own locks. With the knob off
it returns a plain stdlib lock — zero overhead, identical semantics.
With it on it returns a :class:`DebugLock` that:

* records per-thread acquisition stacks and the pairwise acquisition
  order actually observed, flagging ``lock-order-inversion`` the moment
  two locks are ever taken in both orders (with both stacks);
* flags ``self-deadlock`` (re-acquiring a non-reentrant lock on the same
  thread) by raising immediately instead of hanging forever;
* detects live waits-for cycles while blocked (``deadlock`` violation,
  recorded with every participant's stack — the witness keeps waiting so
  the hang is observable, it does not break the deadlock);
* warns on holds longer than ``HOROVOD_LOCK_HOLD_WARN_SECONDS``
  (default 5.0) via a watchdog thread (``lock-hold`` violation);
* emits ``lock_acquire`` / ``lock_hold`` events into the flight recorder
  and registers a ``locks`` state provider so crash dumps show who held
  what.

Lock names are chosen to match the static analyzer's ids
(``Class.attr``), so :func:`check_static_consistency` can assert the
static lock-order graph's claimed order against the runtime-observed
edges in tier-1 tests.

This module imports only the stdlib at top level; the flight recorder is
imported lazily inside emit paths to avoid import cycles.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Sequence, Set, Tuple

from horovod_tpu.utils.env import (DEFAULT_LOCK_HOLD_WARN_SECONDS,
                                   HOROVOD_DEBUG_LOCKS,
                                   HOROVOD_LOCK_HOLD_WARN_SECONDS,
                                   _get_bool, _get_float)

_DEADLOCK_POLL_SECONDS = 0.25


def enabled() -> bool:
    # Read at lock-creation time (not from Config): runtime locks can be
    # constructed before hvd.init() parses the Config.
    return _get_bool(HOROVOD_DEBUG_LOCKS)


def hold_warn_seconds() -> float:
    return _get_float(HOROVOD_LOCK_HOLD_WARN_SECONDS,
                      DEFAULT_LOCK_HOLD_WARN_SECONDS)


def _stack(skip: int = 2) -> str:
    return "".join(traceback.format_stack()[:-skip])


def _emit(kind: str, **fields) -> None:
    try:
        from horovod_tpu import flight_recorder
        flight_recorder.emit(kind, **fields)
    except Exception:
        pass


class _HeldRec:
    __slots__ = ("lock", "t_acquired", "stack", "warned")

    def __init__(self, lock: "DebugLock", t_acquired: float, stack: str):
        self.lock = lock
        self.t_acquired = t_acquired
        self.stack = stack
        self.warned = False


class _Witness:
    """Process-wide singleton. Its own plain mutex (never a DebugLock)
    guards all bookkeeping; emit/IO happens outside it."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (a, b) -> (thread_name, stack) of the first time b was acquired
        # while a was held.
        self._edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._violations: List[Dict[str, object]] = []
        self._held: Dict[int, List[_HeldRec]] = {}
        # tid -> lock currently blocked on
        self._waiting: Dict[int, "DebugLock"] = {}
        self._reported_cycles: Set[Tuple[str, ...]] = set()
        self._watchdog: Optional[threading.Thread] = None
        self._provider_registered = False

    # -- lifecycle --------------------------------------------------------
    def ensure_started(self) -> None:
        with self._mu:
            if self._watchdog is None or not self._watchdog.is_alive():
                t = threading.Thread(target=self._watch, name="hvd-lock-witness",
                                     daemon=True)
                self._watchdog = t
                t.start()
        self._register_provider()

    def _register_provider(self) -> None:
        if self._provider_registered:
            return
        try:
            from horovod_tpu import flight_recorder
            flight_recorder.set_state_provider("locks", self.debug_state)
            self._provider_registered = True
        except Exception:
            pass

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._violations.clear()
            self._reported_cycles.clear()
            # held/waiting reflect live lock state; don't clear them.

    # -- accessors --------------------------------------------------------
    def violations(self) -> List[Dict[str, object]]:
        with self._mu:
            return [dict(v) for v in self._violations]

    def order_edges(self) -> List[Tuple[str, str]]:
        with self._mu:
            return sorted(self._edges)

    def debug_state(self) -> Dict[str, object]:
        with self._mu:
            held = {
                str(tid): [{"lock": r.lock.name,
                            "held_s": round(time.monotonic() - r.t_acquired, 3)}
                           for r in recs]
                for tid, recs in self._held.items() if recs
            }
            return {
                "enabled": True,
                "held": held,
                "waiting": {str(t): l.name for t, l in self._waiting.items()},
                "edges": ["%s->%s" % e for e in sorted(self._edges)],
                "violations": len(self._violations),
            }

    def _add_violation(self, kind: str, message: str, **fields) -> None:
        v = {"kind": kind, "message": message}
        v.update(fields)
        self._violations.append(v)

    # -- acquisition tracking ---------------------------------------------
    def note_acquired(self, lock: "DebugLock", wait_s: float) -> None:
        tid = threading.get_ident()
        tname = threading.current_thread().name
        stack = _stack(skip=3)
        inversion = None
        with self._mu:
            recs = self._held.setdefault(tid, [])
            for rec in recs:
                a, b = rec.lock.name, lock.name
                if a == b:
                    continue
                if (a, b) not in self._edges:
                    self._edges[(a, b)] = (tname, stack)
                    rev = self._edges.get((b, a))
                    if rev is not None:
                        inversion = (a, b, rev)
            recs.append(_HeldRec(lock, time.monotonic(), stack))
            if inversion is not None:
                a, b, (rev_thread, rev_stack) = inversion
                self._add_violation(
                    "lock-order-inversion",
                    f"{a} -> {b} acquired on thread {tname} but {b} -> {a} "
                    f"was previously observed on thread {rev_thread}",
                    locks=[a, b], thread=tname,
                    stack=stack, prior_stack=rev_stack,
                )
        _emit("lock_acquire", lock=lock.name, thread=tname,
              wait_s=round(wait_s, 6))
        if inversion is not None:
            a, b, _ = inversion
            _emit("lock_order_inversion", first=a, second=b, thread=tname)

    def note_released(self, lock: "DebugLock") -> None:
        tid = threading.get_ident()
        hold_s = None
        with self._mu:
            recs = self._held.get(tid, [])
            for i in range(len(recs) - 1, -1, -1):
                if recs[i].lock is lock:
                    rec = recs.pop(i)
                    hold_s = time.monotonic() - rec.t_acquired
                    break
        if hold_s is not None and hold_s > hold_warn_seconds():
            tname = threading.current_thread().name
            with self._mu:
                self._add_violation(
                    "lock-hold",
                    f"{lock.name} held {hold_s:.2f}s on thread {tname} "
                    f"(warn threshold {hold_warn_seconds():.2f}s)",
                    lock=lock.name, thread=tname, hold_s=round(hold_s, 3),
                )
            _emit("lock_hold", lock=lock.name, thread=tname,
                  hold_s=round(hold_s, 3))

    # -- waits-for deadlock detection -------------------------------------
    def note_waiting(self, lock: "DebugLock") -> None:
        with self._mu:
            self._waiting[threading.get_ident()] = lock

    def note_wait_done(self) -> None:
        with self._mu:
            self._waiting.pop(threading.get_ident(), None)

    def check_deadlock(self) -> Optional[List[str]]:
        """Follow the waits-for chain from this thread; record a
        ``deadlock`` violation if it cycles back."""
        me = threading.get_ident()
        with self._mu:
            chain: List[int] = [me]
            locks: List[str] = []
            tid = me
            while True:
                lock = self._waiting.get(tid)
                if lock is None:
                    return None
                locks.append(lock.name)
                owner = lock.owner
                if owner is None:
                    return None
                if owner == me:
                    sig = tuple(sorted(set(locks)))
                    if sig in self._reported_cycles:
                        return locks
                    self._reported_cycles.add(sig)
                    stacks = {
                        str(t): [r.stack for r in self._held.get(t, [])][-1:]
                        for t in chain
                    }
                    self._add_violation(
                        "deadlock",
                        "waits-for cycle: " + " -> ".join(locks + [locks[0]]),
                        locks=sorted(set(locks)),
                        threads=[str(t) for t in chain],
                        stacks=stacks,
                    )
                    break
                if owner in chain:
                    return None  # cycle not through us; its members report it
                chain.append(owner)
                tid = owner
        _emit("lock_deadlock", locks=sorted(set(locks)))
        return locks

    # -- hold-time watchdog -----------------------------------------------
    def _watch(self) -> None:
        while True:
            time.sleep(max(0.2, min(1.0, hold_warn_seconds() / 2.0)))
            warn = hold_warn_seconds()
            now = time.monotonic()
            events = []
            with self._mu:
                for tid, recs in self._held.items():
                    for rec in recs:
                        held_s = now - rec.t_acquired
                        if held_s > warn and not rec.warned:
                            rec.warned = True
                            self._add_violation(
                                "lock-hold",
                                f"{rec.lock.name} held {held_s:.2f}s (still "
                                f"held) on thread {tid} (warn threshold "
                                f"{warn:.2f}s)",
                                lock=rec.lock.name, thread=str(tid),
                                hold_s=round(held_s, 3), stack=rec.stack,
                            )
                            events.append((rec.lock.name, tid, held_s))
            for name, tid, held_s in events:
                _emit("lock_hold", lock=name, thread=str(tid),
                      hold_s=round(held_s, 3), still_held=True)


_witness = _Witness()


class DebugLock:
    """Witness-instrumented lock. Context-manager compatible with
    ``threading.Lock`` / ``threading.RLock``."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.Lock()
        self.owner: Optional[int] = None
        self._depth = 0
        _witness.ensure_started()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self.owner == me:
            if self.reentrant:
                self._depth += 1
                return True
            raise RuntimeError(
                self._record_self_deadlock())
        if self._inner.acquire(blocking=False):
            self._on_acquired(me, 0.0)
            return True
        if not blocking:
            return False
        t0 = time.monotonic()
        deadline = None if timeout is None or timeout < 0 else t0 + timeout
        _witness.note_waiting(self)
        try:
            while True:
                step = _DEADLOCK_POLL_SECONDS
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    step = min(step, remaining)
                if self._inner.acquire(timeout=step):
                    self._on_acquired(me, time.monotonic() - t0)
                    return True
                _witness.check_deadlock()
        finally:
            _witness.note_wait_done()

    def _record_self_deadlock(self) -> str:
        msg = (f"self-deadlock: thread {threading.current_thread().name} "
               f"re-acquired non-reentrant lock {self.name}")
        with _witness._mu:
            _witness._add_violation("self-deadlock", msg, lock=self.name,
                                    thread=threading.current_thread().name,
                                    stack=_stack(skip=3))
        _emit("lock_self_deadlock", lock=self.name)
        return msg

    def _on_acquired(self, me: int, wait_s: float) -> None:
        self.owner = me
        self._depth = 1
        _witness.note_acquired(self, wait_s)

    def release(self) -> None:
        me = threading.get_ident()
        if self.owner != me:
            raise RuntimeError(f"release of {self.name} by non-owner thread")
        self._depth -= 1
        if self._depth > 0:
            return
        _witness.note_released(self)
        self.owner = None
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<DebugLock {self.name} owner={self.owner}>"


def make_lock(name: str, reentrant: bool = False):
    """Runtime lock factory: plain stdlib lock normally, DebugLock under
    ``HOROVOD_DEBUG_LOCKS=1``. ``name`` must match the static analyzer's
    id for the lock (``Class.attr``) — that is what lets tier-1 tests
    assert the static order graph against runtime observations."""
    if enabled():
        return DebugLock(name, reentrant=reentrant)
    return threading.RLock() if reentrant else threading.Lock()


def violations() -> List[Dict[str, object]]:
    return _witness.violations()


def order_edges() -> List[Tuple[str, str]]:
    return _witness.order_edges()


def reset() -> None:
    _witness.reset()


def check_static_consistency(
        static_edges: Sequence[Tuple[str, str]]) -> List[str]:
    """Compare runtime-observed lock-order edges against the static
    graph: an observed edge b→a whose reverse a→b is reachable in the
    static graph is a conflict (the static analysis claimed one order,
    the runtime exhibited the other)."""
    # transitive closure of the static graph
    adj: Dict[str, Set[str]] = {}
    for a, b in static_edges:
        adj.setdefault(a, set()).add(b)
    closure: Dict[str, Set[str]] = {}

    def reach(v: str) -> Set[str]:
        if v in closure:
            return closure[v]
        closure[v] = set()
        out: Set[str] = set()
        stack = [v]
        seen = {v}
        while stack:
            n = stack.pop()
            for m in adj.get(n, ()):
                if m not in seen:
                    seen.add(m)
                    out.add(m)
                    stack.append(m)
        closure[v] = out
        return out

    conflicts = []
    for b, a in order_edges():
        if b in reach(a):
            conflicts.append(
                f"runtime edge {b}->{a} contradicts static order {a}=>…=>{b}")
    return conflicts
