"""Autotuner package — reference: horovod/common/parameter_manager.cc and
optim/{bayesian_optimization,gaussian_process}.cc (SURVEY.md §2.1)."""

from horovod_tpu.autotune.bayesian_optimization import BayesianOptimization
from horovod_tpu.autotune.gaussian_process import GaussianProcessRegressor
from horovod_tpu.autotune.parameter_manager import ParameterManager, Params

__all__ = ["BayesianOptimization", "GaussianProcessRegressor",
           "ParameterManager", "Params"]
