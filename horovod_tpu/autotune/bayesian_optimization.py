"""Bayesian optimization with Expected Improvement.

TPU-native analogue of the reference's BO (reference:
horovod/common/optim/bayesian_optimization.cc:34-80): an Expected
Improvement acquisition over the GP posterior, maximized by multi-restart
gradient optimization (the reference uses vendored L-BFGS; here
scipy.optimize L-BFGS-B, which is the same algorithm).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
from scipy import optimize
from scipy.stats import norm

from horovod_tpu.autotune.gaussian_process import GaussianProcessRegressor


class BayesianOptimization:
    """Maximizes an unknown f over a box via EI (reference:
    bayesian_optimization.h — NextSample/AddSample surface)."""

    def __init__(self, bounds, alpha: float = 1e-8, xi: float = 0.01,
                 n_restarts: int = 16, seed: int = 0):
        self.bounds = np.asarray(bounds, dtype=np.float64)  # (d, 2)
        assert self.bounds.ndim == 2 and self.bounds.shape[1] == 2
        self.dim = len(self.bounds)
        self.xi = xi
        self.n_restarts = n_restarts
        self._gp = GaussianProcessRegressor(alpha=alpha)
        self._X: List[np.ndarray] = []
        self._y: List[float] = []
        self._rng = np.random.RandomState(seed)

    # -- sample bookkeeping -------------------------------------------------
    def add_sample(self, x, y: float) -> None:
        self._X.append(np.asarray(x, dtype=np.float64).ravel())
        self._y.append(float(y))

    @property
    def n_samples(self) -> int:
        return len(self._X)

    def best(self) -> Optional[tuple]:
        if not self._y:
            return None
        i = int(np.argmax(self._y))
        return self._X[i], self._y[i]

    # -- normalized coordinates (unit box) ----------------------------------
    def _to_unit(self, x: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / np.maximum(hi - lo, 1e-12)

    def _from_unit(self, u: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + u * (hi - lo)

    def _expected_improvement(self, U: np.ndarray, f_best: float
                              ) -> np.ndarray:
        mu, sigma = self._gp.predict(U)
        imp = mu - f_best - self.xi
        z = imp / sigma
        return imp * norm.cdf(z) + sigma * norm.pdf(z)

    def next_sample(self) -> np.ndarray:
        """Next point to evaluate: random while under-sampled, else the EI
        maximum from L-BFGS-B restarts at random unit-box starts
        (reference: bayesian_optimization.cc:34-80)."""
        if self.n_samples < max(2, self.dim):
            return self._from_unit(self._rng.uniform(size=self.dim))

        U = np.array([self._to_unit(x) for x in self._X])
        self._gp.fit(U, np.array(self._y))
        f_best = max(self._y)

        def neg_ei(u):
            return -float(self._expected_improvement(u[None, :], f_best)[0])

        best_u, best_v = None, np.inf
        starts = self._rng.uniform(size=(self.n_restarts, self.dim))
        for u0 in starts:
            res = optimize.minimize(
                neg_ei, u0, method="L-BFGS-B",
                bounds=[(0.0, 1.0)] * self.dim)
            if res.fun < best_v:
                best_v, best_u = res.fun, res.x
        if best_u is None:  # all restarts failed — fall back to random
            best_u = self._rng.uniform(size=self.dim)
        return self._from_unit(np.clip(best_u, 0.0, 1.0))
