"""Gaussian-process regression for the autotuner.

TPU-native analogue of the reference's GP (reference:
horovod/common/optim/gaussian_process.cc/.h:46-78 — RBF kernel, Cholesky
fit, posterior mean/std predict, used by Expected Improvement). The
reference implements this in C++ on Eigen; here it is ~60 lines of numpy —
the matrices are tiny (tens of samples, 2-3 dims), so there is nothing for
native code to win.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class GaussianProcessRegressor:
    """GP with an RBF kernel and additive observation noise.

    ``alpha`` is the noise regularization added to the kernel diagonal
    (reference: the GP noise knob HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE
    scales this).
    """

    def __init__(self, alpha: float = 1e-8, length_scale: float = 1.0,
                 signal_variance: float = 1.0):
        self.alpha = alpha
        self.length_scale = length_scale
        self.signal_variance = signal_variance
        self._X: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0
        self._chol: Optional[np.ndarray] = None
        self._alpha_vec: Optional[np.ndarray] = None

    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        # squared-exponential: k(a,b) = s2 * exp(-||a-b||^2 / (2 l^2))
        d2 = (np.sum(A * A, axis=1)[:, None] + np.sum(B * B, axis=1)[None, :]
              - 2.0 * A @ B.T)
        np.maximum(d2, 0.0, out=d2)
        return self.signal_variance * np.exp(
            -0.5 * d2 / (self.length_scale ** 2))

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64).ravel()
        # normalize targets so the fixed kernel amplitude is reasonable
        self._y_mean = float(np.mean(y))
        self._y_std = float(np.std(y)) or 1.0
        yn = (y - self._y_mean) / self._y_std
        K = self._kernel(X, X)
        K[np.diag_indices_from(K)] += self.alpha
        self._chol = np.linalg.cholesky(K)
        self._alpha_vec = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, yn))
        self._X = X

    def predict(self, X: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and standard deviation at ``X`` (denormalized)."""
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        if self._X is None:
            return (np.zeros(len(X)) + self._y_mean,
                    np.full(len(X), np.sqrt(self.signal_variance)))
        Ks = self._kernel(X, self._X)
        mu = Ks @ self._alpha_vec
        v = np.linalg.solve(self._chol, Ks.T)
        var = self.signal_variance - np.sum(v * v, axis=0)
        np.maximum(var, 1e-12, out=var)
        return (mu * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)
