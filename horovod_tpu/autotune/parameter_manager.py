"""Autotuning parameter manager.

TPU-native analogue of the reference's ``ParameterManager`` (reference:
horovod/common/parameter_manager.cc/.h:225-251): while training runs, try
different runtime knob settings, score each by negotiation+collective
throughput (bytes/µs — reference: parameter_manager.cc:142-176), and
converge on the best.

Tuned knobs (reference: parameter_manager.h:225-228):
* categorical — ``cache_enabled``, ``hierarchical_allreduce``,
  ``hierarchical_allgather``;
* continuous, jointly via Bayesian optimization —
  ``fusion_threshold_mb`` and ``cycle_time_ms``.

Tuning schedule (a simplification of the reference's nested tunable-param
chain, same spirit): warmup discard → one-at-a-time sweep of each
categorical value → Bayesian optimization over the continuous box →
freeze at the best configuration seen. Scores are medians over
``SAMPLES_PER_POINT`` samples of ``steps_per_sample`` update calls each
(reference: 5-sample medians, 10 steps per sample).

Only the coordinator tunes; every cycle it broadcasts the current
parameter blob and all workers apply it (reference: SynchronizeParameters,
controller.cc:32-46).
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import List, Optional

import numpy as np

from horovod_tpu.autotune.bayesian_optimization import BayesianOptimization

SAMPLES_PER_POINT = 5  # reference: parameter_manager.cc five-sample medians

# continuous search box: fusion threshold (MB), cycle time (ms),
# grad-bucket payload (MB), cycle pipeline depth
FUSION_MB_BOUNDS = (0.0, 64.0)
CYCLE_MS_BOUNDS = (1.0, 25.0)
BUCKET_MB_BOUNDS = (1.0, 64.0)
DEPTH_BOUNDS = (1.0, 4.0)
# ZeRO-3 gather prefetch window (buckets in flight ahead of consumption);
# deeper hides more gather latency, shallower bounds transient HBM
PREFETCH_BOUNDS = (1.0, 8.0)

# Slow-hop wire codecs for the hierarchical cross-group exchange, in
# packed-byte order (index = the byte in the sync blob). Must stay
# append-only: renumbering would desynchronize mixed-commit workers
# mid-rolling-restart.
COMPRESSION_CODECS = ("none", "fp16", "ieee_fp16")


@dataclasses.dataclass
class Params:
    """The synchronized knob set (reference: the POD Params struct bcast by
    SynchronizeParameters)."""

    fusion_threshold_bytes: int
    cycle_time_ms: float
    cache_enabled: bool
    hierarchical_allreduce: bool
    hierarchical_allgather: bool
    active: bool = True  # still tuning?
    # hierarchy split + slow-hop codec + the throughput knobs the rebooted
    # tuner drives; defaulted so pre-reboot call sites construct unchanged
    hierarchy_group_size: int = 0        # 0 = host-derived grouping
    hierarchy_compression: str = "none"  # cross-group wire codec
    grad_bucket_bytes: int = 0           # 0 = keep the configured value
    cycle_pipeline_depth: int = 0        # 0 = keep the configured value
    zero_prefetch_buckets: int = 0       # 0 = keep the configured value

    _FMT = "<qdBBBBBBqBB"

    def pack(self) -> bytes:
        codec = COMPRESSION_CODECS.index(
            normalize_codec(self.hierarchy_compression))
        return struct.pack(
            self._FMT, self.fusion_threshold_bytes, self.cycle_time_ms,
            int(self.cache_enabled), int(self.hierarchical_allreduce),
            int(self.hierarchical_allgather), int(self.active),
            min(255, max(0, int(self.hierarchy_group_size))), codec,
            int(self.grad_bucket_bytes),
            min(255, max(0, int(self.cycle_pipeline_depth))),
            min(255, max(0, int(self.zero_prefetch_buckets))))

    @classmethod
    def unpack(cls, blob: bytes) -> "Params":
        (f, c, ce, ha, hg, act, gsz, codec, bkt,
         depth, prefetch) = struct.unpack(cls._FMT, blob)
        codec_name = (COMPRESSION_CODECS[codec]
                      if codec < len(COMPRESSION_CODECS) else "none")
        return cls(f, c, bool(ce), bool(ha), bool(hg), bool(act),
                   hierarchy_group_size=gsz,
                   hierarchy_compression=codec_name,
                   grad_bucket_bytes=bkt, cycle_pipeline_depth=depth,
                   zero_prefetch_buckets=prefetch)


# Default swept categorical knobs. The hierarchical flags join the sweep
# only when the runtime's data plane actually consults them (two-level
# mesh, or a host ring wide enough to split into >= 2 groups of >= 2) —
# sweeping a no-op knob would just burn sample windows on noise.
_CATEGORICAL = ("cache_enabled",)

# env.py accepts spelling variants for the codec knob; the packed blob
# and the sweep work over the canonical names only
_CODEC_ALIASES = {"": "none", "off": "none", "bf16": "fp16",
                  "bfloat16": "fp16", "float16": "ieee_fp16",
                  "f16": "ieee_fp16"}


def normalize_codec(name) -> str:
    """Canonical ``COMPRESSION_CODECS`` member for any accepted codec
    spelling; unknown names fail open to ``"none"``."""
    name = str(name or "none").strip().lower()
    name = _CODEC_ALIASES.get(name, name)
    return name if name in COMPRESSION_CODECS else "none"


# Value pairs per categorical knob; knobs not listed sweep (False, True).
# The codec sweep tries the bf16-wire codec only: ieee_fp16 has the same
# wire width, so on throughput it is indistinguishable and scoring it
# separately would double the sample cost of the phase for nothing.
_CATEGORICAL_VALUES = {"hierarchy_compression": ("none", "fp16")}


def search_box_from_roofline(roofline) -> list:
    """Seed the Bayesian search box from a probe-cache artifact.

    With measured hop bandwidth the payload-sized boxes shrink to what
    the slowest lane can actually move in one maximum-length cycle
    (GB/s x ms = MB), so early BO samples don't burn cycles probing
    bucket/fusion sizes the wire provably cannot drain in time. Without
    an artifact (or a pre-hierarchy schema) the static defaults stand.
    """
    box = [FUSION_MB_BOUNDS, CYCLE_MS_BOUNDS, BUCKET_MB_BOUNDS,
           DEPTH_BOUNDS, PREFETCH_BOUNDS]
    if not roofline:
        return box
    bw = (roofline.get("hier_cross_busbw_gbps")
          or roofline.get("allreduce_busbw_gbps"))
    if not bw or bw <= 0:
        return box
    cap_mb = bw * CYCLE_MS_BOUNDS[1]
    cap_mb = max(BUCKET_MB_BOUNDS[0] * 2.0,
                 min(BUCKET_MB_BOUNDS[1], cap_mb))
    box[0] = (FUSION_MB_BOUNDS[0], min(FUSION_MB_BOUNDS[1], cap_mb))
    box[2] = (BUCKET_MB_BOUNDS[0], cap_mb)
    return box


class ParameterManager:
    """Coordinator-side tuner; workers just apply broadcast params."""

    def __init__(self, initial: Params, warmup_samples: int = 3,
                 steps_per_sample: int = 10, bayes_opt_max_samples: int = 20,
                 gp_noise: float = 0.8, log_path: str = "",
                 rank: int = 0, sweep: tuple = _CATEGORICAL,
                 bounds: Optional[list] = None):
        # an empty sweep (e.g. cache disabled via capacity 0 and no
        # two-level mesh) skips the categorical phase entirely
        self._sweep = tuple(sweep)
        self.current = dataclasses.replace(initial)
        self.best = dataclasses.replace(initial)
        self.best_score = -np.inf
        self.active = True
        self._warmup_remaining = warmup_samples
        self._steps_per_sample = max(steps_per_sample, 1)
        self._log_path = log_path
        self._rank = rank

        # accumulation state
        self._step_count = 0
        self._bytes = 0
        self._seconds = 0.0
        self._busbw: List[float] = []  # per-step comms busbw hints (GB/s)
        self._scores: List[float] = []

        # tuning schedule state
        self._phase = "categorical"
        self._cat_index = 0       # which categorical knob
        self._cat_pos = 0         # which of the knob's values is scored
        self._cat_scores: dict = {}
        if self._sweep:
            # the first scored point must actually RUN the value it is
            # labeled with — apply it now rather than scoring the default
            # under a mismatched label
            knob = self._sweep[0]
            setattr(self.current, knob, self._values_of(knob)[0])
        else:
            self._phase = "bayesian"
        # search box: caller-seeded (probe-cache rooflines via
        # search_box_from_roofline) or the static defaults
        self._bounds = list(bounds) if bounds else [
            FUSION_MB_BOUNDS, CYCLE_MS_BOUNDS, BUCKET_MB_BOUNDS,
            DEPTH_BOUNDS, PREFETCH_BOUNDS]
        if len(self._bounds) < 5:
            # pre-ZeRO-3 caller-seeded box — extend rather than crash
            self._bounds.append(PREFETCH_BOUNDS)
        self._bo = BayesianOptimization(
            bounds=self._bounds,
            alpha=max(gp_noise, 1e-6) * 1e-2)
        self._bo_remaining = bayes_opt_max_samples

        # Every artifact names the knobs actually IN the sweep (r4 review
        # weak #5: the hierarchical knobs silently leave the sweep on the
        # socket data plane — correct, but only discoverable by reading
        # the runtime constructor; the reference logs each trial's full
        # param vector, parameter_manager.cc:256-307). Continuous knobs
        # are always swept by the Bayesian phase; categoricals only when
        # the data plane consults them.
        self.swept_knobs = ("fusion_threshold_mb", "cycle_time_ms",
                            "grad_bucket_mb", "pipeline_depth",
                            "zero_prefetch_buckets") + self._sweep
        if self._rank == 0:  # coordinator only, like the CSV below
            from horovod_tpu.utils.logging import get_logger
            get_logger().info(
                "autotune: sweeping %s (categorical knobs not listed are "
                "frozen at their configured values on this data plane)",
                ",".join(self.swept_knobs))
        if self._log_path and self._rank == 0:
            with open(self._log_path, "w") as f:
                f.write("# swept: " + ",".join(self.swept_knobs) + "\n")
                f.write("timestamp,fusion_threshold_mb,cycle_time_ms,"
                        "cache_enabled,hierarchical_allreduce,"
                        "hierarchical_allgather,hierarchy_group_size,"
                        "hierarchy_compression,grad_bucket_mb,"
                        "pipeline_depth,zero_prefetch_buckets,"
                        "score_bytes_per_us\n")

    @staticmethod
    def _values_of(knob: str) -> tuple:
        return _CATEGORICAL_VALUES.get(knob, (False, True))

    # ------------------------------------------------------------------
    def update(self, nbytes: int, seconds: float,
               busbw_gbs: Optional[float] = None) -> bool:
        """Record one cycle's traffic; returns True when params changed
        (reference: ParameterManager::Update, parameter_manager.cc:142-176).

        ``busbw_gbs`` is the comms plane's smoothed bus bandwidth for the
        cycle (GB/s). When provided, the sample score blends end-to-end
        throughput with wire utilization equally — both are
        bytes-per-microsecond-dimensioned (1 GB/s = 1000 B/us), and a
        knob change that genuinely helps moves both the same direction,
        while one that merely shifts cost between negotiation and the
        wire shows up as the two components disagreeing.
        """
        if not self.active:
            return False
        if nbytes <= 0:
            # idle cycle — the socket controllers sync every cycle even
            # with nothing enqueued; scoring those would measure the cycle
            # cadence, not the knobs (reference advances only on tensor
            # traffic, parameter_manager.cc:142-160)
            return False
        self._bytes += int(nbytes)
        self._seconds += float(seconds)
        if busbw_gbs is not None and busbw_gbs > 0:
            self._busbw.append(float(busbw_gbs))
        self._step_count += 1
        if self._step_count < self._steps_per_sample:
            return False
        # one sample
        score = (self._bytes / (self._seconds * 1e6)
                 if self._seconds > 0 else 0.0)
        if self._busbw:
            score = 0.5 * score + 0.5 * float(np.mean(self._busbw)) * 1000.0
        self._step_count = 0
        self._bytes = 0
        self._seconds = 0.0
        self._busbw.clear()

        if self._warmup_remaining > 0:
            self._warmup_remaining -= 1
            return False
        self._scores.append(score)
        if len(self._scores) < SAMPLES_PER_POINT:
            return False
        point_score = float(np.median(self._scores))
        self._scores.clear()
        return self._tune(point_score)

    # ------------------------------------------------------------------
    def _log(self, score: float) -> None:
        if not self._log_path or self._rank != 0:
            return
        with open(self._log_path, "a") as f:
            c = self.current
            f.write(f"{time.time():.3f},"
                    f"{c.fusion_threshold_bytes / (1024 * 1024):.3f},"
                    f"{c.cycle_time_ms:.3f},{int(c.cache_enabled)},"
                    f"{int(c.hierarchical_allreduce)},"
                    f"{int(c.hierarchical_allgather)},"
                    f"{int(c.hierarchy_group_size)},"
                    f"{c.hierarchy_compression},"
                    f"{c.grad_bucket_bytes / (1024 * 1024):.3f},"
                    f"{int(c.cycle_pipeline_depth)},"
                    f"{int(c.zero_prefetch_buckets)},{score:.3f}\n")

    def _record(self, score: float) -> None:
        self._log(score)
        if score > self.best_score:
            self.best_score = score
            self.best = dataclasses.replace(self.current)

    def _tune(self, score: float) -> bool:
        """Advance the schedule; returns True when current params changed
        (reference: ParameterManager::Tune)."""
        self._record(score)

        if self._phase == "categorical":
            knob = self._sweep[self._cat_index]
            values = self._values_of(knob)
            self._cat_scores[(knob, values[self._cat_pos])] = score
            if self._cat_pos + 1 < len(values):
                # score the next value
                self._cat_pos += 1
                setattr(self.current, knob, values[self._cat_pos])
                return True
            # all values scored — keep the best, move to next knob
            best_val = max(values,
                           key=lambda v: self._cat_scores[(knob, v)])
            setattr(self.current, knob, best_val)
            self._cat_index += 1
            self._cat_pos = 0
            if self._cat_index >= len(self._sweep):
                self._phase = "bayesian"
                nxt = self._bo.next_sample()
                self._apply_continuous(nxt)
            else:
                nxt_knob = self._sweep[self._cat_index]
                setattr(self.current, nxt_knob,
                        self._values_of(nxt_knob)[0])
            return True

        if self._phase == "bayesian":
            x = np.array([
                self.current.fusion_threshold_bytes / (1024.0 * 1024.0),
                self.current.cycle_time_ms,
                max(self._bounds[2][0],
                    self.current.grad_bucket_bytes / (1024.0 * 1024.0)),
                max(self._bounds[3][0],
                    float(self.current.cycle_pipeline_depth)),
                max(self._bounds[4][0],
                    float(self.current.zero_prefetch_buckets))])
            self._bo.add_sample(x, score)
            self._bo_remaining -= 1
            if self._bo_remaining <= 0:
                self._finish()
                return True
            self._apply_continuous(self._bo.next_sample())
            return True

        return False

    def _apply_continuous(self, x) -> None:
        self.current.fusion_threshold_bytes = int(
            max(0.0, float(x[0])) * 1024 * 1024)
        self.current.cycle_time_ms = float(np.clip(
            x[1], CYCLE_MS_BOUNDS[0], CYCLE_MS_BOUNDS[1]))
        self.current.grad_bucket_bytes = int(float(np.clip(
            x[2], self._bounds[2][0],
            self._bounds[2][1])) * 1024 * 1024)
        self.current.cycle_pipeline_depth = int(round(float(np.clip(
            x[3], DEPTH_BOUNDS[0], DEPTH_BOUNDS[1]))))
        self.current.zero_prefetch_buckets = int(round(float(np.clip(
            x[4], PREFETCH_BOUNDS[0], PREFETCH_BOUNDS[1]))))

    def _finish(self) -> None:
        """Freeze at the best configuration seen (reference: tuning ends and
        best params stick; logged for resume-with-tuned-flags,
        docs/autotune.rst:30-37)."""
        self.current = dataclasses.replace(self.best)
        self.current.active = False
        self.active = False
        self._log(self.best_score)

    # ------------------------------------------------------------------
    def params(self) -> Params:
        p = dataclasses.replace(self.current)
        p.active = self.active
        return p
