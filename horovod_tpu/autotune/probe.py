"""Hardware bandwidth probes seeding the autotuner.

The north star for the TPU rebuild keeps the reference's response-cache /
fusion-buffer / autotuner design "backed by TPU HBM and ICI bandwidth
probes" (BASELINE.json; the reference itself starts from a fixed 64 MB
threshold, reference: operations.cc:379). These probes measure the actual
machine once at startup and turn the measurement into a principled initial
fusion threshold: fuse at most what the interconnect can reduce within a
set fraction of one cycle, so the first autotune samples start near the
right region instead of at a hardware-blind constant.

Timing protocol: K iterations chained inside ONE jitted program (data
dependency between iterations), wall-clocked against a scalar readback —
the only reliable protocol through remote-dispatch tunnels, where
``block_until_ready`` can return early and repeated identical dispatches
are served from a cache (see docs/benchmarks.md).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.core import mesh as mesh_mod


def _timed_scalar(fn, *args) -> float:
    """Wall-clock one compiled call ending in a scalar readback."""
    t0 = time.perf_counter()
    float(fn(*args))
    return time.perf_counter() - t0


def _per_iter_time(make_chain, x, lo: int, hi: int,
                   repeats: int = 3) -> float:
    """Difference-quotient timing: build chain(lo) and chain(hi), take
    min over repeats of each, return (t_hi - t_lo) / (hi - lo). Cancels
    the constant dispatch/readback overhead that dominates through
    remote-dispatch tunnels (docs/benchmarks.md measurement protocol).
    ``x`` stays a traced argument so XLA cannot constant-fold the chain.
    """
    hi = max(hi, lo + 1)
    c_lo, c_hi = make_chain(lo), make_chain(hi)
    _timed_scalar(c_lo, x)  # compile + warm
    _timed_scalar(c_hi, x)
    t_lo = min(_timed_scalar(c_lo, x) for _ in range(repeats))
    t_hi = min(_timed_scalar(c_hi, x) for _ in range(repeats))
    return max((t_hi - t_lo) / (hi - lo), 1e-9)


def probe_hbm_bandwidth(size_mb: int = 64, iters: int = 16) -> float:
    """Sustained single-device HBM copy bandwidth in GB/s (read + write).

    A chained scale-by-~one copy: each iteration reads and writes the
    buffer once, so bytes moved per iteration = 2 * size.
    """
    n = size_mb * (1 << 20) // 4
    x = jnp.ones((n,), jnp.float32)
    k = jnp.float32(1.0000001)

    def make_chain(length):
        @jax.jit
        def chain(v):
            def body(c, _):
                return c * k, None

            out, _ = jax.lax.scan(body, v, None, length=length)
            return out[0]

        return chain

    dt = _per_iter_time(make_chain, x, max(1, iters // 4), iters)
    return 2.0 * x.nbytes / dt / 1e9


def probe_allreduce_bandwidth(mesh=None, size_mb: int = 32,
                              iters: int = 8) -> float:
    """Algorithm bandwidth (input bytes / time) of a full-mesh all-reduce
    in GB/s — the ICI number that bounds fused-collective latency. On a
    1-device mesh this degenerates to an HBM-bound pass, which is the
    right bound there too."""
    from horovod_tpu.core import basics

    if mesh is None:
        mesh = basics._ensure_init().mesh
    n = size_mb * (1 << 20) // 4
    repl = NamedSharding(mesh, P())
    x = jax.device_put(jnp.ones((n,), jnp.float32), repl)
    inv = jnp.float32(1.0 / mesh.size)

    def make_chain(length):
        @jax.jit
        def chain(w):
            def inner(v):
                def step(c, _):
                    s = jax.lax.psum(c, mesh_mod.GLOBAL_AXES)
                    return s * inv, None

                out, _ = jax.lax.scan(step, v, None, length=length)
                return out

            y = jax.shard_map(inner, mesh=mesh, in_specs=P(), out_specs=P(),
                              check_vma=False)(w)
            return y[0]

        return chain

    dt = _per_iter_time(make_chain, x, max(1, iters // 4), iters)
    return x.nbytes / dt / 1e9


def recommended_fusion_threshold(allreduce_gbps: float,
                                 cycle_time_ms: float,
                                 cycle_fraction: float = 0.5,
                                 floor_bytes: int = 1 << 20,
                                 ceil_bytes: int = 256 << 20,
                                 hbm_gbps: Optional[float] = None) -> int:
    """Fusion threshold such that reducing one full fused buffer takes at
    most ``cycle_fraction`` of a cycle at the probed bandwidth — big
    enough to amortize launch overhead, small enough that fused
    collectives don't starve the cycle cadence (the trade the reference's
    autotuner searches for blindly, reference: parameter_manager.h:225).

    The effective rate is capped by HBM when given: a fused collective
    also packs and unpacks the buffer through HBM (one read + one write
    each way), so the wire can never be fed faster than ``hbm/2``.
    """
    rate = allreduce_gbps
    if hbm_gbps is not None:
        rate = min(rate, hbm_gbps / 2.0)
    budget_s = cycle_time_ms * 1e-3 * cycle_fraction
    threshold = int(rate * 1e9 * budget_s)
    return max(floor_bytes, min(ceil_bytes, threshold))


def probe_and_seed(config, mesh=None) -> dict:
    """Run the probes and seed ``config.fusion_threshold_bytes``; returns
    the measurements. Called at runtime startup when
    ``HOROVOD_AUTOTUNE_PROBE`` is on. Must run on EVERY process in a
    multi-controller (jax.distributed) world — the probe programs execute
    over the global mesh, which all processes must enter together; the
    coordinator's seeded value then wins via the per-cycle parameter
    broadcast, so probe noise cannot diverge the workers."""
    hbm = probe_hbm_bandwidth()
    ar = probe_allreduce_bandwidth(mesh)
    threshold = recommended_fusion_threshold(ar, config.cycle_time_ms,
                                             hbm_gbps=hbm)
    config.fusion_threshold_bytes = threshold
    return {"hbm_gbps": hbm, "allreduce_gbps": ar,
            "fusion_threshold_bytes": threshold}
