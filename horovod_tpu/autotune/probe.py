"""Hardware bandwidth probes seeding the autotuner.

The north star for the TPU rebuild keeps the reference's response-cache /
fusion-buffer / autotuner design "backed by TPU HBM and ICI bandwidth
probes" (BASELINE.json; the reference itself starts from a fixed 64 MB
threshold, reference: operations.cc:379). These probes measure the actual
machine once at startup and turn the measurement into a principled initial
fusion threshold: fuse at most what the interconnect can reduce within a
set fraction of one cycle, so the first autotune samples start near the
right region instead of at a hardware-blind constant.

Timing protocol: K iterations chained inside ONE jitted program (data
dependency between iterations), wall-clocked against a scalar readback —
the only reliable protocol through remote-dispatch tunnels, where
``block_until_ready`` can return early and repeated identical dispatches
are served from a cache (see docs/benchmarks.md).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.core import mesh as mesh_mod

log = logging.getLogger("horovod_tpu")

HOROVOD_PROBE_CACHE = "HOROVOD_PROBE_CACHE"

# persisted roofline artifact schema (bumped on incompatible change;
# a mismatched schema simply re-probes). v2: the hierarchy's two socket
# hops are probed separately (``hier_intra_busbw_gbps`` /
# ``hier_cross_busbw_gbps``) — a v1 artifact knows nothing about the
# split, so reloading it would leave the new lanes unseeded while
# claiming a cache hit.
_CACHE_SCHEMA = 2


def _timed_scalar(fn, *args) -> float:
    """Wall-clock one compiled call ending in a scalar readback."""
    t0 = time.perf_counter()
    float(fn(*args))
    return time.perf_counter() - t0


def _per_iter_time(make_chain, x, lo: int, hi: int,
                   repeats: int = 3) -> float:
    """Difference-quotient timing: build chain(lo) and chain(hi), take
    min over repeats of each, return (t_hi - t_lo) / (hi - lo). Cancels
    the constant dispatch/readback overhead that dominates through
    remote-dispatch tunnels (docs/benchmarks.md measurement protocol).
    ``x`` stays a traced argument so XLA cannot constant-fold the chain.
    """
    hi = max(hi, lo + 1)
    c_lo, c_hi = make_chain(lo), make_chain(hi)
    _timed_scalar(c_lo, x)  # compile + warm
    _timed_scalar(c_hi, x)
    t_lo = min(_timed_scalar(c_lo, x) for _ in range(repeats))
    t_hi = min(_timed_scalar(c_hi, x) for _ in range(repeats))
    return max((t_hi - t_lo) / (hi - lo), 1e-9)


def probe_hbm_bandwidth(size_mb: int = 64, iters: int = 16) -> float:
    """Sustained single-device HBM copy bandwidth in GB/s (read + write).

    A chained scale-by-~one copy: each iteration reads and writes the
    buffer once, so bytes moved per iteration = 2 * size.
    """
    n = size_mb * (1 << 20) // 4
    x = jnp.ones((n,), jnp.float32)
    k = jnp.float32(1.0000001)

    def make_chain(length):
        @jax.jit
        def chain(v):
            def body(c, _):
                return c * k, None

            out, _ = jax.lax.scan(body, v, None, length=length)
            return out[0]

        return chain

    dt = _per_iter_time(make_chain, x, max(1, iters // 4), iters)
    return 2.0 * x.nbytes / dt / 1e9


def probe_allreduce_bandwidth(mesh=None, size_mb: int = 32,
                              iters: int = 8,
                              detail: bool = False) -> Union[float, dict]:
    """Algorithm bandwidth (input bytes / time) of a full-mesh all-reduce
    in GB/s — the ICI number that bounds fused-collective latency. On a
    1-device mesh this degenerates to an HBM-bound pass, which is the
    right bound there too.

    ``detail=True`` returns ``{"algbw_gbps", "busbw_gbps", "world"}`` —
    bus bandwidth (algbw x 2(N-1)/N, the comms-plane convention,
    docs/comms.md) plus the mesh size it was probed on, so a persisted
    roofline from a different world size can be invalidated."""
    from horovod_tpu.core import basics

    if mesh is None:
        mesh = basics._ensure_init().mesh
    n = size_mb * (1 << 20) // 4
    repl = NamedSharding(mesh, P())
    x = jax.device_put(jnp.ones((n,), jnp.float32), repl)
    inv = jnp.float32(1.0 / mesh.size)

    def make_chain(length):
        @jax.jit
        def chain(w):
            def inner(v):
                def step(c, _):
                    s = jax.lax.psum(c, mesh_mod.GLOBAL_AXES)
                    return s * inv, None

                out, _ = jax.lax.scan(step, v, None, length=length)
                return out

            y = jax.shard_map(inner, mesh=mesh, in_specs=P(), out_specs=P(),
                              check_vma=False)(w)
            return y[0]

        return chain

    dt = _per_iter_time(make_chain, x, max(1, iters // 4), iters)
    algbw = x.nbytes / dt / 1e9
    if not detail:
        return algbw
    from horovod_tpu import comms

    world = int(mesh.size)
    return {"algbw_gbps": algbw,
            "busbw_gbps": algbw * comms.bus_factor("allreduce", world),
            "world": world}


def recommended_fusion_threshold(allreduce_gbps: float,
                                 cycle_time_ms: float,
                                 cycle_fraction: float = 0.5,
                                 floor_bytes: int = 1 << 20,
                                 ceil_bytes: int = 256 << 20,
                                 hbm_gbps: Optional[float] = None) -> int:
    """Fusion threshold such that reducing one full fused buffer takes at
    most ``cycle_fraction`` of a cycle at the probed bandwidth — big
    enough to amortize launch overhead, small enough that fused
    collectives don't starve the cycle cadence (the trade the reference's
    autotuner searches for blindly, reference: parameter_manager.h:225).

    The effective rate is capped by HBM when given: a fused collective
    also packs and unpacks the buffer through HBM (one read + one write
    each way), so the wire can never be fed faster than ``hbm/2``.
    """
    rate = allreduce_gbps
    if hbm_gbps is not None:
        rate = min(rate, hbm_gbps / 2.0)
    budget_s = cycle_time_ms * 1e-3 * cycle_fraction
    threshold = int(rate * 1e9 * budget_s)
    return max(floor_bytes, min(ceil_bytes, threshold))


def _cache_path() -> Optional[str]:
    path = os.environ.get(HOROVOD_PROBE_CACHE, "").strip()
    return path or None


def load_cached_roofline(path: Optional[str] = None,
                         world: Optional[int] = None) -> Optional[dict]:
    """Read the persisted probe artifact (``HOROVOD_PROBE_CACHE``).
    Returns None when the knob is unset, the file is missing/corrupt,
    the schema moved on, or — the invalidation this artifact exists to
    get right — it was probed on a different world size (busbw's ring
    factor is a function of N; a 4-chip roofline says nothing about a
    32-chip pod)."""
    path = path or _cache_path()
    if not path:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != _CACHE_SCHEMA:
        return None
    if world is not None and int(doc.get("world", -1)) != int(world):
        log.info("probe cache %s ignored: probed on world=%s, running "
                 "world=%d", path, doc.get("world"), world)
        return None
    return doc


def _persist_roofline(path: str, doc: dict) -> None:
    """fsync'd write of the roofline artifact (tmp + rename, directory
    fsync'd too — a crashed init must not leave a torn JSON that every
    later restart trips over)."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dir_fd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def probe_and_seed(config, mesh=None) -> dict:
    """Run the probes and seed ``config.fusion_threshold_bytes``; returns
    the measurements. Called at runtime startup when
    ``HOROVOD_AUTOTUNE_PROBE`` is on. Must run on EVERY process in a
    multi-controller (jax.distributed) world — the probe programs execute
    over the global mesh, which all processes must enter together; the
    coordinator's seeded value then wins via the per-cycle parameter
    broadcast, so probe noise cannot diverge the workers.

    With ``HOROVOD_PROBE_CACHE=<path>`` the measurements are persisted as
    a JSON roofline artifact (fsync'd) and reloaded on restart instead of
    re-probing every ``hvd.init()`` — a cached artifact from a different
    world size is invalidated (the busbw ring factor depends on N). The
    same artifact seeds the comms plane's lane rooflines
    (comms.configure / docs/comms.md)."""
    from horovod_tpu import comms

    if mesh is None:
        from horovod_tpu.core import basics

        mesh = basics._ensure_init().mesh
    world = int(mesh.size)
    cached = load_cached_roofline(world=world)
    if cached is not None and "hbm_gbps" not in cached:
        # a hier-hop-only artifact (host-ring probe wrote this path):
        # says nothing about the mesh lanes — probe them live
        cached = None
    if cached is not None:
        measured = {
            "hbm_gbps": float(cached["hbm_gbps"]),
            "allreduce_gbps": float(cached["allreduce_gbps"]),
            "allreduce_busbw_gbps": float(
                cached.get("allreduce_busbw_gbps", 0.0)),
            "world": world,
            "cached": True,
        }
        log.info("probe cache hit (%s): HBM %.1f GB/s, allreduce %.1f "
                 "GB/s algbw / %.1f GB/s busbw (world=%d) — probes "
                 "skipped", _cache_path(), measured["hbm_gbps"],
                 measured["allreduce_gbps"],
                 measured["allreduce_busbw_gbps"], world)
    else:
        hbm = probe_hbm_bandwidth()
        ar = probe_allreduce_bandwidth(mesh, detail=True)
        if not isinstance(ar, dict):  # a monkeypatched/legacy float
            ar = {"algbw_gbps": float(ar),
                  "busbw_gbps": float(ar)
                  * comms.bus_factor("allreduce", world),
                  "world": world}
        measured = {
            "hbm_gbps": hbm,
            "allreduce_gbps": ar["algbw_gbps"],
            "allreduce_busbw_gbps": ar["busbw_gbps"],
            "world": world,
            "cached": False,
        }
    threshold = recommended_fusion_threshold(
        measured["allreduce_gbps"], config.cycle_time_ms,
        hbm_gbps=measured["hbm_gbps"])
    config.fusion_threshold_bytes = threshold
    measured["fusion_threshold_bytes"] = threshold
    path = _cache_path()
    if path and not measured["cached"]:
        try:
            _persist_roofline(path, {
                "schema": _CACHE_SCHEMA,
                "hbm_gbps": measured["hbm_gbps"],
                "allreduce_gbps": measured["allreduce_gbps"],
                "allreduce_busbw_gbps": measured["allreduce_busbw_gbps"],
                "world": world,
                "fusion_threshold_bytes": threshold,
                "wall_time": time.time(),
            })
        except OSError as exc:
            log.warning("probe cache not persisted to %s: %s", path, exc)
    # seed the comms plane's XLA-lane rooflines from the live (or cached)
    # measurement — the probe runs after comms.configure, so a first-boot
    # probe (no artifact yet) still pins the roofline this run
    if measured["allreduce_busbw_gbps"] > 0:
        source = "probe_cache" if measured["cached"] else "probe"
        for lane in ("device", "spmd"):
            comms.tracker().seed_roofline(
                lane, measured["allreduce_busbw_gbps"], source=source)
    return measured


# -- host-hierarchy hop probes (socket data plane) ----------------------------

def probe_hier_hops(net, plan, size_mb: int = 4,
                    iters: int = 6) -> dict:
    """Probe the two hops of the socket hierarchy SEPARATELY: a timed
    subgroup ring allreduce inside each group (``hier_intra``) and one
    over each cross-group slot ring (``hier_cross``). The two lanes can
    differ by an order of magnitude (intra-host loopback vs a throttled
    DCN hop), so one blended number would mis-bound both.

    Collective: every rank of the plan must call this at the same
    execution point. The intra rings (one per group) and the cross rings
    (one per slot) are each disjoint over ranks, so all ranks probe both
    hops concurrently. Returns busbw GB/s per hop.
    """
    from horovod_tpu import comms
    from horovod_tpu.runtime import hierarchy

    n = max(1, size_mb * (1 << 20) // 4)
    buf = np.ones((n,), np.float32)

    def timed(ring, pos) -> float:
        # "max" keeps values fixed across iterations (an iterated "sum"
        # would overflow); 2 warmup rounds double as a ring barrier so
        # the timed window starts aligned
        for _ in range(2):
            hierarchy._ring_allreduce(net, ring, pos, buf, "max")
        t0 = time.perf_counter()
        for _ in range(iters):
            hierarchy._ring_allreduce(net, ring, pos, buf, "max")
        dt = (time.perf_counter() - t0) / iters
        algbw = buf.nbytes / dt / 1e9
        return algbw * comms.bus_factor("allreduce", len(ring))

    intra = timed(plan.members, plan.local_index)
    cross = timed(plan.cross_members, plan.group_index)
    return {"hier_intra_busbw_gbps": intra,
            "hier_cross_busbw_gbps": cross}


def probe_host_hier_and_seed(net, config) -> Optional[dict]:
    """Host-ring analogue of :func:`probe_and_seed` for the hierarchy
    lanes: reuse a matching schema-2 artifact when present, otherwise
    probe both hops over the live sockets, persist (rank 0 only — the
    write is atomic but there is no reason for N ranks to race on one
    path), and seed the ``hier_intra``/``hier_cross`` comms rooflines.
    Returns None when the world cannot form a hierarchy (the flat ring
    keeps its self-calibrating peak-observed roofline). Collective:
    every rank must call this at the same execution point."""
    from horovod_tpu import comms
    from horovod_tpu.runtime import hierarchy

    plan = hierarchy.build_plan(
        net, getattr(config, "hierarchy_group_size", 0))
    if not plan.enabled:
        return None
    cached = load_cached_roofline(world=net.world)
    if cached is not None and cached.get("hier_intra_busbw_gbps") \
            and cached.get("hier_cross_busbw_gbps"):
        measured = {
            "hier_intra_busbw_gbps": float(
                cached["hier_intra_busbw_gbps"]),
            "hier_cross_busbw_gbps": float(
                cached["hier_cross_busbw_gbps"]),
            "cached": True,
        }
    else:
        measured = probe_hier_hops(net, plan)
        measured["cached"] = False
        path = _cache_path()
        if path and net.rank == 0:
            try:
                _persist_roofline(path, {
                    "schema": _CACHE_SCHEMA,
                    "hier_intra_busbw_gbps":
                        measured["hier_intra_busbw_gbps"],
                    "hier_cross_busbw_gbps":
                        measured["hier_cross_busbw_gbps"],
                    "world": net.world,
                    "wall_time": time.time(),
                })
            except OSError as exc:
                log.warning("probe cache not persisted to %s: %s",
                            path, exc)
    source = "probe_cache" if measured["cached"] else "probe"
    comms.tracker().seed_roofline(
        "hier_intra", measured["hier_intra_busbw_gbps"], source=source)
    comms.tracker().seed_roofline(
        "hier_cross", measured["hier_cross_busbw_gbps"], source=source)
    return measured
