"""Training-loop callbacks and LR schedules.

TPU-native equivalents of the reference's Keras callbacks (reference:
horovod/_keras/callbacks.py; re-exported via horovod/keras/callbacks.py and
horovod/tensorflow/keras/callbacks.py):

* ``BroadcastGlobalVariablesCallback`` — sync all workers to rank 0's state
  at the start of training (reference: _keras/callbacks.py:20-44).
* ``MetricAverageCallback`` — average epoch metrics across workers
  (reference: _keras/callbacks.py:46-84).
* ``LearningRateWarmupCallback`` / ``LearningRateScheduleCallback`` —
  linear-scaling LR warmup and multiplier schedules
  (reference: _keras/callbacks.py:87-181, per the Facebook "Accurate, Large
  Minibatch SGD" recipe the reference implements).

JAX training loops are explicit, so callbacks here are plain objects the
loop invokes (``on_train_begin``/``on_epoch_end``...); the schedule variants
are also exposed as **optax schedules** — the idiomatic form — via
``warmup_scaled_schedule``.
"""

from __future__ import annotations

import math
from typing import Callable, Mapping, Optional

import jax.numpy as jnp

from horovod_tpu.core import basics
from horovod_tpu.ops import collectives
from horovod_tpu.parallel import dp


class Callback:
    """Minimal callback protocol for explicit JAX training loops."""

    def on_train_begin(self, state):
        return state

    def on_epoch_begin(self, epoch: int, state):
        return state

    def on_epoch_end(self, epoch: int, state, metrics=None):
        return state, metrics

    def on_batch_begin(self, batch: int, state):
        return state


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast model/optimizer state from ``root_rank`` to all workers at
    the start of training — required for consistency with random init or
    restored checkpoints (reference: _keras/callbacks.py:20-44)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state):
        return dp.broadcast_parameters(state, root_rank=self.root_rank)


class MetricAverageCallback(Callback):
    """Average metrics across workers at epoch end so reported values
    reflect the whole job (reference: _keras/callbacks.py:46-84)."""

    def on_epoch_end(self, epoch: int, state, metrics: Optional[Mapping] = None):
        if metrics is None:
            return state, metrics
        return state, average_metrics(metrics)


def average_metrics(metrics: Mapping) -> dict:
    """Functional form of ``MetricAverageCallback``."""
    return {
        k: collectives.allreduce(jnp.asarray(v), average=True)
        for k, v in metrics.items()
    }


def warmup_scaled_schedule(
    base_lr: float,
    warmup_epochs: float,
    steps_per_epoch: int,
    size: Optional[int] = None,
    after: Optional[Callable[[int], float]] = None,
    initial_lr: Optional[float] = None,
):
    """optax schedule: ramp linearly from ``base_lr`` to ``base_lr * size``
    over ``warmup_epochs``, then follow ``after`` (a multiplier schedule on
    the scaled LR) or stay flat.

    This is the reference's ``LearningRateWarmupCallback`` recipe
    (reference: _keras/callbacks.py:87-181): large-batch training scales the
    LR by the number of workers, warming up from the single-worker LR to
    avoid early divergence.
    """
    if size is None:
        size = basics.size()
    scaled = base_lr * size
    start = initial_lr if initial_lr is not None else base_lr
    warmup_steps = max(int(warmup_epochs * steps_per_epoch), 1)

    def schedule(step):
        step = jnp.asarray(step)
        frac = jnp.minimum(step / warmup_steps, 1.0)
        warm = start + (scaled - start) * frac
        if after is not None:
            post_epoch = jnp.maximum(
                (step - warmup_steps) / steps_per_epoch, 0.0)
            return jnp.where(step < warmup_steps, warm,
                             scaled * after(post_epoch))
        return warm

    return schedule


class LearningRateWarmupCallback(Callback):
    """Eager-loop variant of ``warmup_scaled_schedule`` holding the current
    LR as ``self.lr``; the loop reads it each batch (reference:
    _keras/callbacks.py:129-181)."""

    def __init__(self, base_lr: float, warmup_epochs: float = 5.0,
                 steps_per_epoch: int = 1, size: Optional[int] = None,
                 verbose: bool = False):
        self._schedule = warmup_scaled_schedule(
            base_lr, warmup_epochs, steps_per_epoch, size=size)
        self._step = 0
        self.verbose = verbose
        self.lr = float(self._schedule(0))

    def on_batch_begin(self, batch: int, state):
        self.lr = float(self._schedule(self._step))
        self._step += 1
        return state


class LearningRateScheduleCallback(Callback):
    """Multiplier schedule: ``lr = base_lr * multiplier(epoch)``; supports
    staircase or smooth multipliers (reference: _keras/callbacks.py:87-127)."""

    def __init__(self, base_lr: float,
                 multiplier: Callable[[float], float],
                 start_epoch: float = 0.0,
                 end_epoch: Optional[float] = None,
                 staircase: bool = True):
        self.base_lr = base_lr
        self.multiplier = multiplier
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.lr = base_lr

    def on_epoch_begin(self, epoch: int, state):
        if epoch < self.start_epoch or (
                self.end_epoch is not None and epoch >= self.end_epoch):
            return state
        e = math.floor(epoch) if self.staircase else epoch
        self.lr = self.base_lr * self.multiplier(e)
        return state
