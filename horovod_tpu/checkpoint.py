"""Checkpoint / resume: rank-0 save, broadcast restore.

The reference delegates checkpointing to the frameworks but fixes the
*convention* (reference: SURVEY.md §5.4, examples/pytorch_imagenet_resnet50.py,
examples/keras_imagenet_resnet50.py): only rank 0 writes; on resume every
worker loads and rank 0's values are made authoritative via broadcast
(``broadcast_parameters`` / ``broadcast_optimizer_state``; resume epoch via a
0-d broadcast). This module packages that convention for JAX pytrees:

    state = train(...)
    hvd.checkpoint.save(ckpt_dir, state, step=epoch)       # rank 0 writes
    ...
    state, step = hvd.checkpoint.restore_latest(ckpt_dir, target=state)

This is the LEGACY single-writer path, kept as a thin shim over the
PR-9 durability primitives in :mod:`horovod_tpu.ckpt.io`: atomic
fsync'd publishes, pid-liveness tmp cleaning (an mtime-only window let
two live writers with skewed clocks delete each other's fresh tmps),
and a ``.crc`` sidecar — whole-file plus per-leaf digests — that
``restore`` verifies, raising
:class:`~horovod_tpu.exceptions.CheckpointCorruptError` naming the
offending leaf. Sharded multi-writer checkpointing (every rank writes
its ZeRO shard, two-phase commit, neighbor replicas) lives in
:mod:`horovod_tpu.ckpt`.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional, Tuple

import jax
from flax import serialization

from horovod_tpu.ckpt import io as ckpt_io
from horovod_tpu.core import basics
from horovod_tpu.exceptions import CheckpointCorruptError
from horovod_tpu.parallel import dp

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")

# re-exported for callers that tuned the legacy knob; the pid-liveness
# cleaner only uses it for foreign-host / legacy tmp names
_STALE_TMP_SECONDS = ckpt_io.STALE_TMP_SECONDS


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_{step}.msgpack")


def _crc_path(path: str) -> str:
    return path + ".crc"


def _fsync_dir(directory: str) -> None:
    ckpt_io.fsync_dir(directory)


def _clean_stale_tmps(directory: str) -> None:
    """Remove orphaned ``*.tmp`` files left by writers that were killed
    mid-save. Staleness is pid-liveness for this host's tmps (the name
    embeds ``hostname.pid``) and an mtime window only for legacy/foreign
    names — see :func:`horovod_tpu.ckpt.io.clean_stale_tmps`."""
    ckpt_io.clean_stale_tmps(directory)


def _leaf_crcs(state: Any) -> dict:
    """Per-leaf digests keyed by the flattened key path — lets a restore
    failure name the damaged leaf instead of just the file."""
    import numpy as np

    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    out = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path) or "<root>"
        try:
            data = np.ascontiguousarray(np.asarray(leaf)).tobytes()
        except Exception:
            continue  # non-array leaf: covered by the whole-file digest
        out[key] = ckpt_io.checksum(data)
    return out


def save(directory: str, state: Any, step: int = 0,
         keep: Optional[int] = None) -> Optional[str]:
    """Write ``state`` (any pytree of arrays/scalars) as step ``step``.

    Only rank 0 writes (the reference convention); other ranks return
    ``None`` immediately. ``keep`` retains only the newest N checkpoints.
    Next to every checkpoint goes a ``.crc`` sidecar (whole-file and
    per-leaf digests) that :func:`restore` verifies.
    """
    st = basics._ensure_init()
    if st.rank != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    _clean_stale_tmps(directory)
    state = jax.device_get(state)
    data = serialization.to_bytes(state)
    path = _ckpt_path(directory, step)
    sidecar = json.dumps({
        "algorithm": ckpt_io.CRC_ALGORITHM,
        "file_crc": ckpt_io.checksum(data),
        "bytes": len(data),
        "leaves": _leaf_crcs(state),
    }).encode()
    # sidecar first: a crash between the two publishes leaves a
    # checkpoint whose sidecar mismatches (detected and skipped), never
    # a verified-but-wrong one
    ckpt_io.atomic_write(_crc_path(path), sidecar, base="ckpt")
    ckpt_io.atomic_write(path, data, base="ckpt")
    if keep is not None:
        for old_step in all_steps(directory)[:-keep]:
            old = _ckpt_path(directory, old_step)
            os.unlink(old)
            try:
                os.unlink(_crc_path(old))
            except OSError:
                pass
    return path


def all_steps(directory: str) -> list:
    """Sorted step numbers present in ``directory``."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    steps = all_steps(directory)
    return steps[-1] if steps else None


def _verify_sidecar(path: str, data: bytes, target: Any) -> None:
    """Check ``data`` (and, when decodable, each leaf) against the
    ``.crc`` sidecar. No sidecar (pre-PR-9 checkpoint) verifies
    trivially; any mismatch raises :class:`CheckpointCorruptError`."""
    try:
        with open(_crc_path(path), "rb") as f:
            sidecar = json.loads(f.read())
    except OSError:
        return  # legacy checkpoint without a sidecar
    except ValueError as exc:
        raise CheckpointCorruptError(
            f"checkpoint sidecar {_crc_path(path)} is unreadable: {exc}",
            path=path) from exc
    algorithm = sidecar.get("algorithm")
    if "bytes" in sidecar and len(data) != int(sidecar["bytes"]):
        raise CheckpointCorruptError(
            f"checkpoint {path} has {len(data)} bytes; its sidecar "
            f"recorded {sidecar['bytes']} (truncated or torn write)",
            path=path)
    if not ckpt_io.verify_checksum(data, sidecar.get("file_crc", 0),
                                   algorithm):
        # narrow it down to a leaf if the payload still decodes
        leaf = _find_bad_leaf(target, data, sidecar, algorithm)
        raise CheckpointCorruptError(
            f"checkpoint {path} fails its whole-file CRC"
            + (f" (first damaged leaf: {leaf!r})" if leaf else ""),
            path=path, leaf=leaf)


def _find_bad_leaf(target: Any, data: bytes, sidecar: dict,
                   algorithm: Optional[str]) -> Optional[str]:
    import numpy as np

    try:
        state = serialization.from_bytes(target, data)
    except Exception:
        return None
    want = sidecar.get("leaves", {})
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path) or "<root>"
        if key not in want:
            continue
        try:
            blob = np.ascontiguousarray(np.asarray(leaf)).tobytes()
        except Exception:
            continue
        if not ckpt_io.verify_checksum(blob, want[key], algorithm):
            return key
    return None


def restore(path: str, target: Any, broadcast: bool = True,
            verify: bool = True) -> Any:
    """Load a checkpoint file into the structure of ``target``.

    With ``broadcast`` (default), rank 0's loaded values are broadcast so
    every worker resumes bit-identical state even if their filesystems
    disagree — the reference's restore-everywhere-via-broadcast convention
    (reference: torch/__init__.py:255-403). A non-0 rank whose local
    filesystem lacks the file still participates: it feeds ``target``
    into the broadcast and receives rank 0's values.

    With ``verify`` (default), the bytes are checked against the
    ``.crc`` sidecar before deserialization; damage raises
    :class:`CheckpointCorruptError` naming the leaf when it can be
    narrowed down. Decode failures surface the same way — a truncated
    msgpack can otherwise parse into garbage silently.
    """
    st = basics._ensure_init()
    if os.path.exists(path):
        with open(path, "rb") as f:
            data = f.read()
        if verify:
            _verify_sidecar(path, data, target)
        try:
            state = serialization.from_bytes(target, data)
        except CheckpointCorruptError:
            raise
        except Exception as exc:
            raise CheckpointCorruptError(
                f"checkpoint {path} failed to deserialize: {exc}",
                path=path) from exc
    elif broadcast and st.rank != 0:
        state = target  # overwritten by rank 0's broadcast below
    else:
        raise FileNotFoundError(path)
    if broadcast:
        state = dp.broadcast_parameters(state, root_rank=0)
    return state


def restore_latest(directory: str, target: Any,
                   broadcast: bool = True) -> Tuple[Any, Optional[int]]:
    """Restore the newest checkpoint; returns ``(state, step)`` or
    ``(target, None)`` when no checkpoint exists (fresh start — mirrors
    the examples' ``resume_from_epoch = 0`` default).

    The resume decision is rank 0's (only rank 0 writes, so on non-shared
    filesystems only rank 0 can see the files): its latest step is
    broadcast first, and every rank then takes the same branch — so the
    broadcast collectives inside :func:`restore` stay aligned across the
    job (reference: examples/pytorch_imagenet_resnet50.py
    resume_from_epoch broadcast).
    """
    local_step = latest_step(directory)
    step = dp.broadcast_object(local_step, root_rank=0,
                               name="ckpt_resume_step")
    if step is None:
        return target, None
    state = restore(_ckpt_path(directory, step), target, broadcast=broadcast)
    return state, step
