"""Checkpoint / resume: rank-0 save, broadcast restore.

The reference delegates checkpointing to the frameworks but fixes the
*convention* (reference: SURVEY.md §5.4, examples/pytorch_imagenet_resnet50.py,
examples/keras_imagenet_resnet50.py): only rank 0 writes; on resume every
worker loads and rank 0's values are made authoritative via broadcast
(``broadcast_parameters`` / ``broadcast_optimizer_state``; resume epoch via a
0-d broadcast). This module packages that convention for JAX pytrees:

    state = train(...)
    hvd.checkpoint.save(ckpt_dir, state, step=epoch)       # rank 0 writes
    ...
    state, step = hvd.checkpoint.restore_latest(ckpt_dir, target=state)

Serialization is flax msgpack (host-resident, framework-native); files are
written atomically (tmp + rename) so a killed worker never leaves a torn
checkpoint — the failure-handling analogue of the reference's launcher
killing whole jobs on any rank failure (reference: gloo_run.py:256-262).
"""

from __future__ import annotations

import os
import re
import tempfile
import time
from typing import Any, Optional, Tuple

import jax
from flax import serialization

from horovod_tpu.core import basics
from horovod_tpu.parallel import dp

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")

# a .tmp this old belongs to a dead writer, not an in-flight save
_STALE_TMP_SECONDS = 600.0


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_{step}.msgpack")


def _fsync_dir(directory: str) -> None:
    """Durably record the rename in the directory entry — without this a
    host crash after ``os.replace`` can resurface the old (or no) file."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _clean_stale_tmps(directory: str) -> None:
    """Remove orphaned ``*.tmp`` files left by writers that were killed
    mid-save (the elastic failure mode this module exists for). Only files
    older than ``_STALE_TMP_SECONDS`` go — a concurrent live save keeps
    its fresh tmp."""
    try:
        names = os.listdir(directory)
    except OSError:
        return
    now = time.time()
    for name in names:
        if not name.endswith(".tmp"):
            continue
        path = os.path.join(directory, name)
        try:
            if now - os.path.getmtime(path) > _STALE_TMP_SECONDS:
                os.unlink(path)
        except OSError:
            pass  # raced with another cleaner, or already gone


def save(directory: str, state: Any, step: int = 0,
         keep: Optional[int] = None) -> Optional[str]:
    """Write ``state`` (any pytree of arrays/scalars) as step ``step``.

    Only rank 0 writes (the reference convention); other ranks return
    ``None`` immediately. ``keep`` retains only the newest N checkpoints.
    """
    st = basics._ensure_init()
    if st.rank != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    _clean_stale_tmps(directory)
    state = jax.device_get(state)
    data = serialization.to_bytes(state)
    path = _ckpt_path(directory, step)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())  # durable before it can be published
        os.replace(tmp, path)  # atomic publish
        _fsync_dir(directory)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    if keep is not None:
        for old_step in all_steps(directory)[:-keep]:
            os.unlink(_ckpt_path(directory, old_step))
    return path


def all_steps(directory: str) -> list:
    """Sorted step numbers present in ``directory``."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    steps = all_steps(directory)
    return steps[-1] if steps else None


def restore(path: str, target: Any, broadcast: bool = True) -> Any:
    """Load a checkpoint file into the structure of ``target``.

    With ``broadcast`` (default), rank 0's loaded values are broadcast so
    every worker resumes bit-identical state even if their filesystems
    disagree — the reference's restore-everywhere-via-broadcast convention
    (reference: torch/__init__.py:255-403). A non-0 rank whose local
    filesystem lacks the file still participates: it feeds ``target``
    into the broadcast and receives rank 0's values.
    """
    st = basics._ensure_init()
    if os.path.exists(path):
        with open(path, "rb") as f:
            state = serialization.from_bytes(target, f.read())
    elif broadcast and st.rank != 0:
        state = target  # overwritten by rank 0's broadcast below
    else:
        raise FileNotFoundError(path)
    if broadcast:
        state = dp.broadcast_parameters(state, root_rank=0)
    return state


def restore_latest(directory: str, target: Any,
                   broadcast: bool = True) -> Tuple[Any, Optional[int]]:
    """Restore the newest checkpoint; returns ``(state, step)`` or
    ``(target, None)`` when no checkpoint exists (fresh start — mirrors
    the examples' ``resume_from_epoch = 0`` default).

    The resume decision is rank 0's (only rank 0 writes, so on non-shared
    filesystems only rank 0 can see the files): its latest step is
    broadcast first, and every rank then takes the same branch — so the
    broadcast collectives inside :func:`restore` stay aligned across the
    job (reference: examples/pytorch_imagenet_resnet50.py
    resume_from_epoch broadcast).
    """
    local_step = latest_step(directory)
    step = dp.broadcast_object(local_step, root_rank=0,
                               name="ckpt_resume_step")
    if step is None:
        return target, None
    state = restore(_ckpt_path(directory, step), target, broadcast=broadcast)
    return state, step
