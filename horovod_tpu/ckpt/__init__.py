"""Crash-consistent distributed checkpointing.

Sharded two-phase snapshots (every rank writes only its ZeRO shard plus
its round-robin slice of the replicated state), a rendezvous-KV commit
barrier with an atomically-published per-step manifest as the commit
point, neighbor replication of shard bytes (in memory for elastic
re-forms, on disk for single-file loss), and world-size-change restore.

Modules:

* :mod:`~horovod_tpu.ckpt.io` — CRCs, pid-named tmps, fsync'd renames.
* :mod:`~horovod_tpu.ckpt.manifest` — the shard container + manifest.
* :mod:`~horovod_tpu.ckpt.writer` — :class:`CheckpointManager`: the
  stage/barrier/publish protocol on a background writer thread.
* :mod:`~horovod_tpu.ckpt.restore` — ``restore_latest`` with replica
  fallback and re-scatter into the current world size.
* :mod:`~horovod_tpu.ckpt.replica` — the in-memory neighbor-replica
  ring that fixes zero-moment loss on elastic recovery.
* :mod:`~horovod_tpu.ckpt.stats` — ``horovod_ckpt_*`` metric families.
"""

from horovod_tpu.ckpt import io, manifest, replica, restore, stats, writer
from horovod_tpu.ckpt.restore import (latest_step, restore_latest,
                                      restore_step)
from horovod_tpu.ckpt.writer import CheckpointManager, parse_fault
from horovod_tpu.exceptions import CheckpointCorruptError

__all__ = [
    "CheckpointCorruptError",
    "CheckpointManager",
    "io",
    "latest_step",
    "manifest",
    "parse_fault",
    "replica",
    "restore",
    "restore_latest",
    "restore_step",
    "stats",
    "writer",
]
