"""Durable-file primitives shared by the sharded checkpoint writer and
the legacy :mod:`horovod_tpu.checkpoint` shim.

Three invariants every writer in this package leans on:

* **torn writes are invisible** — payload goes to a ``*.tmp`` sibling,
  is fsync'd, and only then ``os.replace``d over the published name
  (followed by a directory fsync so the rename itself is durable);
* **tmp staleness is keyed on writer liveness, not mtime** — the tmp
  name embeds ``<hostname>.<pid>``, and the cleaner only removes a tmp
  whose writer process is provably gone (``os.kill(pid, 0)``). An
  mtime-only window (the pre-PR-9 rule) let two concurrent writers with
  skewed clocks delete each other's *fresh* tmp files;
* **integrity is checksummed** — CRC32C (Castagnoli) when a native
  implementation is importable, else zlib's CRC-32; the algorithm tag is
  recorded next to every digest so restore always verifies with the
  algorithm that wrote it.
"""

from __future__ import annotations

import os
import socket
import tempfile
import zlib
from typing import Optional, Tuple

# tmp names look like  <base>.<hostname>.<pid>.<random>.tmp ; the pid is
# only meaningful on the host that wrote it
_TMP_SUFFIX = ".tmp"

# Castagnoli CRC when available (google-crc32c / crc32c wheels bundled
# with some storage SDKs); the container is NOT allowed to grow a hard
# dependency, so absence degrades to zlib's CRC-32 with a distinct tag.
try:  # pragma: no cover - depends on the environment
    import google_crc32c as _crc32c_mod

    def _crc32c(data: bytes) -> int:
        return int(_crc32c_mod.value(data))

    CRC_ALGORITHM = "crc32c"
except ImportError:  # pragma: no cover
    try:
        import crc32c as _crc32c_mod  # type: ignore

        def _crc32c(data: bytes) -> int:
            return int(_crc32c_mod.crc32c(data))

        CRC_ALGORITHM = "crc32c"
    except ImportError:
        _crc32c_mod = None

        def _crc32c(data: bytes) -> int:
            return zlib.crc32(data) & 0xFFFFFFFF

        CRC_ALGORITHM = "crc32"


def checksum(data, running: int = 0) -> int:
    """Digest of ``data`` (bytes or a buffer-protocol object), optionally
    chained from a previous call's result."""
    if CRC_ALGORITHM == "crc32":
        return zlib.crc32(data, running) & 0xFFFFFFFF
    if running:
        # native crc32c modules don't expose chaining uniformly; chain by
        # mixing, which stays deterministic for (algorithm, data) pairs
        return _crc32c(running.to_bytes(4, "little") + bytes(data))
    return _crc32c(bytes(data))


def verify_checksum(data, want: int, algorithm: Optional[str]) -> bool:
    """Check ``data`` against a recorded digest, honoring the algorithm
    that wrote it (a crc32-tagged manifest verifies with zlib even when
    a native crc32c is importable here, and vice versa)."""
    if algorithm in (None, "crc32"):
        return (zlib.crc32(bytes(data)) & 0xFFFFFFFF) == int(want)
    if algorithm == "crc32c" and CRC_ALGORITHM == "crc32c":
        return _crc32c(bytes(data)) == int(want)
    # written with an algorithm this host cannot compute: unverifiable,
    # not corrupt — the caller decides whether that is acceptable
    return True


def hostname() -> str:
    try:
        return socket.gethostname().split(".")[0] or "localhost"
    except OSError:
        return "localhost"


def make_tmp(directory: str, base: str = "ckpt") -> Tuple[int, str]:
    """``mkstemp`` with the writer's identity in the name:
    ``<base>.<hostname>.<pid>.<random>.tmp``."""
    prefix = f"{base}.{hostname()}.{os.getpid()}."
    return tempfile.mkstemp(dir=directory, prefix=prefix,
                            suffix=_TMP_SUFFIX)


def parse_tmp_writer(name: str) -> Tuple[Optional[str], Optional[int]]:
    """(hostname, pid) embedded in a tmp name, or (None, None) for a
    legacy/foreign tmp."""
    if not name.endswith(_TMP_SUFFIX):
        return None, None
    parts = name[:-len(_TMP_SUFFIX)].split(".")
    # <base>.<hostname>.<pid>.<random>: pid is third-from-last
    if len(parts) < 4:
        return None, None
    try:
        return parts[-3], int(parts[-2])
    except ValueError:
        return None, None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return True  # indeterminate: keep the file
    return True


# mtime fallback for tmps that don't carry a writer identity (legacy
# names, or names written by another host where a local pid probe would
# alias an unrelated process)
STALE_TMP_SECONDS = 600.0


def clean_stale_tmps(directory: str, now: Optional[float] = None) -> int:
    """Remove ``*.tmp`` files whose writer is dead. Returns the number
    removed.

    Staleness is decided by pid-liveness when the tmp was written by
    THIS host (``os.kill(pid, 0)``): a live writer's tmp is never
    touched no matter how old, and a dead writer's tmp goes immediately.
    Foreign-host and legacy tmps fall back to the mtime window — the
    only signal available for them."""
    import time

    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    if now is None:
        now = time.time()
    removed = 0
    host = hostname()
    for name in names:
        if not name.endswith(_TMP_SUFFIX):
            continue
        path = os.path.join(directory, name)
        tmp_host, tmp_pid = parse_tmp_writer(name)
        try:
            if tmp_pid is not None and tmp_host == host:
                if _pid_alive(tmp_pid):
                    continue  # fresh or slow writer — never its peer's call
            elif now - os.path.getmtime(path) <= STALE_TMP_SECONDS:
                continue
            os.unlink(path)
            removed += 1
        except OSError:
            pass  # raced with another cleaner, or already gone
    return removed


def fsync_dir(directory: str) -> None:
    """Durably record a rename in the directory entry — without this a
    host crash after ``os.replace`` can resurface the old (or no) file."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes, base: str = "ckpt") -> None:
    """Write ``data`` to ``path`` via the fsync'd tmp+rename protocol.
    A crash at any instant leaves either the old ``path`` or the new one
    — never a torn file."""
    directory = os.path.dirname(path) or "."
    fd, tmp = make_tmp(directory, base=base)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())  # durable before it can be published
        os.replace(tmp, path)  # atomic publish
        fsync_dir(directory)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
