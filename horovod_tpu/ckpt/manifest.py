"""Shard container format + the step manifest.

One checkpoint = ``N`` shard files plus one ``MANIFEST-<step>.json``,
all in one directory:

* **shard file** (``shard-<step>-r<rank>-of-<world>.hvd``) — a
  self-describing container: magic, a little-endian uint64 header
  length, a JSON header, then the concatenated raw leaf bytes. The
  header records, per entry, the leaf key, dtype/shape, byte extent and
  CRC, plus a *role*: ``own`` (this rank's ZeRO shard), ``replica``
  (the right neighbor's bytes, held for the elastic recovery path) or
  ``replicated`` (this rank's round-robin slice of the replicated
  state).
* **manifest** — the commit point. It names every shard file with its
  whole-file CRC and records the sharded-state layout (world size +
  flat-group geometry), which is what lets restore re-flatten and
  re-scatter into a *different* world size. ``restore_latest`` only
  ever reads files a manifest names; everything else in the directory
  is garbage-in-progress.

Every parse error, short read, or digest mismatch surfaces as
:class:`~horovod_tpu.exceptions.CheckpointCorruptError` carrying the
file path and (for per-leaf damage) the leaf key.
"""

from __future__ import annotations

import json
import os
import pickle
import re
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu.ckpt import io as ckpt_io
from horovod_tpu.ckpt import stats
from horovod_tpu.exceptions import CheckpointCorruptError

MAGIC = b"HVDSHRD1"
FORMAT = "hvdckpt-1"

MANIFEST_RE = re.compile(r"^MANIFEST-(\d+)\.json$")

ROLE_OWN = "own"
ROLE_REPLICA = "replica"
ROLE_REPLICATED = "replicated"


def manifest_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"MANIFEST-{step}.json")


def shard_name(step: int, rank: int, world: int) -> str:
    return f"shard-{step}-r{rank}-of-{world}.hvd"


# ---------------------------------------------------------------------------
# Shard container
# ---------------------------------------------------------------------------

def array_entry(key: str, value, role: str = ROLE_OWN,
                replica_of: Optional[int] = None) -> dict:
    """Container entry for one numpy/JAX array leaf (0-d scalars
    included). Non-array python objects go through :func:`object_entry`."""
    arr = np.asarray(value)
    data = np.ascontiguousarray(arr).tobytes()
    return {"key": key, "kind": "array", "role": role,
            "dtype": np.dtype(arr.dtype).name, "shape": list(arr.shape),
            "replica_of": replica_of, "data": data,
            "crc": ckpt_io.checksum(data)}


def object_entry(key: str, value: Any, role: str = ROLE_OWN,
                 replica_of: Optional[int] = None) -> dict:
    data = pickle.dumps(value)
    return {"key": key, "kind": "object", "role": role,
            "dtype": None, "shape": None, "replica_of": replica_of,
            "data": data, "crc": ckpt_io.checksum(data)}


def pack_shard(entries: List[dict], meta: dict) -> bytes:
    """Serialize entries into one container blob (header + payload)."""
    records = []
    offset = 0
    for e in entries:
        records.append({k: e[k] for k in
                        ("key", "kind", "role", "dtype", "shape",
                         "replica_of", "crc")}
                       | {"offset": offset, "nbytes": len(e["data"])})
        offset += len(e["data"])
    header = json.dumps({
        "meta": dict(meta, crc_algorithm=ckpt_io.CRC_ALGORITHM),
        "entries": records,
    }).encode()
    parts = [MAGIC, struct.pack("<Q", len(header)), header]
    parts.extend(e["data"] for e in entries)
    return b"".join(parts)


def read_shard(path: str, verify: bool = True) -> Tuple[dict, List[dict]]:
    """Parse a shard container: ``(meta, entries)`` where each entry has
    the header fields plus a decoded ``value``.

    With ``verify`` every leaf's bytes are checked against the recorded
    digest; a mismatch raises :class:`CheckpointCorruptError` naming the
    leaf. Structural damage (bad magic, short file, unparseable header)
    raises with ``leaf=None``."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as exc:
        raise CheckpointCorruptError(
            f"checkpoint shard unreadable: {path}: {exc}",
            path=path) from exc
    if len(blob) < len(MAGIC) + 8 or not blob.startswith(MAGIC):
        stats.INTEGRITY_FAILURES.inc()
        raise CheckpointCorruptError(
            f"checkpoint shard {path} is truncated or not a shard "
            f"container (bad magic)", path=path)
    (header_len,) = struct.unpack_from("<Q", blob, len(MAGIC))
    body_off = len(MAGIC) + 8
    try:
        header = json.loads(blob[body_off:body_off + header_len])
        meta = header["meta"]
        records = header["entries"]
    except (ValueError, KeyError, TypeError) as exc:
        stats.INTEGRITY_FAILURES.inc()
        raise CheckpointCorruptError(
            f"checkpoint shard {path} has an unparseable header: {exc}",
            path=path) from exc
    payload_off = body_off + header_len
    algorithm = meta.get("crc_algorithm")
    entries = []
    for rec in records:
        start = payload_off + int(rec["offset"])
        end = start + int(rec["nbytes"])
        data = blob[start:end]
        key = rec.get("key")
        if len(data) != int(rec["nbytes"]):
            stats.INTEGRITY_FAILURES.inc()
            raise CheckpointCorruptError(
                f"checkpoint shard {path} is truncated at leaf "
                f"{key!r} (wanted {rec['nbytes']} bytes, file holds "
                f"{len(data)})", path=path, leaf=key)
        if verify and not ckpt_io.verify_checksum(
                data, rec["crc"], algorithm):
            stats.INTEGRITY_FAILURES.inc()
            raise CheckpointCorruptError(
                f"checkpoint shard {path}: CRC mismatch on leaf "
                f"{key!r} — bytes on disk do not match what was "
                f"written", path=path, leaf=key)
        entry = dict(rec)
        if rec["kind"] == "array":
            try:
                dt = np.dtype(rec["dtype"])
            except TypeError:
                import ml_dtypes  # noqa: F401  (registers bfloat16 etc.)
                dt = np.dtype(rec["dtype"])
            entry["value"] = np.frombuffer(data, dtype=dt).reshape(
                rec["shape"]).copy()
        else:
            try:
                entry["value"] = pickle.loads(data)
            except Exception as exc:
                stats.INTEGRITY_FAILURES.inc()
                raise CheckpointCorruptError(
                    f"checkpoint shard {path}: object leaf {key!r} "
                    f"failed to decode: {exc}", path=path,
                    leaf=key) from exc
        entries.append(entry)
    return meta, entries


# ---------------------------------------------------------------------------
# Manifest
# ---------------------------------------------------------------------------

def build_manifest(step: int, generation: int, world: int,
                   shards: List[dict], sharded_layout: Dict[str, dict],
                   extra: Optional[dict] = None) -> dict:
    """``shards``: per-rank ``{"rank", "file", "bytes", "crc"}`` records
    (whole-file digest of the published shard). ``sharded_layout``: per
    sharded-state key, ``{"kind", "world", "groups": [[dtype, n,
    shard_elems, padded], ...]}`` — enough to re-flatten under a new
    world size."""
    import time

    manifest = {
        "format": FORMAT,
        "step": int(step),
        "generation": int(generation),
        "world": int(world),
        "time": time.time(),
        "crc_algorithm": ckpt_io.CRC_ALGORITHM,
        "shards": sorted(shards, key=lambda s: int(s["rank"])),
        "sharded": sharded_layout,
    }
    if extra:
        manifest.update(extra)
    return manifest


def write_manifest(directory: str, manifest: dict) -> str:
    path = manifest_path(directory, manifest["step"])
    ckpt_io.atomic_write(
        path, json.dumps(manifest, indent=1).encode(), base="manifest")
    return path


def load_manifest(directory: str, step: int) -> dict:
    path = manifest_path(directory, step)
    try:
        with open(path, "rb") as f:
            manifest = json.loads(f.read())
    except OSError as exc:
        raise CheckpointCorruptError(
            f"manifest unreadable: {path}: {exc}", path=path) from exc
    except ValueError as exc:
        stats.INTEGRITY_FAILURES.inc()
        raise CheckpointCorruptError(
            f"manifest {path} is not valid JSON: {exc}",
            path=path) from exc
    if manifest.get("format") != FORMAT:
        stats.INTEGRITY_FAILURES.inc()
        raise CheckpointCorruptError(
            f"manifest {path}: unknown format "
            f"{manifest.get('format')!r}", path=path)
    return manifest


def all_steps(directory: str) -> List[int]:
    """Steps with a published manifest, ascending."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = MANIFEST_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def verify_manifest_files(directory: str, manifest: dict) -> None:
    """Cheap consistency probe: every shard file the manifest names must
    exist with the recorded size and whole-file digest. Raises
    :class:`CheckpointCorruptError` naming the first damaged file."""
    algorithm = manifest.get("crc_algorithm")
    for rec in manifest["shards"]:
        path = os.path.join(directory, rec["file"])
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as exc:
            raise CheckpointCorruptError(
                f"manifest names a missing shard file: {path}: {exc}",
                path=path) from exc
        if len(blob) != int(rec["bytes"]):
            stats.INTEGRITY_FAILURES.inc()
            raise CheckpointCorruptError(
                f"shard file {path} has {len(blob)} bytes; manifest "
                f"recorded {rec['bytes']} (torn or rewritten)",
                path=path)
        if not ckpt_io.verify_checksum(blob, rec["crc"], algorithm):
            stats.INTEGRITY_FAILURES.inc()
            raise CheckpointCorruptError(
                f"shard file {path} fails its whole-file CRC",
                path=path)
