"""In-memory neighbor replication of ZeRO shards (Gemini-style).

``zero.resync`` rebuilds a sharded optimizer state after an elastic
re-form by allgathering the *surviving* shards — which leaves the dead
rank's moment segments to a neutral fill (zeros). That silently perturbs
training on every recovery. This module closes the gap: at every commit
each rank ships its sharded-leaf bytes to its **left** neighbor (so rank
``i`` holds rank ``(i+1) % N``'s shard) and keeps the received copy in
host memory. When a re-form then loses one rank, the survivor holding
its replica contributes the true bytes to the resync gathers and the
restored moments are bit-identical to the last commit.

Ordering contract (see ``elastic.State.commit``): the exchange runs
*before* the in-memory snapshot. Either both complete — replica step ==
snapshot step on every survivor — or the exchange raises (a peer died)
and neither advances, so the pair can never disagree about which step a
recovery rolls back to.

The exchange is collective (two ragged allgathers over the data plane),
so it must run on the training thread; the registry reads are local.
Wire cost is one allgather of the shard payload per commit — bounded by
the sharded-state bytes, i.e. ~1/N of the replicated optimizer bytes
per rank. ``HOROVOD_CKPT_REPLICATION=0`` disables it.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional, Tuple

import numpy as np

from horovod_tpu import flight_recorder
from horovod_tpu.analysis import witness
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import _get_bool

HOROVOD_CKPT_REPLICATION = "HOROVOD_CKPT_REPLICATION"

_lock = witness.make_lock("ckpt.replica._lock")
# {key: exported-shard-arrays} received from the right neighbor, plus
# the tags needed to validate a later lookup
_entries: Dict[str, Any] = {}  # guarded-by: _lock
_src_rank: int = -1            # guarded-by: _lock
_step: int = -1                # guarded-by: _lock


def enabled() -> bool:
    """Replication is on by default in any multi-process world of >= 2
    ranks; it is meaningless single-process (every shard already lives
    in this process)."""
    if not _get_bool(HOROVOD_CKPT_REPLICATION, True):
        return False
    from horovod_tpu.core import state as state_mod
    from horovod_tpu.ops import collectives

    st = state_mod.global_state()
    if not st.initialized:
        # uninitialized use (e.g. single-process ArrayState.commit()
        # before/without hvd.init()) — nothing to replicate to
        return False
    return st.size >= 2 and collectives._multiprocess_world(st)


def exchange(entries: Dict[str, Any], step: int) -> None:
    """Ring-shift the local sharded-leaf payloads one rank to the LEFT:
    after this call, rank ``i`` holds rank ``(i+1) % N``'s ``entries``.

    Collective — every rank must call it with the same key set in the
    same commit. On success the registry atomically advances to
    ``step``; on any failure (a dead peer, a transport timeout) it is
    left at the previous commit, matching the snapshot the elastic
    rollback will restore."""
    from horovod_tpu.core import basics
    from horovod_tpu.ops import collectives

    st = basics._ensure_init()
    blob = pickle.dumps({"rank": st.rank, "step": int(step),
                         "entries": entries})
    local = np.frombuffer(blob, np.uint8)
    # ragged allgather: per-rank lengths first, then the payloads
    lens = np.asarray(collectives.allgather(
        np.array([local.shape[0]], np.int64),
        name="ckpt_replica_len")).reshape(-1)
    cat = np.asarray(collectives.allgather(
        np.ascontiguousarray(local), name="ckpt_replica_payload"))
    neighbor = (st.rank + 1) % st.size
    off = int(lens[:neighbor].sum())
    received = pickle.loads(
        cat[off:off + int(lens[neighbor])].tobytes())
    if received["rank"] != neighbor or received["step"] != int(step):
        # peers disagree about membership/step: do not poison the store
        log.warning(
            "ckpt replica exchange: unexpected payload from neighbor "
            "(rank %s step %s, wanted rank %s step %s) — keeping the "
            "previous replica", received["rank"], received["step"],
            neighbor, step)
        return
    global _entries, _src_rank, _step
    with _lock:
        _entries = received["entries"]
        _src_rank = neighbor
        _step = int(step)


def lookup(key: str, step: Optional[int] = None
           ) -> Optional[Tuple[int, Any]]:
    """(source_rank, exported-arrays) for ``key`` if this rank holds a
    replica from commit ``step`` (any step when ``step`` is None)."""
    with _lock:
        if key not in _entries:
            return None
        if step is not None and _step != int(step):
            return None
        return _src_rank, _entries[key]


def holdings() -> Tuple[int, int, Tuple[str, ...]]:
    """(source_rank, step, keys) — flight-recorder state provider."""
    with _lock:
        return _src_rank, _step, tuple(_entries)


def export_store() -> Optional[Tuple[int, int, Dict[str, Any]]]:
    """Atomic snapshot ``(source_rank, step, entries)`` for the
    checkpoint writer, or None when empty. The entry values are never
    mutated after the exchange, so handing the (shallow-copied) dict to
    a background thread is race-free."""
    with _lock:
        if not _entries:
            return None
        return _src_rank, _step, dict(_entries)


def clear(reason: str = "") -> None:
    """Drop the store — called after a re-form's sync completes (the
    old-rank tags are meaningless in the new membership) and by
    shutdown."""
    global _entries, _src_rank, _step
    with _lock:
        had = bool(_entries)
        _entries, _src_rank, _step = {}, -1, -1
    if had and reason:
        flight_recorder.emit("ckpt_replica_clear", reason=reason)
