"""Restore the newest consistent checkpoint cut.

``restore_latest`` walks the published manifests newest-first and, for
the first one whose files check out, rebuilds the full state:

* **replicated** leaves come straight from whichever rank's shard file
  round-robin-owned them;
* **sharded** leaves (ZeRO) are reassembled into FULL flat buffers from
  every rank's ``own`` segments, then re-sliced for the *current* world
  size via :func:`horovod_tpu.parallel.zero.from_full_buffers` — the
  manifest records the writing layout, so restoring into a different
  world size is a re-flatten/re-scatter, not an error;
* a missing or corrupt shard file falls back to the ``replica`` section
  of its left neighbor's file (each rank also writes rank
  ``(r+1) % N``'s bytes), so any single-file loss per checkpoint is
  recoverable;
* an unrecoverable manifest (two adjacent files gone, CRC damage in
  both copies) is skipped with a warning and the next-older cut is
  tried — a torn commit can never shadow an intact one.

All integrity damage surfaces as
:class:`~horovod_tpu.exceptions.CheckpointCorruptError` carrying the
file path and offending leaf key.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu import flight_recorder
from horovod_tpu.ckpt import manifest as mf
from horovod_tpu.ckpt import stats
from horovod_tpu.exceptions import CheckpointCorruptError
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import _get_bool

HOROVOD_CKPT_VERIFY = "HOROVOD_CKPT_VERIFY"


def latest_step(directory: str) -> Optional[int]:
    steps = mf.all_steps(directory)
    return steps[-1] if steps else None


def _read_rank_entries(directory: str, manifest: dict, verify: bool
                       ) -> Dict[int, List[dict]]:
    """Per old-rank entry lists, substituting the left neighbor's
    ``replica`` section for any unreadable file."""
    world = int(manifest["world"])
    by_rank: Dict[int, List[dict]] = {}
    failures: Dict[int, Exception] = {}
    raw: Dict[int, Tuple[dict, List[dict]]] = {}
    for rec in manifest["shards"]:
        r = int(rec["rank"])
        path = os.path.join(directory, rec["file"])
        try:
            if verify:
                blob_ok = (os.path.isfile(path)
                           and os.path.getsize(path) == int(rec["bytes"]))
                if not blob_ok:
                    raise CheckpointCorruptError(
                        f"shard file {path} missing or wrong size",
                        path=path)
            raw[r] = mf.read_shard(path, verify=verify)
        except (CheckpointCorruptError, OSError) as exc:
            failures[r] = exc
    for r, (_meta, entries) in raw.items():
        by_rank[r] = [e for e in entries
                      if e["role"] in (mf.ROLE_OWN, mf.ROLE_REPLICATED)]
    for r, exc in failures.items():
        left = (r - 1) % world
        rep = [dict(e, role=(mf.ROLE_OWN if "#" in e["key"]
                             else mf.ROLE_REPLICATED))
               for _m, entries in ([raw[left]] if left in raw else [])
               for e in entries
               if e["role"] == mf.ROLE_REPLICA
               and e.get("replica_of") == r]
        if not rep:
            raise CheckpointCorruptError(
                f"shard file for rank {r} is damaged ({exc}) and its "
                f"left neighbor (rank {left}) holds no usable replica",
                path=getattr(exc, "path", None),
                leaf=getattr(exc, "leaf", None))
        log.warning("checkpoint restore: rank %d's shard file is "
                    "damaged (%s); recovered from rank %d's replica "
                    "section", r, exc, left)
        stats.REPLICA_RESTORES.inc()
        flight_recorder.emit("ckpt_restore_replica", rank=r,
                             source=left, step=int(manifest["step"]))
        by_rank[r] = rep
    return by_rank


def _assemble(manifest: dict, by_rank: Dict[int, List[dict]]
              ) -> Tuple[Dict[str, Any], Dict[str, Dict[str, Any]]]:
    """(replicated-leaf values by key, per-sharded-key FULL buffers)."""
    world = int(manifest["world"])
    replicated: Dict[str, Any] = {}
    sub: Dict[str, Dict[int, Any]] = {}  # subkey -> {rank: array}
    for r, entries in by_rank.items():
        for e in entries:
            if e["role"] == mf.ROLE_REPLICATED:
                replicated.setdefault(e["key"], e["value"])
            else:
                sub.setdefault(e["key"], {})[r] = e["value"]
    full: Dict[str, Dict[str, Any]] = {}
    for key, layout in manifest.get("sharded", {}).items():
        groups = layout["groups"]

        def _full_buffer(subkey: str, gi: int) -> np.ndarray:
            _dt, _n, shard_elems, padded = groups[gi]
            per_rank = sub.get(subkey, {})
            if len(per_rank) < world:
                missing = sorted(set(range(world)) - set(per_rank))
                raise CheckpointCorruptError(
                    f"sharded leaf {subkey!r}: missing segments from "
                    f"ranks {missing}", leaf=subkey)
            sample = next(iter(per_rank.values()))
            buf = np.zeros((int(padded),), np.asarray(sample).dtype)
            for r in range(world):
                seg = np.asarray(per_rank[r]).reshape(-1)
                if seg.shape[0] != int(shard_elems):
                    raise CheckpointCorruptError(
                        f"sharded leaf {subkey!r}: rank {r} segment "
                        f"has {seg.shape[0]} elements, layout says "
                        f"{shard_elems}", leaf=subkey)
                buf[r * int(shard_elems):(r + 1) * int(shard_elems)] = seg
            return buf

        if layout["kind"] == "flat_adamw":
            counts = sub.get(f"{key}#count", {})
            if not counts:
                raise CheckpointCorruptError(
                    f"sharded leaf {key!r}: no count entry",
                    leaf=f"{key}#count")
            full[key] = {
                "kind": "flat_adamw",
                "count": np.asarray(next(iter(counts.values()))),
                "master": [_full_buffer(f"{key}#master/{gi}", gi)
                           for gi in range(len(groups))],
                "mu": [_full_buffer(f"{key}#mu/{gi}", gi)
                       for gi in range(len(groups))],
                "nu": [_full_buffer(f"{key}#nu/{gi}", gi)
                       for gi in range(len(groups))],
            }
        else:
            leaves: List[Any] = []
            li = 0
            while f"{key}#leaf/{li}" in sub:
                per_rank = sub[f"{key}#leaf/{li}"]
                sample = np.asarray(next(iter(per_rank.values())))
                if sample.ndim == 0:
                    leaves.append(sample)
                else:
                    gi = _group_for(groups, per_rank)
                    leaves.append(_full_buffer(f"{key}#leaf/{li}", gi))
                li += 1
            full[key] = {"kind": "generic", "leaves": leaves}
    return replicated, full


def _group_for(groups, per_rank) -> int:
    n = int(np.asarray(next(iter(per_rank.values()))).reshape(-1).shape[0])
    for gi, (_dt, _gn, shard_elems, _p) in enumerate(groups):
        if int(shard_elems) == n:
            return gi
    raise CheckpointCorruptError(
        f"generic sharded leaf with {n} elements matches no layout "
        f"group {groups!r}")


def restore_step(directory: str, step: int, target_trees: Dict[str, Any],
                 verify: Optional[bool] = None
                 ) -> Tuple[Dict[str, Any], int]:
    """Rebuild ``target_trees``-shaped state from the manifest at
    ``step``. Raises :class:`CheckpointCorruptError` when the cut is
    unrecoverable."""
    import jax

    from horovod_tpu.parallel import zero

    if verify is None:
        verify = _get_bool(HOROVOD_CKPT_VERIFY, True)
    manifest = mf.load_manifest(directory, step)
    by_rank = _read_rank_entries(directory, manifest, verify)
    replicated, full = _assemble(manifest, by_rank)
    out: Dict[str, Any] = {}
    index = 0
    for name in sorted(target_trees):
        tree = target_trees[name]
        if tree is None:
            out[name] = None
            continue
        flat, treedef = jax.tree_util.tree_flatten(
            tree, is_leaf=zero.is_sharded_state)
        new_flat = []
        for leaf in flat:
            key = f"{name}/{index}"
            index += 1
            if zero.is_sharded_state(leaf):
                if key not in full:
                    raise CheckpointCorruptError(
                        f"checkpoint has no sharded record for {key!r} "
                        f"(state structure changed?)", leaf=key)
                new_flat.append(zero.from_full_buffers(
                    leaf, full[key],
                    manifest["sharded"][key]["groups"]))
            else:
                if key not in replicated:
                    raise CheckpointCorruptError(
                        f"checkpoint has no record for leaf {key!r} "
                        f"(state structure changed?)", leaf=key)
                new_flat.append(replicated[key])
        out[name] = jax.tree_util.tree_unflatten(treedef, new_flat)
    return out, int(manifest["step"])


def restore_latest(directory: str, target_trees: Dict[str, Any],
                   verify: Optional[bool] = None
                   ) -> Tuple[Optional[Dict[str, Any]], Optional[int]]:
    """Newest consistent cut, or ``(None, None)`` when the directory
    holds no checkpoint at all. Corrupt/torn newer cuts are skipped
    (with a warning); if every published cut is damaged the LAST error
    propagates — silently training from scratch over recoverable data
    loss is worse than failing loudly."""
    steps = mf.all_steps(directory)
    if not steps:
        return None, None
    t0 = time.monotonic()
    last_error: Optional[Exception] = None
    for step in reversed(steps):
        try:
            trees, got = restore_step(directory, step, target_trees,
                                      verify=verify)
        except CheckpointCorruptError as exc:
            last_error = exc
            log.warning("checkpoint at step %d is not restorable (%s); "
                        "falling back to the previous cut", step, exc)
            continue
        stats.RESTORE_SECONDS.observe(time.monotonic() - t0)
        flight_recorder.emit("ckpt_restore", step=got,
                             directory=directory,
                             seconds=round(time.monotonic() - t0, 6))
        return trees, got
    raise last_error  # type: ignore[misc]
