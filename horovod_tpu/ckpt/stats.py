"""Metric families of the checkpoint subsystem.

Defined once here (the registry is idempotent by name, but a single
definition keeps the help strings from forking) and imported by the
writer, the restore path, and ``parallel/zero.py``'s replica-aware
resync — the latter lazily, to keep ``ckpt`` → ``zero`` the only static
import direction between the two packages.
"""

from __future__ import annotations

from horovod_tpu.metrics import CKPT_COMMIT_BUCKETS, registry as _metrics

COMMITS = _metrics().counter(
    "horovod_ckpt_commits_total",
    "Checkpoint commits attempted (per rank; includes commits later "
    "abandoned at the barrier).")
COMMIT_SECONDS = _metrics().histogram(
    "horovod_ckpt_commit_seconds",
    "End-to-end wall time of one checkpoint commit on this rank: "
    "serialize + stage + barrier + publish (the async writer observes "
    "this off-thread; the inline snapshot cost is "
    "horovod_ckpt_snapshot_seconds).", buckets=CKPT_COMMIT_BUCKETS)
SNAPSHOT_SECONDS = _metrics().histogram(
    "horovod_ckpt_snapshot_seconds",
    "Inline (training-thread) cost of one commit: device->host-slab "
    "copy-on-commit plus writer handoff — the step-time overhead the "
    "<2% goal budgets.", buckets=CKPT_COMMIT_BUCKETS)
BYTES = _metrics().counter(
    "horovod_ckpt_bytes_total",
    "Checkpoint bytes written by this rank (own shard + neighbor "
    "replica + replicated-state slice).")
REPLICA_RESTORES = _metrics().counter(
    "horovod_ckpt_replica_restores_total",
    "Dead-rank ZeRO shard segments restored from a neighbor replica "
    "(instead of falling back to zeros / recomputed fill).")
INTEGRITY_FAILURES = _metrics().counter(
    "horovod_ckpt_integrity_failures_total",
    "Checkpoint files or leaves that failed CRC/structure verification "
    "on restore.")
COMMITS_ABANDONED = _metrics().counter(
    "horovod_ckpt_commits_abandoned_total",
    "Commits abandoned before publishing (barrier timeout, a peer died "
    "mid-commit, or a generation change) — the previous manifest stays "
    "authoritative.")
RESTORE_SECONDS = _metrics().histogram(
    "horovod_ckpt_restore_seconds",
    "Wall time of restore_latest on this rank.",
    buckets=CKPT_COMMIT_BUCKETS)
