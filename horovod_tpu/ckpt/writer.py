"""Sharded two-phase checkpoint commits.

Every rank serializes ONLY what it owns — its ZeRO shard, its
round-robin slice of the replicated state, and (when replication is on)
its right neighbor's bytes — into one self-describing container
(:mod:`horovod_tpu.ckpt.manifest`). Commits are crash-consistent by
construction:

1. **stage** — write the container to a pid-named ``*.tmp`` in the
   checkpoint directory, fsync it, and announce ``staged.<rank>`` on the
   rendezvous KV (scope ``ckpt.g<generation>.s<step>``).
2. **barrier** — wait until all ``world`` ranks have staged. A timeout,
   a dead peer, or a generation change abandons the commit: the tmp is
   unlinked and the previous manifest stays authoritative.
3. **publish** — fsync'd rename tmp -> final shard name, announce
   ``published.<rank>`` (with the whole-file digest), and the leader
   (rank 0), once all ranks have published, atomically writes
   ``MANIFEST-<step>.json`` — the commit point. ``restore_latest`` only
   ever reads files a manifest names, so a rank killed at ANY instant
   of this protocol leaves the newest *published* checkpoint intact.

The KV barrier runs over HTTP on the background writer thread — it must
NOT use collectives (those belong to the training thread and would
interleave with training traffic). Without a rendezvous KV in a
multi-process world the barrier is skipped with a warning and the
restore-side manifest verification is the net.

Asynchrony: ``commit()`` only pays the device->host-slab copy inline
(the slab reuses the PR-3 fusion-buffer allocator, and holding the
lease until the write completes is the copy-on-commit guard); callers
that already hand over an immutable host snapshot (``ArrayState._saved``
— replaced, never mutated, on each ``save()``) pass ``copy=False`` and
skip even that. Serialization, staging and the barrier run on
``hvd-ckpt-writer``. The
handoff is a blocking one-slot queue — back-pressure, NOT latest-wins:
every rank must attempt the same set of steps or the barrier could
never form.

``HOROVOD_CKPT_FAULT=kill:rank=<r>:phase=<stage|barrier|publish>``
kills the matching rank at that exact protocol point (chaos matrix /
crash-consistency tests).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from horovod_tpu import flight_recorder
from horovod_tpu.ckpt import io as ckpt_io
from horovod_tpu.ckpt import manifest as mf
from horovod_tpu.ckpt import replica as replica_mod
from horovod_tpu.ckpt import stats
from horovod_tpu.elastic import fault_inject
from horovod_tpu.runtime.fusion_buffer import FusionBufferManager
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import _get_bool, _get_float, _get_int

HOROVOD_CKPT_DIR = "HOROVOD_CKPT_DIR"
HOROVOD_CKPT_ASYNC = "HOROVOD_CKPT_ASYNC"
HOROVOD_CKPT_KEEP = "HOROVOD_CKPT_KEEP"
HOROVOD_CKPT_BARRIER_TIMEOUT_SECONDS = \
    "HOROVOD_CKPT_BARRIER_TIMEOUT_SECONDS"
HOROVOD_CKPT_FAULT = "HOROVOD_CKPT_FAULT"

DEFAULT_KEEP = 2
DEFAULT_BARRIER_TIMEOUT = 30.0

_PHASES = ("stage", "barrier", "publish")


class FaultSpec(NamedTuple):
    rank: int
    phase: str
    step: Optional[int]
    code: int


def parse_fault(text: str) -> Optional[FaultSpec]:
    """``kill:rank=<r>:phase=<stage|barrier|publish>[:step=<s>][:code=<c>]``
    — the checkpoint-protocol sibling of ``fault_inject.parse_spec``
    (which targets training steps, not commit phases)."""
    text = (text or "").strip()
    if not text:
        return None
    parts = text.split(":")
    if parts[0] != "kill":
        raise ValueError(
            f"HOROVOD_CKPT_FAULT action must be 'kill', got {parts[0]!r}")
    fields: Dict[str, str] = {}
    for part in parts[1:]:
        k, _, v = part.partition("=")
        fields[k] = v
    if "rank" not in fields or "phase" not in fields:
        raise ValueError(
            "HOROVOD_CKPT_FAULT needs rank= and phase= "
            f"(got {text!r})")
    phase = fields["phase"]
    if phase not in _PHASES:
        raise ValueError(
            f"HOROVOD_CKPT_FAULT phase must be one of {_PHASES}, "
            f"got {phase!r}")
    return FaultSpec(rank=int(fields["rank"]), phase=phase,
                     step=(int(fields["step"]) if "step" in fields
                           else None),
                     code=int(fields.get("code", 1)))


def _kv_from_env(scope: str, timeout: float):
    """Rendezvous KV client for the commit barrier, or None outside a
    launcher-managed job (same env contract as elastic.runner)."""
    addr = os.environ.get("HOROVOD_RENDEZVOUS_HTTP_ADDR")
    port = os.environ.get("HOROVOD_RENDEZVOUS_HTTP_PORT")
    if not addr or not port:
        return None
    from horovod_tpu.run.rendezvous import KVStoreClient

    return KVStoreClient(addr, int(port), scope=scope, timeout=timeout)


# ---------------------------------------------------------------------------
# Snapshot plan: split the state trees into this rank's items
# ---------------------------------------------------------------------------

class _Item(NamedTuple):
    key: str
    kind: str                 # "array" | "object"
    role: str                 # manifest.ROLE_*
    value: Any
    replica_of: Optional[int]


def _expand_sharded(key: str, export: Dict[str, Any], role: str,
                    replica_of: Optional[int]) -> List[_Item]:
    """One exported sharded state -> flat subkey items
    (``{key}#master/<gi>`` ...), the unit a shard file stores."""
    items: List[_Item] = []
    if export.get("kind") == "flat_adamw":
        items.append(_Item(f"{key}#count", "array", role,
                           np.asarray(export["count"]), replica_of))
        for comp in ("master", "mu", "nu"):
            for gi, arr in enumerate(export[comp]):
                items.append(_Item(f"{key}#{comp}/{gi}", "array", role,
                                   np.asarray(arr), replica_of))
    else:
        for li, arr in enumerate(export["leaves"]):
            items.append(_Item(f"{key}#leaf/{li}", "array", role,
                               np.asarray(arr), replica_of))
    return items


def build_rank_payload(trees: Dict[str, Any], rank: int, world: int
                       ) -> Tuple[List[_Item], Dict[str, dict],
                                  Dict[str, Any]]:
    """Split host-resident state trees into this rank's shard items.

    Returns ``(items, sharded_layout, exchange_entries)``:

    * sharded leaves (``zero.is_sharded_state``) -> ``own`` subkey items
      plus a manifest layout record (world-size-change restore);
    * every other leaf is replicated state — round-robin owned: rank
      ``leaf_index % world`` writes it (role ``replicated``);
    * ``exchange_entries`` is what the neighbor-replica ring ships:
      the sharded exports by key, plus this rank's replicated slice
      under ``item:``-prefixed keys — so a lost rank's shard FILE is
      fully reconstructible from its left neighbor's.
    """
    import jax

    from horovod_tpu.parallel import zero

    items: List[_Item] = []
    layout: Dict[str, dict] = {}
    exchange: Dict[str, Any] = {}
    index = 0
    for name in sorted(trees):
        tree = trees[name]
        if tree is None:
            continue
        flat, _ = jax.tree_util.tree_flatten(
            tree, is_leaf=zero.is_sharded_state)
        for leaf in flat:
            i, index = index, index + 1
            key = f"{name}/{i}"
            if zero.is_sharded_state(leaf):
                export = zero.export_shard_arrays(leaf)
                layout[key] = zero.layout_of(leaf)
                items.extend(_expand_sharded(key, export, mf.ROLE_OWN,
                                             None))
                exchange[key] = export
            elif i % world == rank:
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    value = np.asarray(leaf)
                    items.append(_Item(key, "array", mf.ROLE_REPLICATED,
                                       value, None))
                    exchange[f"item:{key}"] = {"kind": "array",
                                               "value": value}
                else:
                    items.append(_Item(key, "object",
                                       mf.ROLE_REPLICATED, leaf, None))
                    exchange[f"item:{key}"] = {"kind": "object",
                                               "value": leaf}
    return items, layout, exchange


def _replica_items(src_rank: int, entries: Dict[str, Any]) -> List[_Item]:
    """The neighbor's exchanged entries -> ``replica`` role items."""
    items: List[_Item] = []
    for key in sorted(entries):
        payload = entries[key]
        if key.startswith("item:"):
            items.append(_Item(key[len("item:"):], payload["kind"],
                               mf.ROLE_REPLICA, payload["value"],
                               src_rank))
        elif isinstance(payload, dict) and "kind" in payload:
            items.extend(_expand_sharded(key, payload, mf.ROLE_REPLICA,
                                         src_rank))
    return items


# ---------------------------------------------------------------------------
# Commit manager
# ---------------------------------------------------------------------------

class _Pending(NamedTuple):
    step: int
    generation: int
    rank: int
    world: int
    items: List[_Item]
    layout: Dict[str, dict]
    leases: List[Any]


class CheckpointManager:
    """Per-process commit pipeline: inline host-slab snapshot + the
    staged/barrier/publish protocol on a background writer thread."""

    def __init__(self, directory: str, *,
                 async_write: Optional[bool] = None,
                 keep: Optional[int] = None,
                 barrier_timeout: Optional[float] = None,
                 generation_fn=None):
        self.directory = directory
        self._async = (_get_bool(HOROVOD_CKPT_ASYNC, True)
                       if async_write is None else bool(async_write))
        self._keep = (keep if keep is not None
                      else _get_int(HOROVOD_CKPT_KEEP, DEFAULT_KEEP))
        self._barrier_timeout = (
            barrier_timeout if barrier_timeout is not None
            else _get_float(HOROVOD_CKPT_BARRIER_TIMEOUT_SECONDS,
                            DEFAULT_BARRIER_TIMEOUT))
        self._generation_fn = generation_fn or (lambda: 0)
        self._fault = parse_fault(os.environ.get(HOROVOD_CKPT_FAULT, ""))
        self._slab = FusionBufferManager(purpose="ckpt_staging")
        # one-slot blocking handoff: commit() blocks while a prior write
        # is still queued (back-pressure keeps all ranks on the same
        # step set — a latest-wins queue would starve the barrier)
        self._queue: "queue.Queue[_Pending]" = queue.Queue(maxsize=1)
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._published_steps: List[int] = []  # this rank's, oldest first
        self._warned_no_kv = False
        os.makedirs(directory, exist_ok=True)

    # -- training-thread side ---------------------------------------------

    def commit(self, trees: Dict[str, Any], step: int,
               generation: Optional[int] = None,
               rank: Optional[int] = None,
               world: Optional[int] = None,
               copy: bool = True) -> None:
        """Snapshot ``trees`` (host pytrees, e.g. ``ArrayState._saved``)
        into reusable host slabs and hand them to the writer. Only the
        slab copy and the (possibly blocking) handoff run inline.

        ``copy=False`` skips the slab copy and hands the caller's arrays
        to the writer directly — valid ONLY when the caller guarantees
        the trees are a host-resident snapshot that is *replaced, never
        mutated* after this call (``ArrayState._saved``'s contract: each
        ``save()`` builds a fresh dict of fresh host copies, so a blob
        the writer is still serializing can never change underneath it)."""
        t0 = time.monotonic()
        if rank is None or world is None:
            from horovod_tpu.core import basics
            from horovod_tpu.ops import collectives
            st = basics._ensure_init()
            if collectives._multiprocess_world(st):
                rank = st.rank if rank is None else rank
                world = st.size if world is None else world
            else:
                # single-process world (e.g. the 8-device virtual CPU
                # mesh): ONE writer process owns every shard — a world
                # of st.size would await shard files no other process
                # exists to write and abandon every commit
                rank = 0 if rank is None else rank
                world = 1 if world is None else world
        if generation is None:
            generation = self._generation_fn()
        items, layout, _exchange = build_rank_payload(trees, rank, world)
        if copy:
            items, leases = self._slab_copy(items)
        else:
            leases = []
        rep = replica_mod.export_store()
        if rep is not None and rep[1] == int(step):
            items = items + _replica_items(rep[0], rep[2])
        pending = _Pending(step=int(step), generation=int(generation),
                           rank=int(rank), world=int(world),
                           items=items, layout=layout, leases=leases)
        if self._async:
            self._ensure_thread()
            self._queue.put(pending)  # blocks when the slot is full
        else:
            self._write_commit(pending)
        stats.SNAPSHOT_SECONDS.observe(time.monotonic() - t0)
        try:
            # goodput ledger: only the inline training-thread seconds are
            # checkpoint badput — in async mode that is the slab copy +
            # handoff (including any full-slot block), not the write
            from horovod_tpu import goodput

            goodput.record_span("ckpt_stall", time.monotonic() - t0)
        except Exception:
            pass  # accounting must never fail a commit

    def _slab_copy(self, items: List[_Item]
                   ) -> Tuple[List[_Item], List[Any]]:
        """Copy array values into fusion-buffer leases grouped by dtype.
        The returned items view the slab, so the caller's arrays may be
        mutated or freed the moment commit() returns; the leases are
        held until the write completes (copy-on-commit guard)."""
        by_dtype: Dict[str, List[int]] = {}
        for idx, item in enumerate(items):
            if item.kind == "array":
                by_dtype.setdefault(np.dtype(item.value.dtype).name,
                                    []).append(idx)
        out = list(items)
        leases: List[Any] = []
        for dts, idxs in sorted(by_dtype.items()):
            total = sum(int(np.asarray(items[i].value).size)
                        for i in idxs)
            if total == 0:
                continue
            lease = self._slab.acquire(1, total, np.dtype(dts))
            leases.append(lease)
            flat = lease.array[0]
            off = 0
            for i in idxs:
                src = np.asarray(items[i].value)
                n = int(src.size)
                np.copyto(flat[off:off + n], src.reshape(-1))
                out[i] = items[i]._replace(
                    value=flat[off:off + n].reshape(src.shape))
                off += n
        return out, leases

    def wait(self) -> None:
        """Block until every handed-off commit has been written (or
        abandoned)."""
        self._queue.join()

    def close(self) -> None:
        self.wait()
        self._closed = True

    # -- writer thread -----------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="hvd-ckpt-writer")
            self._thread.start()

    def _writer_loop(self) -> None:
        while not self._closed:
            try:
                pending = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._write_commit(pending)
            except Exception as exc:
                stats.COMMITS_ABANDONED.inc()
                log.warning("checkpoint commit at step %s abandoned: %s",
                            pending.step, exc)
            finally:
                self._queue.task_done()

    def _maybe_fault(self, phase: str, step: int) -> None:
        spec = self._fault
        if spec is None or spec.phase != phase:
            return
        if spec.rank != fault_inject.initial_rank():
            return
        if spec.step is not None and spec.step != step:
            return
        log.error("ckpt fault injection: killing rank %d at commit "
                  "phase %r (step %d)", spec.rank, phase, step)
        flight_recorder.emit("ckpt_fault_kill", phase=phase, step=step,
                             rank=spec.rank)
        flight_recorder.dump_on_failure("ckpt_fault_kill")
        import sys
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(spec.code)

    def _write_commit(self, p: _Pending) -> bool:
        stats.COMMITS.inc()
        t0 = time.monotonic()
        tmp = None
        try:
            ckpt_io.clean_stale_tmps(self.directory)
            entries = []
            for item in p.items:
                if item.kind == "array":
                    entries.append(mf.array_entry(
                        item.key, item.value, role=item.role,
                        replica_of=item.replica_of))
                else:
                    entries.append(mf.object_entry(
                        item.key, item.value, role=item.role,
                        replica_of=item.replica_of))
            blob = mf.pack_shard(entries, meta={
                "step": p.step, "generation": p.generation,
                "rank": p.rank, "world": p.world})
            final_name = mf.shard_name(p.step, p.rank, p.world)
            file_crc = ckpt_io.checksum(blob)
            record = json.dumps({
                "rank": p.rank, "file": final_name,
                "bytes": len(blob), "crc": file_crc}).encode()
            # -- stage ------------------------------------------------
            fd, tmp = ckpt_io.make_tmp(self.directory, base=final_name)
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            self._maybe_fault("stage", p.step)
            kv = self._barrier_kv(p)
            if kv is not None:
                kv.set(f"staged.{p.rank}", record)
            self._maybe_fault("barrier", p.step)
            if not self._await_count(kv, "staged.", p):
                self._abandon(p, tmp, "barrier")
                return False
            # -- publish ----------------------------------------------
            final = os.path.join(self.directory, final_name)
            os.replace(tmp, final)
            tmp = None
            ckpt_io.fsync_dir(self.directory)
            self._maybe_fault("publish", p.step)
            if kv is not None:
                kv.set(f"published.{p.rank}", record)
            if p.rank == 0:
                if not self._publish_manifest(kv, p, record):
                    self._abandon(p, None, "publish")
                    return False
            self._published_steps.append(p.step)
            self._gc(p)
            stats.BYTES.inc(len(blob))
            stats.COMMIT_SECONDS.observe(time.monotonic() - t0)
            flight_recorder.emit(
                "ckpt_commit", step=p.step, generation=p.generation,
                rank=p.rank, bytes=len(blob),
                seconds=round(time.monotonic() - t0, 6))
            return True
        except Exception:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            raise
        finally:
            for lease in p.leases:
                try:
                    self._slab.release(lease)
                except Exception:  # pragma: no cover - release best-effort
                    pass

    # -- protocol helpers --------------------------------------------------

    def _barrier_kv(self, p: _Pending):
        if p.world <= 1:
            return None
        scope = f"ckpt.g{p.generation}.s{p.step}"
        kv = _kv_from_env(scope, self._barrier_timeout)
        if kv is None and not self._warned_no_kv:
            self._warned_no_kv = True
            log.warning(
                "checkpointing in a %d-rank world without a rendezvous "
                "KV (HOROVOD_RENDEZVOUS_HTTP_ADDR unset): the commit "
                "barrier is skipped; restore-side manifest verification "
                "is the only consistency net", p.world)
        return kv

    def _await_count(self, kv, prefix: str, p: _Pending) -> bool:
        """True once all ``world`` ranks announced ``prefix``; False on
        timeout or a generation change (the commit must be abandoned)."""
        if kv is None:
            return True
        deadline = time.monotonic() + self._barrier_timeout
        while True:
            if self._generation_fn() != p.generation:
                return False
            try:
                names = kv.keys()
            except Exception:
                names = []
            if sum(1 for k in names if k.startswith(prefix)) >= p.world:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.1)

    def _abandon(self, p: _Pending, tmp: Optional[str],
                 phase: str) -> None:
        stats.COMMITS_ABANDONED.inc()
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        log.warning("checkpoint commit at step %d abandoned at the %s "
                    "phase (timeout %.1fs, generation %d); previous "
                    "manifest stays authoritative", p.step, phase,
                    self._barrier_timeout, p.generation)
        flight_recorder.emit("ckpt_commit_abandoned", step=p.step,
                             generation=p.generation, rank=p.rank,
                             phase=phase)

    def _publish_manifest(self, kv, p: _Pending, own_record) -> bool:
        """Leader side of the publish phase: collect every rank's
        published shard record, then atomically write the manifest —
        THE commit point."""
        shards: List[dict] = []
        if kv is not None:
            if not self._await_count(kv, "published.", p):
                return False
            for r in range(p.world):
                try:
                    shards.append(json.loads(
                        kv.get(f"published.{r}", wait=False)))
                except Exception as exc:
                    log.warning("ckpt publish: lost rank %d's record "
                                "(%s); abandoning manifest", r, exc)
                    return False
        elif p.world > 1:
            # no KV: shared-filesystem fallback — wait for all final
            # shard files to appear, then digest them directly
            if not self._await_files(p):
                return False
            for r in range(p.world):
                path = os.path.join(
                    self.directory, mf.shard_name(p.step, r, p.world))
                try:
                    with open(path, "rb") as f:
                        blob = f.read()
                except OSError as exc:
                    log.warning("ckpt publish: shard file %s unreadable "
                                "(%s); abandoning manifest", path, exc)
                    return False
                shards.append({"rank": r,
                               "file": os.path.basename(path),
                               "bytes": len(blob),
                               "crc": ckpt_io.checksum(blob)})
        else:
            shards.append(json.loads(own_record))
        manifest = mf.build_manifest(p.step, p.generation, p.world,
                                     shards, p.layout)
        mf.write_manifest(self.directory, manifest)
        if kv is not None:
            try:
                kv.clear_scope()
            except Exception:
                pass  # best-effort: the TTL reaper collects leftovers
        return True

    def _await_files(self, p: _Pending) -> bool:
        deadline = time.monotonic() + self._barrier_timeout
        want = [os.path.join(self.directory,
                             mf.shard_name(p.step, r, p.world))
                for r in range(p.world)]
        while True:
            if all(os.path.exists(w) for w in want):
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.1)

    def _gc(self, p: _Pending) -> None:
        """Keep the last ``keep`` checkpoints. Every rank prunes its OWN
        old shard files (they may live on rank-local disks); the leader
        additionally prunes superseded manifests."""
        if self._keep <= 0:
            return
        drop_own = self._published_steps[:-self._keep]
        self._published_steps = self._published_steps[-self._keep:]
        for step in drop_own:
            path = os.path.join(self.directory,
                                mf.shard_name(step, p.rank, p.world))
            try:
                os.unlink(path)
            except OSError:
                pass
        if p.rank != 0:
            return
        steps = mf.all_steps(self.directory)
        for step in steps[:-self._keep]:
            try:
                manifest = mf.load_manifest(self.directory, step)
                files = [rec["file"] for rec in manifest["shards"]]
            except Exception:
                files = []
            try:
                os.unlink(mf.manifest_path(self.directory, step))
            except OSError:
                pass
            for name in files:
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:
                    pass
