"""Collective transport observatory: per-op algbw/busbw accounting,
roofline utilization, and bandwidth-degradation alerts.

The time plane (profiler.py) answers "where did the time go", the memory
plane (memory.py) "where did the bytes sit", the request plane
(tracing.py) "which request suffered" — this module answers the question
the whole runtime exists to optimize: **how many bytes per second does
each collective actually move, on which wire, and is that getting
worse?** Reference Horovod's autotuner literally scores
``ParameterManager.update(nbytes, seconds)`` — bandwidth IS the
objective; this is the live measurement of it.

One process-wide :class:`CommsTracker` ingests ``(op, lane, nbytes,
seconds)`` records from every transport lane that moves bytes:

* ``device`` — single-controller fused XLA allreduce
  (``runtime/executor._dispatch_allreduce``) and the eager
  ``_op_event``-bracketed collectives (``ops/collectives.py``);
* ``host_ring`` — the NetComm TCP ring data plane
  (``_execute_*_host``);
* ``spmd`` — the one-device-per-process sub-mesh fused allreduce
  (``_dispatch_allreduce_spmd``);
* ``zero`` — ZeRO reduce-scatter / allgather phases
  (``parallel/zero.py``);
* ``bucket_wire`` — grad-bucket release traffic end-to-end
  (``parallel/buckets.py``; the underlying dispatches also appear on
  their carrying lane — the two views answer different questions);
* ``kv`` — control-plane KV store traffic (``run/rendezvous.py``).

Two bandwidths per record (the NCCL-tests convention):

* **algorithm bandwidth** ``algbw = payload_bytes / seconds`` — what the
  caller experiences;
* **bus bandwidth** ``busbw = algbw * factor(op, N)`` — what the wire
  carries, comparable across ops and world sizes: ``2(N-1)/N`` for
  allreduce, ``(N-1)/N`` for reduce-scatter / allgather / alltoall, 1
  for broadcast and point-to-point, and 0 for the ``N == 1`` degenerate
  world (nothing crosses a bus).

Records are keyed by ``(op, lane, size_bucket)`` (power-of-two byte
buckets) into bounded rolling windows; per-lane busbw is EWMA-smoothed
and compared against a **roofline** — seeded from the persisted
``probe_and_seed`` artifact (``HOROVOD_PROBE_CACHE``, autotune/probe.py)
where one exists, the peak smoothed busbw this lane ever reached
otherwise — to export ``horovod_comms_utilization_fraction{lane}``. An
EWMA degradation detector (the comms analogue of the SLO burn alert,
tracing.py) emits ONE ``comms_degraded`` flight event per downward
``HOROVOD_COMMS_DEGRADED_FRACTION`` crossing, naming the op/lane/bucket
that slowed, and re-arms when the lane recovers — "step time regressed"
becomes "host_ring allreduce busbw dropped 3x".

Surfaces (mirroring the established planes end-to-end):
``horovod_comms_*`` metric families + ``GET /comms`` (metrics.py); a
``comms`` flight-recorder state provider in every dump; a per-rank "bus
bandwidth (GB/s)" counter track in the merged Perfetto trace
(profiler.merge_profile_dir); a comms panel in tools/hvd_top.py; and
:func:`format_comms_report` — the cross-rank postmortem section naming
the slowest lane and the rank furthest below roofline
(``tpurun --postmortem``).

Env knobs (registered in utils/env.py, table in docs/comms.md):
``HOROVOD_COMMS`` (accounting on/off, default on),
``HOROVOD_COMMS_WINDOW`` (rolling records per key, default 128),
``HOROVOD_COMMS_EWMA_ALPHA`` (smoothing, default 0.25),
``HOROVOD_COMMS_DEGRADED_FRACTION`` (alert threshold, default 0.5).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from horovod_tpu.analysis import witness
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils.env import _get_bool, _get_float, _get_int

HOROVOD_COMMS = "HOROVOD_COMMS"
HOROVOD_COMMS_WINDOW = "HOROVOD_COMMS_WINDOW"
HOROVOD_COMMS_EWMA_ALPHA = "HOROVOD_COMMS_EWMA_ALPHA"
HOROVOD_COMMS_DEGRADED_FRACTION = "HOROVOD_COMMS_DEGRADED_FRACTION"

DEFAULT_WINDOW = 128
DEFAULT_EWMA_ALPHA = 0.25
DEFAULT_DEGRADED_FRACTION = 0.5
_SAMPLE_RING = 512   # bounded per-record trail for the trace counter track
_WARMUP_OPS = 8      # lane records before the degradation detector arms
_TOP_KEYS = 32       # (op, lane, bucket) rows surfaced in the ledger

LANES = ("device", "host_ring", "spmd", "zero", "bucket_wire", "kv",
         "hier_intra", "hier_cross")

_ALGBW = _metrics().gauge(
    "horovod_comms_algbw_gbs",
    "Rolling algorithm bandwidth (payload bytes / wall seconds, GB/s) "
    "per collective op and transport lane.",
    labelnames=("op", "lane"))
_BUSBW = _metrics().gauge(
    "horovod_comms_busbw_gbs",
    "Rolling bus bandwidth (algbw x op ring factor, GB/s) per collective "
    "op and transport lane — comparable across ops and world sizes.",
    labelnames=("op", "lane"))
_BYTES = _metrics().counter(
    "horovod_comms_bytes_total",
    "Cumulative payload bytes moved per collective op and lane.",
    labelnames=("op", "lane"))
_OPS = _metrics().counter(
    "horovod_comms_ops_total",
    "Collective operations recorded per op and lane.",
    labelnames=("op", "lane"))
_UTIL = _metrics().gauge(
    "horovod_comms_utilization_fraction",
    "Smoothed per-lane bus bandwidth as a fraction of the lane roofline "
    "(probe-seeded where available, peak-observed otherwise).",
    labelnames=("lane",))
_DEGRADED = _metrics().counter(
    "horovod_comms_degraded_total",
    "Downward HOROVOD_COMMS_DEGRADED_FRACTION crossings per lane (one "
    "per sustained degradation; re-armed on recovery).",
    labelnames=("lane",))


def bus_factor(op: str, world: int) -> float:
    """Bus-traffic factor mapping algorithm bandwidth to bus bandwidth
    (the NCCL-tests convention). ``world <= 1`` degenerates to 0 for
    every op: a one-rank collective moves nothing across any bus."""
    n = int(world)
    if n <= 1:
        return 0.0
    op = op.lower()
    if op == "allreduce":
        return 2.0 * (n - 1) / n
    if op in ("reducescatter", "allgather", "alltoall"):
        return float(n - 1) / n
    # broadcast and point-to-point (kv get/put): every payload byte
    # crosses the bus exactly once
    return 1.0


def size_bucket(nbytes: int) -> int:
    """Power-of-two byte bucket (the ceiling), so steady-state keys are
    bounded: a 3 MiB and a 3.5 MiB allreduce share the 4 MiB bucket."""
    n = max(int(nbytes), 1)
    return 1 << (n - 1).bit_length()


def _fmt_bucket(bucket: int) -> str:
    n = float(bucket)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024.0 or unit == "GiB":
            return ("%d%s" % (n, unit)) if n == int(n) else \
                ("%.1f%s" % (n, unit))
        n /= 1024.0
    return "%dB" % bucket


class CommsTracker:
    """Process-wide per-collective bandwidth ledger.

    Hot-path cost per record is one short lock: a deque append, a few
    dict stores and an EWMA multiply; gauge updates happen outside any
    subsystem lock. Flight events are emitted AFTER the tracker lock is
    released (lock hygiene: emit paths take the recorder's own lock)."""

    def __init__(self) -> None:
        self._lock = witness.make_lock("CommsTracker._lock")
        # (op, lane, bucket) -> deque[(wall_time, nbytes, seconds, busbw)]
        self._windows: Dict[Tuple[str, str, int], deque] = {}  # guarded-by: _lock
        self._key_ewma: Dict[Tuple[str, str, int], float] = {}  # guarded-by: _lock
        # (op, lane) -> [bytes_total, ops_total, seconds_total]
        self._totals: Dict[Tuple[str, str], List[float]] = {}  # guarded-by: _lock
        self._lane_ewma: Dict[str, float] = {}       # guarded-by: _lock
        self._lane_peak: Dict[str, float] = {}       # guarded-by: _lock
        self._lane_ops: Dict[str, int] = {}          # guarded-by: _lock
        self._roofline: Dict[str, float] = {}        # guarded-by: _lock
        self._roofline_source: Dict[str, str] = {}   # guarded-by: _lock
        self._alerting: Dict[str, bool] = {}         # guarded-by: _lock
        self._last_degraded: Dict[str, dict] = {}    # guarded-by: _lock
        self._degraded_count: Dict[str, int] = {}    # guarded-by: _lock
        self._samples: deque = deque(maxlen=_SAMPLE_RING)  # guarded-by: _lock
        self.enabled = True
        self.rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        self.world = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        self.window = DEFAULT_WINDOW
        self.ewma_alpha = DEFAULT_EWMA_ALPHA
        self.degraded_fraction = DEFAULT_DEGRADED_FRACTION

    # -- roofline ----------------------------------------------------------
    def seed_roofline(self, lane: str, busbw_gbs: float,
                      source: str = "probe") -> None:
        """Pin a lane's roofline (GB/s of bus bandwidth) from an external
        measurement — the persisted ``probe_and_seed`` artifact or a live
        probe. Unseeded lanes fall back to their peak observed busbw."""
        busbw_gbs = float(busbw_gbs)
        if busbw_gbs <= 0:
            return
        with self._lock:
            self._roofline[lane] = busbw_gbs
            self._roofline_source[lane] = source

    def _roofline_locked(self, lane: str) -> Tuple[Optional[float], str]:
        seeded = self._roofline.get(lane)
        if seeded:
            return seeded, self._roofline_source.get(lane, "probe")
        peak = self._lane_peak.get(lane)
        if peak:
            return peak, "peak_observed"
        return None, "none"

    # -- recording ---------------------------------------------------------
    def record(self, op: str, lane: str, nbytes: int, seconds: float,
               world: Optional[int] = None) -> None:
        """Ingest one completed collective: compute algbw/busbw, roll the
        (op, lane, bucket) window, update the lane EWMA + utilization,
        and run the degradation detector."""
        if not self.enabled:
            return
        nbytes = int(nbytes)
        seconds = float(seconds)
        if nbytes <= 0 or seconds <= 0:
            return
        op = str(op).lower()
        n = int(world) if world else self.world
        algbw = nbytes / seconds / 1e9
        busbw = algbw * bus_factor(op, n)
        bucket = size_bucket(nbytes)
        now = time.time()
        key = (op, lane, bucket)
        alert = None  # (lane, op, bucket, busbw, roofline, util) after lock
        recovered = False
        with self._lock:
            win = self._windows.get(key)
            if win is None or win.maxlen != self.window:
                win = deque(win or (), maxlen=self.window)
                self._windows[key] = win
            win.append((now, nbytes, seconds, busbw))
            prev = self._key_ewma.get(key)
            a = self.ewma_alpha
            self._key_ewma[key] = busbw if prev is None \
                else (1.0 - a) * prev + a * busbw
            tot = self._totals.setdefault((op, lane), [0, 0, 0.0])
            tot[0] += nbytes
            tot[1] += 1
            tot[2] += seconds
            lane_prev = self._lane_ewma.get(lane)
            lane_ewma = busbw if lane_prev is None \
                else (1.0 - a) * lane_prev + a * busbw
            self._lane_ewma[lane] = lane_ewma
            if lane_ewma > self._lane_peak.get(lane, 0.0):
                self._lane_peak[lane] = lane_ewma
            ops_seen = self._lane_ops.get(lane, 0) + 1
            self._lane_ops[lane] = ops_seen
            roofline, _src = self._roofline_locked(lane)
            util = (lane_ewma / roofline) if roofline else None
            if util is not None and ops_seen >= _WARMUP_OPS:
                if util < self.degraded_fraction \
                        and not self._alerting.get(lane, False):
                    self._alerting[lane] = True
                    self._degraded_count[lane] = \
                        self._degraded_count.get(lane, 0) + 1
                    self._last_degraded[lane] = {
                        "wall_time": now, "op": op,
                        "size_bucket": _fmt_bucket(bucket),
                        "busbw_gbs": round(lane_ewma, 4),
                        "roofline_gbs": round(roofline, 4),
                        "utilization": round(util, 4),
                    }
                    alert = (lane, op, bucket, lane_ewma, roofline, util)
                elif util >= self.degraded_fraction \
                        and self._alerting.get(lane, False):
                    self._alerting[lane] = False  # re-arm
                    recovered = True
            self._samples.append((now, round(busbw, 4), lane))
        # metrics + flight events outside the tracker lock
        _ALGBW.labels(op=op, lane=lane).set(round(algbw, 4))
        _BUSBW.labels(op=op, lane=lane).set(round(busbw, 4))
        _BYTES.labels(op=op, lane=lane).inc(nbytes)
        _OPS.labels(op=op, lane=lane).inc()
        if util is not None:
            _UTIL.labels(lane=lane).set(round(util, 4))
        if alert is not None:
            lane_a, op_a, bucket_a, bw, roof, u = alert
            _DEGRADED.labels(lane=lane_a).inc()
            from horovod_tpu import flight_recorder

            flight_recorder.emit(
                "comms_degraded", lane=lane_a, op=op_a,
                size_bucket=_fmt_bucket(bucket_a),
                busbw_gbs=round(bw, 4), roofline_gbs=round(roof, 4),
                utilization=round(u, 4),
                threshold=self.degraded_fraction)
        elif recovered:
            from horovod_tpu import flight_recorder

            flight_recorder.emit("comms_recovered", lane=lane)

    # -- snapshots ---------------------------------------------------------
    def ledger(self) -> dict:
        """Per-lane bandwidth state + the busiest (op, lane, bucket) keys
        — the payload of the flight-recorder ``comms`` state provider, so
        every dump carries it."""
        with self._lock:
            lanes = {}
            for lane in sorted(set(self._lane_ewma) | set(self._roofline)):
                roofline, src = self._roofline_locked(lane)
                ewma = self._lane_ewma.get(lane)
                util = (ewma / roofline) if (ewma and roofline) else None
                bytes_total = sum(
                    t[0] for (o, ln), t in self._totals.items()
                    if ln == lane)
                ops_total = sum(
                    t[1] for (o, ln), t in self._totals.items()
                    if ln == lane)
                lanes[lane] = {
                    "busbw_gbs": round(ewma, 4) if ewma else None,
                    "peak_busbw_gbs": round(
                        self._lane_peak.get(lane, 0.0), 4) or None,
                    "roofline_gbs": round(roofline, 4) if roofline
                    else None,
                    "roofline_source": src,
                    "utilization": round(util, 4) if util is not None
                    else None,
                    "bytes_total": int(bytes_total),
                    "ops_total": int(ops_total),
                    "alerting": self._alerting.get(lane, False),
                    "degraded_count": self._degraded_count.get(lane, 0),
                    "last_degraded": self._last_degraded.get(lane),
                }
            keys = []
            for (op, lane, bucket), win in self._windows.items():
                if not win:
                    continue
                w_bytes = sum(r[1] for r in win)
                w_secs = sum(r[2] for r in win)
                algbw = (w_bytes / w_secs / 1e9) if w_secs > 0 else 0.0
                # per-record busbw already folded in each record's own
                # world size; time-weighting recovers the windowed rate
                busbw = (sum(r[3] * r[2] for r in win) / w_secs) \
                    if w_secs > 0 else 0.0
                keys.append({
                    "op": op, "lane": lane,
                    "size_bucket": _fmt_bucket(bucket),
                    "algbw_gbs": round(algbw, 4),
                    "busbw_gbs": round(busbw, 4),
                    "ewma_busbw_gbs": round(
                        self._key_ewma.get((op, lane, bucket), 0.0), 4),
                    "ops": len(win),
                    "window_bytes": int(w_bytes),
                })
            keys.sort(key=lambda k: -k["window_bytes"])
        return {
            "rank": self.rank,
            "world": self.world,
            "wall_time": time.time(),
            "degraded_fraction": self.degraded_fraction,
            "lanes": lanes,
            "keys": keys[:_TOP_KEYS],
        }

    def samples(self) -> List[list]:
        """The per-record trail: [wall_time, busbw_gbs, lane] rows — the
        merged-trace "bus bandwidth (GB/s)" counter track reads this."""
        with self._lock:
            return [list(s) for s in self._samples]

    def reset(self) -> None:
        """Drop all accumulated state (tests and bench A/B harnesses)."""
        with self._lock:
            self._windows.clear()
            self._key_ewma.clear()
            self._totals.clear()
            self._lane_ewma.clear()
            self._lane_peak.clear()
            self._lane_ops.clear()
            self._alerting.clear()
            self._last_degraded.clear()
            self._degraded_count.clear()
            self._samples.clear()


_tracker = CommsTracker()


def tracker() -> CommsTracker:
    return _tracker


def record(op: str, lane: str, nbytes: int, seconds: float,
           world: Optional[int] = None) -> None:
    """Module-level shorthand for instrumentation points; no-op when the
    tracker is disabled."""
    _tracker.record(op, lane, nbytes, seconds, world=world)


def configure(rank: Optional[int] = None,
              world: Optional[int] = None) -> None:
    """Adopt the rank/world, parse the ``HOROVOD_COMMS_*`` knobs, seed
    lane rooflines from the persisted probe artifact
    (``HOROVOD_PROBE_CACHE``) when one matches this world size, and
    register the flight-recorder ``comms`` state provider. Called from
    ``hvd.init()`` (idempotent across elastic re-inits)."""
    t = _tracker
    if rank is not None:
        t.rank = int(rank)
    if world is not None:
        t.world = int(world)
    t.enabled = _get_bool(HOROVOD_COMMS, True)
    t.window = max(1, _get_int(HOROVOD_COMMS_WINDOW, DEFAULT_WINDOW))
    t.ewma_alpha = min(1.0, max(0.0, _get_float(
        HOROVOD_COMMS_EWMA_ALPHA, DEFAULT_EWMA_ALPHA)))
    t.degraded_fraction = _get_float(HOROVOD_COMMS_DEGRADED_FRACTION,
                                     DEFAULT_DEGRADED_FRACTION)
    try:
        from horovod_tpu.autotune import probe

        roofline = probe.load_cached_roofline(world=t.world)
        if roofline and roofline.get("allreduce_busbw_gbps"):
            # the probe measures the XLA-mesh collective path: that
            # roofline bounds the fused device and SPMD lanes; the host
            # ring and control plane self-calibrate from their own peaks
            for lane in ("device", "spmd"):
                t.seed_roofline(lane, roofline["allreduce_busbw_gbps"],
                                source="probe_cache")
        if roofline:
            # schema-2 artifacts carry separately-probed hierarchy hops:
            # the fast intra-group lane and the (possibly throttled)
            # cross-group lane have very different rooflines, and folding
            # both under one number would blind the degradation detector
            # on whichever hop it mis-bounds
            for lane, key in (("hier_intra", "hier_intra_busbw_gbps"),
                              ("hier_cross", "hier_cross_busbw_gbps")):
                if roofline.get(key):
                    t.seed_roofline(lane, roofline[key],
                                    source="probe_cache")
    except Exception:
        pass  # a stale/corrupt artifact must not break init
    from horovod_tpu import flight_recorder

    if t.enabled:
        flight_recorder.set_state_provider("comms", t.ledger)
    else:
        flight_recorder.set_state_provider("comms", None)


_DATA_LANES = frozenset(("device", "host_ring", "spmd", "zero",
                         "bucket_wire", "hier_intra", "hier_cross"))


def data_lane_busbw_gbs() -> Optional[float]:
    """Byte-weighted smoothed bus bandwidth (GB/s) across the training
    data-plane lanes (the serving ``kv`` lane is excluded). This is the
    autotuner's wire-utilization score component; ``None`` until a
    data-plane collective has been recorded."""
    t = _tracker
    with t._lock:
        lane_bytes: Dict[str, float] = {}
        for (op, lane), tot in t._totals.items():
            if lane in _DATA_LANES:
                lane_bytes[lane] = lane_bytes.get(lane, 0.0) + tot[0]
        num = den = 0.0
        for lane, nbytes in lane_bytes.items():
            ewma = t._lane_ewma.get(lane)
            if ewma and nbytes > 0:
                num += ewma * nbytes
                den += nbytes
    return (num / den) if den > 0 else None


def comms_state() -> dict:
    """Document for the metrics server's ``GET /comms`` route: the
    ledger + the recent busbw sample trail."""
    state = _tracker.ledger()
    state["samples"] = _tracker.samples()[-64:]
    state["enabled"] = _tracker.enabled
    return state


# -- cross-rank postmortem ----------------------------------------------------

def format_comms_report(dumps: List[dict]) -> str:
    """Cross-rank comms report from flight-recorder dumps' ``comms``
    state: per-rank lane busbw vs roofline, the slowest lane across the
    fleet, and the rank furthest below its roofline. Empty string when
    no dump carries a comms ledger (pre-comms-plane dumps)."""
    ranks = []
    for d in dumps:
        comms = (d.get("state") or {}).get("comms")
        if not isinstance(comms, dict):
            continue
        ranks.append((d.get("launch_rank", d.get("rank", "?")), comms))
    if not ranks:
        return ""
    lines = ["=== comms report (%d rank%s) ==="
             % (len(ranks), "" if len(ranks) == 1 else "s")]
    lane_utils: Dict[str, List[float]] = {}
    worst = None  # (rank, lane, utilization, busbw, roofline)
    for rank, comms in sorted(ranks, key=lambda r: str(r[0])):
        lanes = comms.get("lanes", {})
        parts = []
        for lane, rec in sorted(lanes.items()):
            if not isinstance(rec, dict) or rec.get("busbw_gbs") is None:
                continue
            util = rec.get("utilization")
            parts.append("%s %.2f GB/s%s%s" % (
                lane, rec["busbw_gbs"],
                ("/%.2f (%.0f%%)" % (rec["roofline_gbs"], 100.0 * util))
                if isinstance(util, (int, float)) else "",
                " DEGRADED" if rec.get("alerting") else ""))
            if isinstance(util, (int, float)):
                lane_utils.setdefault(lane, []).append(util)
                if worst is None or util < worst[2]:
                    worst = (rank, lane, util, rec["busbw_gbs"],
                             rec.get("roofline_gbs"))
        lines.append("rank %s: %s" % (
            rank, "; ".join(parts) if parts else "no traffic recorded"))
        for lane, rec in sorted(lanes.items()):
            last = rec.get("last_degraded") if isinstance(rec, dict) \
                else None
            if isinstance(last, dict):
                lines.append(
                    "rank %s: degraded %s %s %s — %.2f GB/s vs %.2f "
                    "roofline (%.0f%% < threshold)" % (
                        rank, lane, last.get("op", "?"),
                        last.get("size_bucket", "?"),
                        last.get("busbw_gbs", 0.0),
                        last.get("roofline_gbs", 0.0),
                        100.0 * last.get("utilization", 0.0)))
    if lane_utils:
        slowest = min(lane_utils,
                      key=lambda ln: sum(lane_utils[ln])
                      / len(lane_utils[ln]))
        mean_util = sum(lane_utils[slowest]) / len(lane_utils[slowest])
        lines.append("slowest lane: %s (%.0f%% of roofline across %d "
                     "rank%s)" % (slowest, 100.0 * mean_util,
                                  len(lane_utils[slowest]),
                                  "" if len(lane_utils[slowest]) == 1
                                  else "s"))
    if worst is not None:
        rank, lane, util, busbw, roof = worst
        lines.append(
            "furthest below roofline: rank %s %s (%.2f of %s GB/s, "
            "%.0f%%)" % (rank, lane, busbw,
                         ("%.2f" % roof) if isinstance(roof, (int, float))
                         else "?", 100.0 * util))
    return "\n".join(lines)
