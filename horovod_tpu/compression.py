"""Gradient compression algorithms.

Mirrors the reference's ``hvd.Compression`` (reference:
horovod/torch/compression.py:28-78, horovod/tensorflow/compression.py):
a compressor is applied to a tensor before it enters the collective and
undone afterwards. On TPU the natural 16-bit type is **bfloat16** (same
exponent range as fp32, native MXU type), so ``Compression.fp16`` maps to
bf16 by default; IEEE fp16 is available as ``Compression.ieee_fp16`` for
bit-parity experiments.

Inside ``jit`` the cast fuses into the surrounding collective, so
compression halves ICI/DCN bytes at zero extra HBM traffic.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: ``compress`` returns (compressed_tensor, context) and
    ``decompress`` undoes it using the context."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: torch/compression.py:35-43)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to bfloat16 for the collective, cast back
    after (reference: torch/compression.py:45-60, with fp16→bf16 for TPU)."""

    wire_dtype = jnp.bfloat16

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating) and tensor.dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), ctx
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class IEEEFP16Compressor(FP16Compressor):
    """IEEE float16 wire format (exact reference behavior; narrower exponent
    than bf16 — prefer ``Compression.fp16`` on TPU)."""

    wire_dtype = jnp.float16


class Compression:
    """Optional gradient compression algorithm used during allreduce
    (reference: torch/compression.py:63-78)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    ieee_fp16 = IEEEFP16Compressor
