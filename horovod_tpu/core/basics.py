"""Lifecycle + topology API: init / shutdown / rank / size / ...

TPU-native analogue of the reference's C lifecycle API and ctypes wrapper
(reference: horovod/common/operations.cc:611-732, horovod/common/basics.py).

Worker model
------------
The reference runs one process per accelerator; ``rank``/``size`` are MPI
ranks. JAX is a single-controller SPMD system: one process typically drives
many devices, and on a pod each host runs one process. We therefore define
**worker == device (TPU chip)**:

* ``size()``       — total number of devices in the global mesh.
* ``local_size()`` — extent of the ``local`` (ICI) mesh axis.
* ``cross_size()`` — extent of the ``cross`` (DCN) mesh axis.
* ``rank()``       — flat index of the first device owned by this process
                     (0 in single-process mode). With one process per chip —
                     the reference's launch topology — this is exactly the
                     MPI rank.
* ``local_rank()`` / ``cross_rank()`` — ``rank`` split along the mesh axes.

User conventions from the reference carry over unchanged: scale the learning
rate by ``size()``, checkpoint when ``rank() == 0``.
"""

from __future__ import annotations

import atexit
import os
from typing import Optional, Sequence

import jax

from horovod_tpu import flight_recorder
from horovod_tpu.core import mesh as mesh_mod
from horovod_tpu.core import state as state_mod
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import Config


class NotInitializedError(RuntimeError):
    def __init__(self) -> None:
        # reference error text: horovod/common/operations.cc NOT_INITIALIZED
        super().__init__(
            "horovod_tpu has not been initialized; use hvd.init()."
        )


def _ensure_init() -> state_mod.GlobalState:
    st = state_mod.global_state()
    if not st.initialized:
        raise NotInitializedError()
    return st


def init(
    comm=None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[tuple[int, int]] = None,
) -> None:
    """Initialize the framework: build the device mesh, parse config knobs,
    and start background subsystems.

    Mirrors ``horovod_init`` → ``InitializeHorovodOnce`` (reference:
    horovod/common/operations.cc:554-600). ``comm`` is accepted for API
    compatibility and ignored (there is no MPI communicator on TPU; process
    membership comes from ``jax.distributed``).

    Multi-process (multi-host) initialization: if ``HOROVOD_COORDINATOR_ADDR``
    is set (by the ``tpurun`` launcher), ``jax.distributed.initialize`` is
    called first so all processes join one global device mesh.
    """
    st = state_mod.global_state()
    with st.lock:
        if st.initialized:
            return

        # NOTE: must not touch any jax API that initializes the local
        # backend (jax.devices / jax.process_count) before
        # jax.distributed.initialize — the guard reads env vars only.
        coordinator = os.environ.get("HOROVOD_COORDINATOR_ADDR")
        num_processes = int(os.environ.get("HOROVOD_NUM_PROCESSES", "1"))
        if coordinator and num_processes > 1 and not _jax_dist_initialized():
            process_id = int(os.environ.get("HOROVOD_PROCESS_ID", "0"))
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )

        st.config = Config.from_env()
        st.mesh = mesh_mod.build_mesh(devices=devices, mesh_shape=mesh_shape)

        cross, local = st.mesh.devices.shape
        st.size = cross * local
        st.local_size = local
        st.cross_size = cross

        # rank = flat index of the first device this process owns.
        flat = list(st.mesh.devices.flatten())
        proc = jax.process_index()
        st.rank = next(
            (i for i, d in enumerate(flat) if d.process_index == proc), 0
        )
        st.local_rank = st.rank % local
        st.cross_rank = st.rank // local

        # Socket (host data plane) mode: the launcher's env contract defines
        # the world — worker == process, exactly the reference's MPI-rank
        # semantics (reference: gloo_context.cc:128-133 reads
        # HOROVOD_RANK/SIZE/... set by gloo_run). Without this, rank()/size()
        # would report only the process-local mesh.
        env_size = int(os.environ.get("HOROVOD_SIZE", "1"))
        if env_size > 1 and jax.process_count() == 1:
            st.size = env_size
            st.rank = int(os.environ.get("HOROVOD_RANK", "0"))
            st.local_size = int(
                os.environ.get("HOROVOD_LOCAL_SIZE", str(env_size)))
            st.local_rank = int(
                os.environ.get("HOROVOD_LOCAL_RANK", str(st.rank)))
            st.cross_size = int(os.environ.get(
                "HOROVOD_CROSS_SIZE",
                str(max(1, env_size // max(st.local_size, 1)))))
            st.cross_rank = int(os.environ.get(
                "HOROVOD_CROSS_RANK", str(st.rank // max(st.local_size, 1))))

        st.initialized = True
        st.shut_down = False
        log.debug(
            "initialized: size=%d local=%d cross=%d rank=%d",
            st.size, st.local_size, st.cross_size, st.rank,
        )

        # flight recorder: adopt the (possibly re-formed) rank, hook fatal
        # signals so a SIGTERM/SIGSEGV leaves a postmortem dump
        flight_recorder.configure(rank=st.rank)
        flight_recorder.install_signal_handlers()
        flight_recorder.emit("init", rank=st.rank, size=st.size)

        # step profiler: adopt the rank and register its flight-recorder
        # state provider (HOROVOD_PROFILE / HOROVOD_PROFILE_DIR)
        from horovod_tpu import profiler

        profiler.configure(rank=st.rank)

        # memory plane: adopt the rank, register the flight-recorder
        # "memory" state provider, start the reconciliation sampler
        # (HOROVOD_MEMORY / HOROVOD_MEMORY_SAMPLE_SECONDS)
        from horovod_tpu import memory

        memory.configure(rank=st.rank)

        # tracing + SLO plane: adopt the rank, register the "slo" state
        # provider, flip the /healthz readiness gate (HOROVOD_TRACE /
        # HOROVOD_SLO_*)
        from horovod_tpu import tracing

        tracing.configure(rank=st.rank)

        # collective transport observatory: adopt rank/world, seed lane
        # rooflines from the persisted probe artifact, register the
        # "comms" state provider (HOROVOD_COMMS_* / HOROVOD_PROBE_CACHE)
        from horovod_tpu import comms

        comms.configure(rank=st.rank, world=st.size)

        # goodput ledger: adopt rank/world, pin the wall-clock epoch
        # (first init only — elastic re-inits keep the original clock),
        # register the "goodput" state provider (HOROVOD_GOODPUT_*)
        from horovod_tpu import goodput

        goodput.configure(rank=st.rank, world=st.size)

        if st.config.timeline_file:
            from horovod_tpu.timeline import Timeline

            st.timeline = Timeline(st.config.timeline_file,
                                   mark_cycles=st.config.timeline_mark_cycles)

        # Prometheus exposition endpoint (HOROVOD_METRICS_PORT): when the
        # knob is unset, no thread or socket exists — the metrics hot path
        # stays a plain dict/int update per event.
        if st.config.metrics_port is not None:
            from horovod_tpu.metrics import registry as metrics_registry

            port = metrics_registry().serve(st.config.metrics_port)
            log.debug("metrics endpoint serving on port %d", port)


def _jax_dist_initialized() -> bool:
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


def shutdown() -> None:
    """Tear down background subsystems and reset state.

    Mirrors ``horovod_shutdown`` (reference: horovod/common/operations.cc):
    in-flight enqueued tensors receive a shut-down error through their
    callbacks before the state is reset.
    """
    st = state_mod.global_state()
    with st.lock:
        if not st.initialized:
            return
        st.shut_down = True
        if st.runtime is not None:
            st.runtime.stop()
        if st.timeline is not None:
            st.timeline.close()
        from horovod_tpu.metrics import registry as metrics_registry

        reg = metrics_registry()
        reg.stop_server()
        if st.config.metrics_dump:
            try:
                reg.dump(st.config.metrics_dump, rank=st.rank)
            except OSError as exc:
                log.warning("could not write metrics dump: %s", exc)
        from horovod_tpu.ops import collectives

        collectives.clear_compiled_cache()
        # step profiler: close any implicit step, dump + ship the profile
        # (no-op unless HOROVOD_PROFILE / HOROVOD_PROFILE_DIR enabled it)
        from horovod_tpu import profiler

        profiler.finalize()
        # memory plane: stop the sampler so it doesn't outlive the state
        # it reconciles (re-init restarts it with the new rank)
        from horovod_tpu import memory

        memory.tracker().stop()
        # /healthz must stop reporting ready the moment the runtime is
        # gone — a load balancer probing a shut-down worker gets 503
        from horovod_tpu import tracing

        tracing.mark_initialized(False)
        flight_recorder.emit("shutdown", rank=st.rank)
        # leave a final dump behind (and ship it to the launcher) so the
        # postmortem covers clean exits too — only when a destination is
        # configured; a bare single-process run writes nothing
        if flight_recorder.recorder().enabled and (
                flight_recorder.recorder().dir
                or flight_recorder._rendezvous_addr() is not None):
            flight_recorder.recorder().dump("shutdown")
    state_mod.reset()


def reinit(
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[tuple[int, int]] = None,
) -> None:
    """Tear down and re-initialize against the CURRENT environment.

    The elastic runner calls this after re-forming membership: by then
    ``HOROVOD_RANK``/``HOROVOD_SIZE``/rendezvous knobs describe the new
    generation, and ``init()`` rebuilds the mesh, config, and topology from
    them. A plain ``init()`` call would be a no-op (``st.initialized``
    short-circuits), hence the explicit shutdown-first entry point.
    """
    shutdown()
    init(devices=devices, mesh_shape=mesh_shape)


atexit.register(shutdown)  # reference: horovod/common/basics.py:40


def is_initialized() -> bool:
    return state_mod.global_state().initialized


def rank() -> int:
    return _ensure_init().rank


def size() -> int:
    return _ensure_init().size


def local_rank() -> int:
    return _ensure_init().local_rank


def local_size() -> int:
    return _ensure_init().local_size


def cross_rank() -> int:
    return _ensure_init().cross_rank


def cross_size() -> int:
    return _ensure_init().cross_size


def mesh():
    """The global (cross, local) device mesh."""
    return _ensure_init().mesh


def metrics() -> dict:
    """Snapshot of the process-wide runtime metrics registry as a nested
    JSON-serializable dict: cycle timing, queue depth, cache hit/miss
    counts, fusion bytes/utilization, per-op collective latency and bytes,
    stall and timeline health counters (see docs/metrics.md).

    Works before ``init()`` too — the registry is process-global — but
    counters only move once the runtime is running."""
    from horovod_tpu.metrics import registry as metrics_registry

    return metrics_registry().snapshot()


def is_homogeneous() -> bool:
    """True when every process owns the same number of devices
    (reference: mpi_controller.cc:25-81 homogeneity check)."""
    st = _ensure_init()
    counts: dict[int, int] = {}
    for d in st.mesh.devices.flatten():
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    return len(set(counts.values())) <= 1


# Capability probes, mirroring horovod_*_built/enabled
# (reference: horovod/common/operations.cc:640-732). The TPU build has no
# MPI/NCCL/Gloo; its transports are XLA collectives over ICI/DCN.
def mpi_built() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def mlsl_built() -> bool:
    return False


def xla_built() -> bool:
    return True


def mpi_enabled() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False
