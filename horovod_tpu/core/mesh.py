"""Device-mesh construction: the GLOBAL/LOCAL/CROSS communicator triple.

The reference maintains three communicators — GLOBAL (all ranks), LOCAL
(ranks on one node, fast intra-node transport) and CROSS (one rank per node,
inter-node transport) (reference: horovod/common/common.h:105-109,
mpi/mpi_context.h:78-84). The TPU-native equivalent is a 2-D
``jax.sharding.Mesh`` whose axes map onto the interconnect hierarchy:

* ``local`` axis — devices reached over ICI (intra-slice / intra-host).
* ``cross`` axis — hosts/slices reached over DCN.
* GLOBAL — the flattened pair ``('cross', 'local')``.

Collectives over the GLOBAL communicator are ``lax.psum(..., axis_name=
('cross', 'local'))``; hierarchical two-level algorithms reduce over
``local`` first (ICI) then ``cross`` (DCN), mirroring the reference's
NCCL-then-MPI hierarchical allreduce (reference: ops/nccl_operations.cc:150-346).
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.utils import env as env_mod

CROSS_AXIS = "cross"
LOCAL_AXIS = "local"
GLOBAL_AXES = (CROSS_AXIS, LOCAL_AXIS)


def build_mesh(
    devices: Sequence[jax.Device] | None = None,
    mesh_shape: tuple[int, int] | None = None,
) -> Mesh:
    """Build the (cross, local) mesh over all devices.

    By default ``cross`` spans processes (DCN) and ``local`` spans the
    devices owned by each process (ICI) — the same split the reference makes
    with ``MPI_COMM_TYPE_SHARED`` (reference: mpi/mpi_context.cc). The shape
    can be overridden with ``HOROVOD_MESH_SHAPE=cross,local`` or the
    ``mesh_shape`` argument so hierarchical paths are testable on a
    single-host virtual mesh.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)

    if mesh_shape is None:
        mesh_shape = env_mod.parse_mesh_shape(
            os.environ.get(env_mod.HOROVOD_MESH_SHAPE)
        )
    if mesh_shape is None:
        num_processes = jax.process_count()
        if n % num_processes == 0 and num_processes > 1:
            mesh_shape = (num_processes, n // num_processes)
        else:
            mesh_shape = (1, n)

    cross, local = mesh_shape
    if cross * local != n:
        raise ValueError(
            f"mesh shape {mesh_shape} does not cover {n} devices"
        )
    device_array = np.asarray(devices).reshape(cross, local)
    return Mesh(device_array, GLOBAL_AXES)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def worker_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that splits axis 0 across all workers (devices).

    This is the single-controller encoding of "one tensor per worker": a
    stacked array of shape ``(num_workers, *tensor_shape)`` with axis 0 laid
    out one slice per device.
    """
    return NamedSharding(mesh, P(GLOBAL_AXES))
