"""Process-global framework state.

TPU-native analogue of ``HorovodGlobalState`` (reference:
horovod/common/global_state.h:42-112): one singleton owning the mesh, the
parsed config knobs, the background enqueue runtime, the timeline, the
autotuner and lifecycle flags. Unlike the reference there is no raw POD /
pointer soup — components attach lazily and are torn down in ``shutdown()``.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Any, Optional

from horovod_tpu.analysis.witness import make_lock
from horovod_tpu.utils.env import Config

if TYPE_CHECKING:
    from jax.sharding import Mesh


@dataclasses.dataclass
class GlobalState:
    initialized: bool = False
    shut_down: bool = False
    mesh: Optional["Mesh"] = None

    # Worker topology (worker == device; see core/basics.py docstring).
    rank: int = 0
    size: int = 1
    local_rank: int = 0
    local_size: int = 1
    cross_rank: int = 0
    cross_size: int = 1

    config: Config = dataclasses.field(default_factory=Config)

    # Lazily attached subsystems (enqueue runtime, timeline, autotuner, ...).
    runtime: Any = None
    timeline: Any = None
    parameter_manager: Any = None
    controller: Any = None

    # Reentrant: init/shutdown paths re-enter through basics helpers.
    # make_lock gives the deadlock witness visibility under
    # HOROVOD_DEBUG_LOCKS=1 and is a plain RLock otherwise.
    lock: Any = dataclasses.field(
        default_factory=lambda: make_lock("GlobalState.lock", reentrant=True))


_global_state = GlobalState()


def global_state() -> GlobalState:
    return _global_state


def reset() -> None:
    """Replace the singleton with a fresh state (used by shutdown/tests)."""
    global _global_state
    _global_state = GlobalState()
