// Native per-cycle negotiation engine: LRU response cache + fusion
// bin-packing.
//
// TPU-native analogue of the reference's C++ cycle hot path (reference:
// horovod/common/response_cache.cc — LRU cache with stable cache bits;
// horovod/common/controller.cc:551-672 — FuseResponses bin-packing with
// look-ahead). The Python layer (runtime/response_cache.py,
// runtime/fusion.py) defines the semantics and remains as the fallback;
// this module executes the same algorithms natively. Responses and cache
// params keys cross the ABI as opaque byte blobs (the Python side packs
// them with its versioned wire codec, runtime/message.py), so the C++
// stays schema-free.
//
// Exact-behavior contract with the Python implementations (verified by the
// differential tests in tests/test_native_cycle.py):
//   * put() of an existing name refreshes the entry in place and touches
//     LRU order; a new name at capacity evicts the LRU entry first and
//     recycles its bit through a min-heap so bit numbering stays bounded
//     by capacity (reference: response_cache.cc:232+ bit redistribution).
//   * cached() never touches LRU order (announcement timing differs across
//     workers; see the invariant note in runtime/response_cache.py).
//   * fuse(): greedy bin-packing that skips past non-joinable responses
//     (look-ahead) rather than flushing the bin.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <list>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct CacheEntry {
  std::string name;
  std::string params;               // opaque params key
  std::string blob;                 // opaque packed response
  std::list<int64_t>::iterator pos; // position in the LRU list
};

struct Cache {
  int64_t capacity = 0;
  std::unordered_map<std::string, int64_t> name_to_bit;
  std::unordered_map<int64_t, CacheEntry> entries;
  std::list<int64_t> lru; // front = least recently used
  std::priority_queue<int64_t, std::vector<int64_t>, std::greater<int64_t>>
      free_bits;
  int64_t next_bit = 0;

  int64_t alloc_bit() {
    if (!free_bits.empty()) {
      int64_t b = free_bits.top();
      free_bits.pop();
      return b;
    }
    return next_bit++;
  }
};

}  // namespace

extern "C" {

void* hvc_cache_new(int64_t capacity) {
  Cache* c = new Cache();
  c->capacity = capacity;
  return c;
}

void hvc_cache_free(void* h) { delete static_cast<Cache*>(h); }

// 0 = MISS, 1 = HIT, 2 = INVALID (params changed for a known name).
int hvc_cache_cached(void* h, const char* name, const uint8_t* params,
                     int64_t plen) {
  Cache* c = static_cast<Cache*>(h);
  auto it = c->name_to_bit.find(name);
  if (it == c->name_to_bit.end()) return 0;
  auto eit = c->entries.find(it->second);
  if (eit == c->entries.end()) return 0;
  const std::string& key = eit->second.params;
  if (key.size() == static_cast<size_t>(plen) &&
      std::memcmp(key.data(), params, plen) == 0)
    return 1;
  return 2;
}

int64_t hvc_cache_put(void* h, const char* name, const uint8_t* params,
                      int64_t plen, const uint8_t* blob, int64_t blen) {
  Cache* c = static_cast<Cache*>(h);
  if (c->capacity <= 0) return -1;
  std::string sname(name);
  auto it = c->name_to_bit.find(sname);
  if (it != c->name_to_bit.end()) {
    auto eit = c->entries.find(it->second);
    if (eit != c->entries.end()) {
      // refresh in place + touch LRU (move to back)
      CacheEntry& e = eit->second;
      c->lru.erase(e.pos);
      c->lru.push_back(it->second);
      e.pos = std::prev(c->lru.end());
      e.params.assign(reinterpret_cast<const char*>(params), plen);
      e.blob.assign(reinterpret_cast<const char*>(blob), blen);
      return it->second;
    }
  }
  if (static_cast<int64_t>(c->entries.size()) >= c->capacity) {
    int64_t old_bit = c->lru.front();
    c->lru.pop_front();
    auto eit = c->entries.find(old_bit);
    if (eit != c->entries.end()) {
      c->name_to_bit.erase(eit->second.name);
      c->entries.erase(eit);
    }
    c->free_bits.push(old_bit);
  }
  int64_t bit = c->alloc_bit();
  c->lru.push_back(bit);
  CacheEntry e;
  e.name = sname;
  e.params.assign(reinterpret_cast<const char*>(params), plen);
  e.blob.assign(reinterpret_cast<const char*>(blob), blen);
  e.pos = std::prev(c->lru.end());
  c->entries.emplace(bit, std::move(e));
  c->name_to_bit[sname] = bit;
  return bit;
}

int64_t hvc_cache_bit_for_name(void* h, const char* name) {
  Cache* c = static_cast<Cache*>(h);
  auto it = c->name_to_bit.find(name);
  return it == c->name_to_bit.end() ? -1 : it->second;
}

// Returns the blob length for `bit` WITHOUT touching LRU order, or -1.
int64_t hvc_cache_get_len(void* h, int64_t bit) {
  Cache* c = static_cast<Cache*>(h);
  auto it = c->entries.find(bit);
  return it == c->entries.end() ? -1
                                : static_cast<int64_t>(it->second.blob.size());
}

// Copies the blob for `bit` into out (cap bytes) and touches LRU order.
// Returns the blob length, or -1 if absent / cap too small.
int64_t hvc_cache_get(void* h, int64_t bit, uint8_t* out, int64_t cap) {
  Cache* c = static_cast<Cache*>(h);
  auto it = c->entries.find(bit);
  if (it == c->entries.end()) return -1;
  CacheEntry& e = it->second;
  if (static_cast<int64_t>(e.blob.size()) > cap) return -1;
  std::memcpy(out, e.blob.data(), e.blob.size());
  c->lru.erase(e.pos);
  c->lru.push_back(bit);
  e.pos = std::prev(c->lru.end());
  return static_cast<int64_t>(e.blob.size());
}

void hvc_cache_invalidate(void* h, const char* name) {
  Cache* c = static_cast<Cache*>(h);
  auto it = c->name_to_bit.find(name);
  if (it == c->name_to_bit.end()) return;
  int64_t bit = it->second;
  c->name_to_bit.erase(it);
  auto eit = c->entries.find(bit);
  if (eit != c->entries.end()) {
    c->lru.erase(eit->second.pos);
    c->entries.erase(eit);
    c->free_bits.push(bit);
  }
}

int64_t hvc_cache_size(void* h) {
  return static_cast<int64_t>(static_cast<Cache*>(h)->entries.size());
}

// Fusion bin-packing (reference: FuseResponses, controller.cc:551-672).
// Inputs are per-response: is_allreduce flag, join-key id (same id ==
// same dtype + reduction params), payload bytes. Output: sequences of
// [group_len, idx...] in execution order. Returns ints written, or -1 if
// `cap` is too small (caller sizes cap = 2n, which always suffices).
int64_t hvc_fuse(int64_t n, const uint8_t* is_allreduce,
                 const int64_t* key_id, const int64_t* nbytes,
                 int64_t threshold, int32_t* out, int64_t cap) {
  std::vector<int64_t> remaining(n);
  for (int64_t i = 0; i < n; ++i) remaining[i] = i;
  int64_t w = 0;
  std::vector<int64_t> skipped;
  skipped.reserve(n);
  size_t start = 0;  // head cursor into `remaining` (avoids O(n) pops)
  while (start < remaining.size()) {
    int64_t head = remaining[start++];
    if (!is_allreduce[head]) {
      if (w + 2 > cap) return -1;
      out[w++] = 1;
      out[w++] = static_cast<int32_t>(head);
      continue;
    }
    int64_t head_count_pos = w;
    if (w + 2 > cap) return -1;
    out[w++] = 1;
    out[w++] = static_cast<int32_t>(head);
    int64_t acc_bytes = nbytes[head];
    skipped.clear();
    for (size_t j = start; j < remaining.size(); ++j) {
      int64_t cand = remaining[j];
      if (is_allreduce[cand] && key_id[cand] == key_id[head] &&
          acc_bytes + nbytes[cand] <= threshold) {
        if (w + 1 > cap) return -1;
        out[w++] = static_cast<int32_t>(cand);
        out[head_count_pos]++;
        acc_bytes += nbytes[cand];
      } else {
        skipped.push_back(cand);
      }
    }
    remaining.assign(skipped.begin(), skipped.end());
    start = 0;
  }
  return w;
}

}  // extern "C"
