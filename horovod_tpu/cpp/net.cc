// Native TCP coordination + host-collective transport.
//
// TPU-native analogue of the reference's Gloo layer (reference:
// horovod/common/gloo/gloo_controller.cc, gloo_context.cc and the vendored
// third_party/gloo): provides the controller verbs the negotiation protocol
// needs (gather-to-coordinator, broadcast-from-coordinator, barrier,
// cross-rank bitwise AND/OR) and host-memory data collectives (ring
// allreduce, allgatherv, broadcast) for CPU-resident tensors. On TPU the
// *device* data plane is XLA over ICI/DCN; this library is the host-side
// control/data plane for multi-process mode and tests, loaded via ctypes
// (no pybind11 in the image).
//
// Topology: rank 0 listens; every worker opens one persistent socket to
// rank 0 (star, used for control verbs), and each rank additionally
// connects to its ring successor (rank+1)%world for the bandwidth-optimal
// ring allreduce. Rendezvous: workers register their ring-listen port with
// the coordinator, which broadcasts the address book.
//
// Build: `make -C horovod_tpu/cpp` -> libhvdtpu_net.so.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/resource.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// socket helpers
// ---------------------------------------------------------------------------

int send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return 0;
}

int recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      return -1;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return 0;
}

// Monotonic wall clock in milliseconds (deadline arithmetic for the
// accept loops; CLOCK_MONOTONIC so a wall-clock step can't extend or
// collapse a timeout budget).
int64_t mono_ms() {
  timespec ts;
  ::clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// recv_all bounded by an absolute monotonic deadline — for the
// handshake reads right after an accept: a peer whose connect completed
// but who died (SIGKILL, host partition) before sending its hello emits
// no RST, and an unbounded recv would hang init forever even with the
// accept itself bounded.
int recv_all_deadline(int fd, void* buf, size_t n, int64_t deadline_ms) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    int64_t remain = deadline_ms - mono_ms();
    if (remain <= 0) return -1;
    pollfd pfd{fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(std::min<int64_t>(remain, 1000)));
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pr == 0) continue;  // re-check the deadline
    ssize_t k = ::recv(fd, p, n, MSG_DONTWAIT);
    if (k == 0) return -1;  // peer closed
    if (k < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return -1;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return 0;
}

// accept(2) bounded by an absolute monotonic deadline: poll the listen fd
// for readability with the remaining budget before accepting, so a peer
// that dies between rendezvous and dial fails this rank's init with an
// error instead of hanging it forever (blocking ::accept has no timeout;
// tcp_connect_retry bounds only the outbound dials).
int accept_deadline(int listen_fd, int64_t deadline_ms) {
  for (;;) {
    int64_t remain = deadline_ms - mono_ms();
    if (remain <= 0) return -1;
    pollfd pfd{listen_fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(std::min<int64_t>(remain, 1000)));
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pr == 0) continue;  // re-check the deadline
    int fd = ::accept(listen_fd, nullptr, nullptr);
    // ECONNABORTED/EPROTO: the queued connection was reset by the dialer
    // (port scanners do this) — keep accepting real peers
    if (fd < 0 && (errno == EINTR || errno == EAGAIN ||
                   errno == EWOULDBLOCK || errno == ECONNABORTED ||
                   errno == EPROTO))
      continue;
    return fd;
  }
}

// Per-communicator protocol counters (deterministic metrics independent of
// box speed). Atomic so concurrent use of one handle from several threads
// counts correctly; per-handle so two Comm instances in one process don't
// conflate (advisor r3). Defined outside Comm because duplex_exchange is
// layered below the communicator.
struct ProtoCounters {
  // Data-plane bytes sent through duplex_exchange (the ring/mesh
  // collective kernels). Lets tests assert the optimal byte counts of the
  // reduce-scatter ((w-1)/w) and pairwise alltoall ((w-1)/w) instead of
  // trusting the algorithm comment.
  std::atomic<uint64_t> data_bytes_sent{0};
  // Number of duplex_exchange invocations (ring/mesh steps) — fusion's
  // dispatch win (K tensors in one fused buffer = 1/K the ring launches)
  // is this counter's delta.
  std::atomic<uint64_t> exchange_calls{0};
  // Control-plane bytes sent over the star (negotiation gathers/bcasts +
  // cache-bit syncs) — the response cache's amortization is the per-op
  // delta of this counter: a fresh name costs a packed request+response
  // round trip, a steady name amortizes one fixed-width bit sync per
  // cycle.
  std::atomic<uint64_t> ctrl_bytes_sent{0};
};

// Full-duplex exchange: send `sn` bytes to `sfd` while receiving `rn` bytes
// from `rfd`, making progress on whichever direction is ready. Required for
// the ring steps: every rank sends and receives a chunk simultaneously, so a
// blocking send of a chunk larger than the kernel socket buffers would
// deadlock the whole ring (all ranks stuck in send, nobody draining).
int duplex_exchange(ProtoCounters* ctr, int sfd, const void* send_buf,
                    size_t sn, int rfd, void* recv_buf, size_t rn) {
  ctr->data_bytes_sent += sn;
  ctr->exchange_calls += 1;
  const char* sp = static_cast<const char*>(send_buf);
  char* rp = static_cast<char*>(recv_buf);
  while (sn > 0 || rn > 0) {
    pollfd fds[2];
    nfds_t nfds = 0;
    int si = -1, ri = -1;
    if (sn > 0) {
      si = nfds;
      fds[nfds++] = {sfd, POLLOUT, 0};
    }
    if (rn > 0) {
      ri = nfds;
      fds[nfds++] = {rfd, POLLIN, 0};
    }
    int pr = ::poll(fds, nfds, -1);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (si >= 0 && (fds[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
      ssize_t k = ::send(sfd, sp, sn, MSG_NOSIGNAL | MSG_DONTWAIT);
      if (k < 0) {
        if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
          return -1;
      } else {
        sp += k;
        sn -= static_cast<size_t>(k);
      }
    }
    if (ri >= 0 && (fds[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t k = ::recv(rfd, rp, rn, MSG_DONTWAIT);
      if (k == 0) return -1;  // peer closed
      if (k < 0) {
        if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
          return -1;
      } else {
        rp += k;
        rn -= static_cast<size_t>(k);
      }
    }
  }
  return 0;
}

int send_frame(int fd, const void* buf, uint64_t n) {
  if (send_all(fd, &n, sizeof(n)) != 0) return -1;
  return send_all(fd, buf, n);
}

// receives into a resizable vector; returns length or -1
int64_t recv_frame(int fd, std::vector<char>& out) {
  uint64_t n = 0;
  if (recv_all(fd, &n, sizeof(n)) != 0) return -1;
  out.resize(n);
  if (n > 0 && recv_all(fd, out.data(), n) != 0) return -1;
  return static_cast<int64_t>(n);
}

int tcp_listen(int* port_inout) {
  // SOCK_NONBLOCK: accept_deadline's poll-then-accept would otherwise
  // race — a connection aborted (RST) between poll() reporting POLLIN
  // and ::accept running is removed from the queue and a blocking
  // accept parks forever, the exact hang the deadline exists to
  // prevent. Accepted fds do NOT inherit the flag on Linux.
  int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(*port_inout));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  *port_inout = ntohs(addr.sin_port);
  if (::listen(fd, 128) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

int tcp_connect_retry(const char* host, int port, int timeout_ms) {
  for (int elapsed = 0;; elapsed += 50) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return -1;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    if (elapsed >= timeout_ms) return -1;
    ::usleep(50 * 1000);
  }
}

// ---------------------------------------------------------------------------
// communicator
// ---------------------------------------------------------------------------

struct Comm {
  int rank = 0;
  int world = 1;
  // star: coordinator holds star[r] per worker r (star[0] unused);
  // workers hold star[0] = socket to coordinator. Control verbs only —
  // kept separate from the data mesh so control frames never interleave
  // with collective payloads.
  std::vector<int> star;
  // full data mesh: mesh[s] = socket to rank s (mesh[rank] unused). The
  // ring links are the (rank±1) entries; the remaining links carry the
  // pairwise alltoall (a ring-only topology would force W/2x the bytes
  // through the neighbor links).
  std::vector<int> mesh;
  // ring aliases into mesh (not separately owned)
  int ring_next = -1;
  int ring_prev = -1;
  ProtoCounters counters;
  std::string error;
};

// handshake tags
constexpr uint32_t KHELLO = 0x68766431;  // "hvd1" (star hello)
constexpr uint32_t KMESH = 0x68766d31;   // "hvm1" (mesh hello)

// ring address book entry: where each rank's ring listener is reachable.
// The coordinator fills `ip` from getpeername() on the rank's star socket —
// the address the rank actually routes from — so multi-host rings dial the
// right machine, not the coordinator host.
struct RingAddr {
  char ip[46];  // INET6_ADDRSTRLEN
  int32_t port;
};

// Build the full data mesh over the per-rank listeners: every rank dials
// all lower ranks (their listeners are already up, so connects land in
// the backlog even while the peer is still dialing) and accepts one
// connection from every higher rank, identified by a hello frame.
int mesh_build(Comm* c, int listen_fd, const std::vector<RingAddr>& addrs,
               int timeout_ms) {
  const int w = c->world, r = c->rank;
  c->mesh.assign(w, -1);
  for (int s = 0; s < r; ++s) {
    int fd = tcp_connect_retry(addrs[s].ip, addrs[s].port, timeout_ms);
    if (fd < 0) return -1;
    uint32_t magic = KMESH;
    int32_t me = r;
    if (send_all(fd, &magic, sizeof(magic)) != 0 ||
        send_all(fd, &me, sizeof(me)) != 0) {
      ::close(fd);
      return -1;
    }
    c->mesh[s] = fd;
  }
  // The accept phase gets its own timeout_ms budget (the dials above each
  // had theirs): a higher-ranked peer that died after rendezvous would
  // otherwise park this rank in a blocking accept forever.
  const int64_t deadline = mono_ms() + timeout_ms;
  for (int got = 0; got < w - 1 - r;) {
    int fd = accept_deadline(listen_fd, deadline);
    if (fd < 0) return -1;
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // A stray dialer (port scanner, LB health check) must not kill the
    // job: its handshake gets a short budget — not the loop's whole
    // remaining deadline — and on any mismatch the fd is dropped and
    // the loop keeps accepting real peers.
    const int64_t conn_deadline = std::min(deadline, mono_ms() + 5000);
    uint32_t magic = 0;
    int32_t peer = -1;
    if (recv_all_deadline(fd, &magic, sizeof(magic), conn_deadline) != 0 ||
        magic != KMESH ||
        recv_all_deadline(fd, &peer, sizeof(peer), conn_deadline) != 0 ||
        peer <= r || peer >= w || c->mesh[peer] != -1) {
      ::close(fd);
      continue;
    }
    c->mesh[peer] = fd;
    ++got;
  }
  c->ring_next = c->mesh[(r + 1) % w];
  c->ring_prev = c->mesh[(r - 1 + w) % w];
  return 0;
}

int comm_init(Comm* c, int rank, int world, const char* coord_host,
              int coord_port, int timeout_ms) {
  c->rank = rank;
  c->world = world;
  if (world < 1) {
    c->error = "bad world size";
    return -1;
  }
  c->star.assign(world, -1);
  if (world == 1) return 0;

  // Fail fast on fd exhaustion: every process holds world-1 mesh sockets
  // plus its star link and listeners, and the COORDINATOR additionally
  // holds world-1 star sockets (~2x world total there), plus whatever
  // Python has open. At large worlds a default `ulimit -n` of 1024 dies
  // mid-rendezvous with a confusing EMFILE; check up front (and try the
  // soft->hard raise first) so the error is actionable. Sized for the
  // coordinator's worst case on every rank — uniform, and a rank's
  // margin is harmless.
  {
    rlimit rl{};
    const rlim_t need = 2 * static_cast<rlim_t>(world) + 64;
    if (::getrlimit(RLIMIT_NOFILE, &rl) == 0 && rl.rlim_cur < need) {
      rlimit want = rl;
      want.rlim_cur = std::min<rlim_t>(std::max<rlim_t>(need, rl.rlim_cur),
                                       rl.rlim_max);
      if (want.rlim_cur > rl.rlim_cur) ::setrlimit(RLIMIT_NOFILE, &want);
      if (::getrlimit(RLIMIT_NOFILE, &rl) != 0 || rl.rlim_cur < need) {
        c->error = "open-file limit too low for the data mesh: world " +
                   std::to_string(world) + " needs ~" + std::to_string(need) +
                   " fds per process (world-1 mesh sockets + star link(s) —"
                   " the coordinator holds world-1 of those — + listeners +"
                   " margin) but RLIMIT_NOFILE is " +
                   std::to_string(rl.rlim_cur) +
                   "; raise it (`ulimit -n` / LimitNOFILE) before launch";
        return -1;
      }
    }
  }

  // --- star setup + rendezvous of ring listen ports ---
  int ring_listen_port = 0;
  int ring_listen_fd = tcp_listen(&ring_listen_port);
  if (ring_listen_fd < 0) {
    c->error = "ring listen failed";
    return -1;
  }

  if (rank == 0) {
    int port = coord_port;
    int lfd = tcp_listen(&port);
    if (lfd < 0 || port != coord_port) {
      c->error = "coordinator listen failed on port " +
                 std::to_string(coord_port);
      return -1;
    }
    std::vector<RingAddr> ring_addrs(world);
    std::memset(ring_addrs.data(), 0, sizeof(RingAddr) * world);
    std::snprintf(ring_addrs[0].ip, sizeof(ring_addrs[0].ip), "%s",
                  coord_host);
    ring_addrs[0].port = ring_listen_port;
    const int64_t hello_deadline = mono_ms() + timeout_ms;
    for (int got = 1; got < world;) {
      int fd = accept_deadline(lfd, hello_deadline);
      if (fd < 0) {
        c->error = "accept failed (worker hello timeout after " +
                   std::to_string(timeout_ms) + "ms: " +
                   std::to_string(world - got) + " of " +
                   std::to_string(world - 1) + " workers never dialed)";
        return -1;
      }
      sockaddr_in peer_addr{};
      socklen_t peer_len = sizeof(peer_addr);
      if (::getpeername(fd, reinterpret_cast<sockaddr*>(&peer_addr),
                        &peer_len) != 0) {
        ::close(fd);
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // stray dialers get a short handshake budget and are skipped, not
      // fatal (see mesh_build) — a rendezvous port is reachable by
      // anything on the network
      const int64_t conn_deadline =
          std::min(hello_deadline, mono_ms() + 5000);
      uint32_t magic = 0;
      int32_t peer_rank = -1, peer_ring_port = 0;
      if (recv_all_deadline(fd, &magic, sizeof(magic),
                            conn_deadline) != 0 || magic != KHELLO ||
          recv_all_deadline(fd, &peer_rank, sizeof(peer_rank),
                            conn_deadline) != 0 ||
          recv_all_deadline(fd, &peer_ring_port, sizeof(peer_ring_port),
                            conn_deadline) != 0 ||
          peer_rank <= 0 || peer_rank >= world ||
          c->star[peer_rank] != -1) {
        ::close(fd);
        continue;
      }
      c->star[peer_rank] = fd;
      ::inet_ntop(AF_INET, &peer_addr.sin_addr, ring_addrs[peer_rank].ip,
                  sizeof(ring_addrs[peer_rank].ip));
      ring_addrs[peer_rank].port = peer_ring_port;
      ++got;
    }
    ::close(lfd);
    // broadcast the mesh address book
    for (int r = 1; r < world; ++r) {
      if (send_all(c->star[r], ring_addrs.data(),
                   sizeof(RingAddr) * world) != 0) {
        c->error = "address book send failed";
        return -1;
      }
    }
    if (mesh_build(c, ring_listen_fd, ring_addrs, timeout_ms) != 0) {
      c->error = "mesh setup failed";
      return -1;
    }
  } else {
    int fd = tcp_connect_retry(coord_host, coord_port, timeout_ms);
    if (fd < 0) {
      c->error = "connect to coordinator failed";
      return -1;
    }
    c->star[0] = fd;
    uint32_t magic = KHELLO;
    int32_t r32 = rank, rp = ring_listen_port;
    if (send_all(fd, &magic, sizeof(magic)) != 0 ||
        send_all(fd, &r32, sizeof(r32)) != 0 ||
        send_all(fd, &rp, sizeof(rp)) != 0) {
      c->error = "hello send failed";
      return -1;
    }
    std::vector<RingAddr> ring_addrs(world);
    // bounded: a coordinator that accepted our hello then died (no RST)
    // must fail this rank's init, not hang it forever
    if (recv_all_deadline(fd, ring_addrs.data(), sizeof(RingAddr) * world,
                          mono_ms() + timeout_ms) != 0) {
      c->error = "address book recv failed";
      return -1;
    }
    if (mesh_build(c, ring_listen_fd, ring_addrs, timeout_ms) != 0) {
      c->error = "mesh setup failed";
      return -1;
    }
  }
  ::close(ring_listen_fd);
  if (c->ring_next < 0 || c->ring_prev < 0) {
    c->error = "ring setup failed";
    return -1;
  }
  return 0;
}

void comm_close(Comm* c) {
  for (int fd : c->star)
    if (fd >= 0) ::close(fd);
  for (int fd : c->mesh)
    if (fd >= 0) ::close(fd);
  c->star.clear();
  c->mesh.clear();
  // aliases into mesh — already closed above
  c->ring_next = c->ring_prev = -1;
}

// ---------------------------------------------------------------------------
// control verbs (star) — reference: gloo_controller.cc verbs
// ---------------------------------------------------------------------------

// Workers send a frame to rank 0; rank 0 receives one frame per worker.
// out_lens/out buffers are coordinator-only.
int gatherv(Comm* c, const void* in, uint64_t in_len,
            std::vector<std::vector<char>>* out) {
  if (c->world == 1) {
    out->assign(1, std::vector<char>(static_cast<const char*>(in),
                                     static_cast<const char*>(in) + in_len));
    return 0;
  }
  if (c->rank == 0) {
    out->assign(c->world, {});
    (*out)[0].assign(static_cast<const char*>(in),
                     static_cast<const char*>(in) + in_len);
    for (int r = 1; r < c->world; ++r) {
      if (recv_frame(c->star[r], (*out)[r]) < 0) return -1;
    }
    return 0;
  }
  c->counters.ctrl_bytes_sent += in_len + 8;
  return send_frame(c->star[0], in, in_len);
}

// Rank 0 sends one frame to every worker; workers receive it.
int bcast(Comm* c, std::vector<char>* data) {
  if (c->world == 1) return 0;
  if (c->rank == 0) {
    for (int r = 1; r < c->world; ++r) {
      c->counters.ctrl_bytes_sent += data->size() + 8;
      if (send_frame(c->star[r], data->data(), data->size()) != 0) return -1;
    }
    return 0;
  }
  return recv_frame(c->star[0], *data) < 0 ? -1 : 0;
}

// Bitwise AND + OR over fixed-width word arrays (reference:
// CrossRankBitwiseAnd/Or, mpi_controller.cc:87-105). One round trip:
// gather words to rank 0, reduce, broadcast both results.
int bit_and_or(Comm* c, uint64_t* words, uint64_t nwords, uint64_t* out_and,
               uint64_t* out_or) {
  std::memcpy(out_and, words, nwords * 8);
  std::memcpy(out_or, words, nwords * 8);
  if (c->world == 1) return 0;
  if (c->rank == 0) {
    std::vector<uint64_t> buf(nwords);
    for (int r = 1; r < c->world; ++r) {
      if (recv_all(c->star[r], buf.data(), nwords * 8) != 0) return -1;
      for (uint64_t i = 0; i < nwords; ++i) {
        out_and[i] &= buf[i];
        out_or[i] |= buf[i];
      }
    }
    for (int r = 1; r < c->world; ++r) {
      c->counters.ctrl_bytes_sent += 2 * nwords * 8;
      if (send_all(c->star[r], out_and, nwords * 8) != 0 ||
          send_all(c->star[r], out_or, nwords * 8) != 0)
        return -1;
    }
    return 0;
  }
  c->counters.ctrl_bytes_sent += nwords * 8;
  if (send_all(c->star[0], words, nwords * 8) != 0) return -1;
  if (recv_all(c->star[0], out_and, nwords * 8) != 0) return -1;
  return recv_all(c->star[0], out_or, nwords * 8);
}

int barrier(Comm* c) {
  uint64_t token = 0x626172;  // "bar"
  std::vector<std::vector<char>> tmp;
  if (gatherv(c, &token, sizeof(token), &tmp) != 0) return -1;
  std::vector<char> b(sizeof(token));
  std::memcpy(b.data(), &token, sizeof(token));
  return bcast(c, &b);
}

// ---------------------------------------------------------------------------
// host data collectives — reference: the Gloo op layer
// (gloo_operations.cc); ring allreduce is the classic
// reduce-scatter + allgather ring the reference's transports implement.
// ---------------------------------------------------------------------------

// Reduction op codes shared with the Python binding (runtime/native.py):
// 0=sum, 1=min, 2=max, 3=product. Average is sum + a host-side divide —
// same as the reference's MPI_SUM + postscale (horovod averages after
// summing).
enum RedOp { kRedSum = 0, kRedMin = 1, kRedMax = 2, kRedProd = 3 };

// chunk boundary i of `count` elements split into `w` near-equal chunks
inline uint64_t chunk_begin(uint64_t count, int w, int i) {
  return count * static_cast<uint64_t>(i) / w;
}

template <typename T>
void combine(T* dst, const T* src, uint64_t n, int op) {
  switch (op) {
    case kRedSum:
      for (uint64_t i = 0; i < n; ++i) dst[i] += src[i];
      break;
    case kRedMin:
      for (uint64_t i = 0; i < n; ++i) dst[i] = std::min(dst[i], src[i]);
      break;
    case kRedMax:
      for (uint64_t i = 0; i < n; ++i) dst[i] = std::max(dst[i], src[i]);
      break;
    case kRedProd:
      for (uint64_t i = 0; i < n; ++i) dst[i] *= src[i];
      break;
  }
}

template <typename T>
int ring_allreduce_t(Comm* c, T* data, uint64_t count, int op) {
  if (op < kRedSum || op > kRedProd) return -1;
  if (c->world == 1 || count == 0) return 0;
  const int w = c->world;
  // chunk boundaries
  std::vector<uint64_t> begin(w + 1);
  for (int i = 0; i <= w; ++i) begin[i] = count * i / w;
  uint64_t max_chunk = 0;
  for (int i = 0; i < w; ++i)
    max_chunk = std::max(max_chunk, begin[i + 1] - begin[i]);
  std::vector<T> recv_buf(max_chunk);

  // reduce-scatter: after w-1 steps, rank r owns the full sum of chunk
  // (r+1) % w. Send+recv run full-duplex so chunks larger than the kernel
  // socket buffers can't deadlock the ring.
  for (int step = 0; step < w - 1; ++step) {
    int send_chunk = (c->rank - step + w) % w;
    int recv_chunk = (c->rank - step - 1 + w) % w;
    uint64_t send_n = begin[send_chunk + 1] - begin[send_chunk];
    uint64_t recv_n = begin[recv_chunk + 1] - begin[recv_chunk];
    if (duplex_exchange(&c->counters, c->ring_next, data + begin[send_chunk],
                        send_n * sizeof(T), c->ring_prev, recv_buf.data(),
                        recv_n * sizeof(T)) != 0)
      return -1;
    combine(data + begin[recv_chunk], recv_buf.data(), recv_n, op);
  }
  // allgather ring: circulate the owned (fully reduced) chunks
  for (int step = 0; step < w - 1; ++step) {
    int send_chunk = (c->rank + 1 - step + w) % w;
    int recv_chunk = (c->rank - step + w) % w;
    uint64_t send_n = begin[send_chunk + 1] - begin[send_chunk];
    uint64_t recv_n = begin[recv_chunk + 1] - begin[recv_chunk];
    if (duplex_exchange(&c->counters, c->ring_next, data + begin[send_chunk],
                        send_n * sizeof(T), c->ring_prev,
                        data + begin[recv_chunk], recv_n * sizeof(T)) != 0)
      return -1;
  }
  return 0;
}

// True half-ring reduce-scatter (VERDICT r2 ask 6): w-1 ring steps, each
// moving one chunk — (w-1)/w of the payload total, the optimal byte
// count (the old fallback ran a full allreduce then sliced: 2x). After
// the steps, rank r's chunk r region of `data` holds the full reduction;
// it is copied to `out`.
template <typename T>
int ring_reducescatter_t(Comm* c, T* data, uint64_t count, int op, T* out) {
  if (op < kRedSum || op > kRedProd) return -1;
  const int w = c->world, r = c->rank;
  uint64_t own_b = chunk_begin(count, w, r);
  uint64_t own_n = chunk_begin(count, w, r + 1) - own_b;
  if (w == 1 || count == 0) {
    std::memcpy(out, data + own_b, own_n * sizeof(T));
    return 0;
  }
  uint64_t max_chunk = 0;
  for (int i = 0; i < w; ++i)
    max_chunk = std::max(max_chunk,
                         chunk_begin(count, w, i + 1) - chunk_begin(count, w, i));
  std::vector<T> recv_buf(max_chunk);
  // shifted by one vs the allreduce phase so the final owned chunk is
  // chunk `rank` (the reduce-scatter output convention), not rank+1
  for (int step = 0; step < w - 1; ++step) {
    int send_chunk = (r - step - 1 + 2 * w) % w;
    int recv_chunk = (r - step - 2 + 2 * w) % w;
    uint64_t sb = chunk_begin(count, w, send_chunk);
    uint64_t sn = chunk_begin(count, w, send_chunk + 1) - sb;
    uint64_t rb = chunk_begin(count, w, recv_chunk);
    uint64_t rn = chunk_begin(count, w, recv_chunk + 1) - rb;
    if (duplex_exchange(&c->counters, c->ring_next, data + sb, sn * sizeof(T),
                        c->ring_prev, recv_buf.data(),
                        rn * sizeof(T)) != 0)
      return -1;
    combine(data + rb, recv_buf.data(), rn, op);
  }
  std::memcpy(out, data + own_b, own_n * sizeof(T));
  return 0;
}

// Pairwise all-to-all over the full mesh (VERDICT r2 ask 6): w-1 rounds;
// in round k every rank sends its (r+k)-th chunk to rank r+k while
// receiving chunk r from rank r-k — every byte crosses exactly one link
// ((w-1)/w of the payload total; the old fallback star-allgathered
// everything to everyone: Wx). Chunks are equal-sized byte blocks.
int pairwise_alltoall(Comm* c, const char* in, char* out,
                      uint64_t chunk_bytes) {
  const int w = c->world, r = c->rank;
  std::memcpy(out + static_cast<uint64_t>(r) * chunk_bytes,
              in + static_cast<uint64_t>(r) * chunk_bytes, chunk_bytes);
  for (int k = 1; k < w; ++k) {
    int to = (r + k) % w;
    int from = (r - k + w) % w;
    if (duplex_exchange(&c->counters, c->mesh[to],
                        in + static_cast<uint64_t>(to) * chunk_bytes,
                        chunk_bytes, c->mesh[from],
                        out + static_cast<uint64_t>(from) * chunk_bytes,
                        chunk_bytes) != 0)
      return -1;
  }
  return 0;
}

}  // namespace

// ---------------------------------------------------------------------------
// C API (ctypes surface)
// ---------------------------------------------------------------------------

extern "C" {

// Bumped whenever an exported signature changes (the Python binding
// refuses to drive a stale prebuilt .so whose symbols still resolve but
// whose ABI differs — e.g. the op argument added to the ring kernels).
// v3: full data mesh + true reduce-scatter / pairwise alltoall kernels.
// v5: generic point-to-point sendrecv over the mesh (the hierarchical
// host collectives compose subgroup rings from it in Python).
int hvdnet_abi_version() { return 5; }

void* hvdnet_init(int rank, int world, const char* coord_host, int coord_port,
                  int timeout_ms) {
  Comm* c = new Comm();
  if (comm_init(c, rank, world, coord_host, coord_port, timeout_ms) != 0) {
    comm_close(c);  // release any sockets a partial setup established
    delete c;
    return nullptr;
  }
  return c;
}

void hvdnet_finalize(void* h) {
  Comm* c = static_cast<Comm*>(h);
  if (!c) return;
  comm_close(c);
  delete c;
}

// Wake every verb blocked on this communicator — from ANY thread —
// without freeing fds. The steady-state verb reads are unbounded
// (recv_all / duplex_exchange poll with no deadline: a healthy round
// always completes, and a per-read deadline would misfire under fusion
// backpressure), so a partitioned-but-alive peer blocks them forever.
// ::shutdown(SHUT_RDWR) makes a concurrently blocked recv return 0
// ("peer closed") immediately, failing the verb with the normal
// transport-lost path; unlike ::close it does not release the fd, so
// the blocked thread never touches a recycled descriptor. The watchdog
// in runtime/socket_controller.py calls this when a control round
// exceeds HOROVOD_COLLECTIVE_TIMEOUT.
void hvdnet_abort(void* h) {
  Comm* c = static_cast<Comm*>(h);
  if (!c) return;
  for (int fd : c->star)
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  for (int fd : c->mesh)
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
}

int hvdnet_rank(void* h) { return static_cast<Comm*>(h)->rank; }
int hvdnet_world(void* h) { return static_cast<Comm*>(h)->world; }

// Cumulative data-plane bytes this process sent through the collective
// kernels (ring allreduce / reduce-scatter / pairwise alltoall).
uint64_t hvdnet_data_bytes_sent(void* h) {
  Comm* c = static_cast<Comm*>(h);  // null after close(): report 0,
  return c ? c->counters.data_bytes_sent.load() : 0;  // don't crash
}

// Cumulative ring/mesh kernel steps (duplex exchanges) — fusion's
// dispatch-count win is this counter's delta.
uint64_t hvdnet_exchange_calls(void* h) {
  Comm* c = static_cast<Comm*>(h);
  return c ? c->counters.exchange_calls.load() : 0;
}

// Cumulative control-plane (star) bytes this process sent — negotiation
// gathers/bcasts and cache-bit syncs; the response cache's byte
// amortization is this counter's per-op delta.
uint64_t hvdnet_ctrl_bytes_sent(void* h) {
  Comm* c = static_cast<Comm*>(h);
  return c ? c->counters.ctrl_bytes_sent.load() : 0;
}

int hvdnet_barrier(void* h) { return barrier(static_cast<Comm*>(h)); }

int hvdnet_bit_and_or(void* h, uint64_t* words, uint64_t nwords,
                      uint64_t* out_and, uint64_t* out_or) {
  return bit_and_or(static_cast<Comm*>(h), words, nwords, out_and, out_or);
}

// Gather variable-length byte blobs to rank 0. On rank 0, out_lens must
// hold `world` entries and out must have capacity out_cap; returns total
// bytes written or -1. Workers return 0.
int64_t hvdnet_gatherv(void* h, const void* in, uint64_t in_len,
                       void* out, uint64_t out_cap, uint64_t* out_lens) {
  Comm* c = static_cast<Comm*>(h);
  std::vector<std::vector<char>> blobs;
  if (gatherv(c, in, in_len, &blobs) != 0) return -1;
  if (c->rank != 0) return 0;
  uint64_t off = 0;
  for (int r = 0; r < c->world; ++r) {
    out_lens[r] = blobs[r].size();
    if (off + blobs[r].size() > out_cap) return -1;
    std::memcpy(static_cast<char*>(out) + off, blobs[r].data(),
                blobs[r].size());
    off += blobs[r].size();
  }
  return static_cast<int64_t>(off);
}

// Broadcast a byte blob from rank 0. Workers pass a capacity buffer;
// returns the blob length or -1.
int64_t hvdnet_bcast(void* h, void* buf, uint64_t len_or_cap) {
  Comm* c = static_cast<Comm*>(h);
  if (c->rank == 0) {
    std::vector<char> data(static_cast<char*>(buf),
                           static_cast<char*>(buf) + len_or_cap);
    if (bcast(c, &data) != 0) return -1;
    return static_cast<int64_t>(len_or_cap);
  }
  std::vector<char> data;
  if (c->world > 1) {
    if (recv_frame(c->star[0], data) < 0) return -1;
    if (data.size() > len_or_cap) return -1;
    std::memcpy(buf, data.data(), data.size());
  }
  return static_cast<int64_t>(data.size());
}

int hvdnet_allreduce_f32(void* h, float* data, uint64_t count, int op) {
  return ring_allreduce_t<float>(static_cast<Comm*>(h), data, count, op);
}

int hvdnet_allreduce_f64(void* h, double* data, uint64_t count, int op) {
  return ring_allreduce_t<double>(static_cast<Comm*>(h), data, count, op);
}

int hvdnet_allreduce_i32(void* h, int32_t* data, uint64_t count, int op) {
  return ring_allreduce_t<int32_t>(static_cast<Comm*>(h), data, count, op);
}

int hvdnet_allreduce_i64(void* h, int64_t* data, uint64_t count, int op) {
  return ring_allreduce_t<int64_t>(static_cast<Comm*>(h), data, count, op);
}

// Half-ring reduce-scatter: `data` (count elements, all ranks equal
// shape) is consumed as scratch; rank r's fully-reduced chunk r lands in
// `out` (chunk sizes follow the same near-equal split as the ring
// allreduce). (w-1)/w of the payload crosses each link — optimal.
int hvdnet_reducescatter_f32(void* h, float* data, uint64_t count, int op,
                             float* out) {
  return ring_reducescatter_t<float>(static_cast<Comm*>(h), data, count, op,
                                     out);
}

int hvdnet_reducescatter_f64(void* h, double* data, uint64_t count, int op,
                             double* out) {
  return ring_reducescatter_t<double>(static_cast<Comm*>(h), data, count, op,
                                      out);
}

int hvdnet_reducescatter_i32(void* h, int32_t* data, uint64_t count, int op,
                             int32_t* out) {
  return ring_reducescatter_t<int32_t>(static_cast<Comm*>(h), data, count,
                                       op, out);
}

int hvdnet_reducescatter_i64(void* h, int64_t* data, uint64_t count, int op,
                             int64_t* out) {
  return ring_reducescatter_t<int64_t>(static_cast<Comm*>(h), data, count,
                                       op, out);
}

// Generic point-to-point exchange over the full data mesh: send `sn`
// bytes to `send_peer` while receiving `rn` bytes from `recv_peer`
// (full-duplex, same progress engine as the ring steps — a blocking
// one-direction-at-a-time send would deadlock symmetric exchanges whose
// payload exceeds the kernel socket buffers). Either direction may be
// zero-length (pure send / pure recv). Both sides of a transfer must
// agree on the byte count; framing is the caller's contract, exactly as
// in the ring kernels. The hierarchical host collectives compose
// intra-group and cross-group rings from this verb in Python so the
// slow hop can be compressed and fault-injected independently.
int hvdnet_sendrecv(void* h, int send_peer, const void* sbuf, uint64_t sn,
                    int recv_peer, void* rbuf, uint64_t rn) {
  Comm* c = static_cast<Comm*>(h);
  const int w = c->world;
  int sfd = -1, rfd = -1;
  if (sn > 0) {
    if (send_peer < 0 || send_peer >= w || send_peer == c->rank) return -1;
    sfd = c->mesh[send_peer];
    if (sfd < 0) return -1;
  }
  if (rn > 0) {
    if (recv_peer < 0 || recv_peer >= w || recv_peer == c->rank) return -1;
    rfd = c->mesh[recv_peer];
    if (rfd < 0) return -1;
  }
  if (sn == 0 && rn == 0) return 0;
  return duplex_exchange(&c->counters, sfd, sbuf, sn, rfd, rbuf, rn);
}

// Pairwise all-to-all: `in` holds world equal chunks of chunk_bytes
// (chunk j destined for rank j); `out` receives world chunks in source
// rank order. Dtype-agnostic (pure byte movement, no reduction).
int hvdnet_alltoall(void* h, const void* in, void* out,
                    uint64_t chunk_bytes) {
  return pairwise_alltoall(static_cast<Comm*>(h),
                           static_cast<const char*>(in),
                           static_cast<char*>(out), chunk_bytes);
}

// Allgatherv over the star: gather blobs to rank 0, then broadcast the
// concatenation (lens first). Every rank ends with all blobs in rank order.
// out must have capacity out_cap; out_lens has world entries; returns total.
int64_t hvdnet_allgatherv(void* h, const void* in, uint64_t in_len,
                          void* out, uint64_t out_cap, uint64_t* out_lens) {
  Comm* c = static_cast<Comm*>(h);
  std::vector<std::vector<char>> blobs;
  if (gatherv(c, in, in_len, &blobs) != 0) return -1;
  std::vector<char> packed;
  if (c->rank == 0) {
    uint64_t w = c->world;
    packed.resize(8 * w);
    for (uint64_t r = 0; r < w; ++r) {
      uint64_t n = blobs[r].size();
      std::memcpy(packed.data() + 8 * r, &n, 8);
    }
    for (auto& b : blobs) packed.insert(packed.end(), b.begin(), b.end());
  }
  if (bcast(c, &packed) != 0) return -1;
  uint64_t w = c->world;
  uint64_t off = 8 * w, total = 0;
  for (uint64_t r = 0; r < w; ++r) {
    std::memcpy(&out_lens[r], packed.data() + 8 * r, 8);
    total += out_lens[r];
  }
  if (total > out_cap || packed.size() != off + total) return -1;
  std::memcpy(out, packed.data() + off, total);
  return static_cast<int64_t>(total);
}

}  // extern "C"
