// Native timeline writer: lock-free SPSC ring + dedicated writer thread.
//
// TPU-native analogue of the reference's timeline machinery (reference:
// horovod/common/timeline.cc:28-127 TimelineWriter, timeline.h:66-75 —
// a boost::lockfree::spsc_queue drained by a writer thread so the hot
// coordination path never blocks on file I/O). Records are packed into a
// fixed byte ring by the producer (the runtime cycle thread, which holds
// the Python-side timeline lock, so single-producer holds); the consumer
// thread formats Chrome-trace JSON and writes buffered.
//
// On ring overflow events are dropped and counted; the drop count is
// emitted as a final metadata record at close so a truncated trace is
// detectable rather than silently misleading.
//
// C API (ctypes, no pybind11 in the image):
//   void* hvd_tl_open(const char* path);
//   int   hvd_tl_emit(void* h, char ph, int pid, double ts_us,
//                     const char* name, const char* args_json,
//                     const char* s);   // returns 1 if dropped
//   void  hvd_tl_close(void* h);

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>

namespace {

constexpr size_t kRingBytes = 1 << 20;  // 1 MiB of in-flight events

struct Record {
  char ph;
  int32_t pid;
  double ts_us;
  // followed by: u16 name_len, name bytes, u16 args_len, args bytes,
  // u8 s_len, s bytes
};

class SpscRing {
 public:
  // Producer: copy `n` bytes in if they fit; false on overflow.
  bool push(const uint8_t* data, uint32_t n) {
    size_t head = head_.load(std::memory_order_relaxed);
    size_t tail = tail_.load(std::memory_order_acquire);
    size_t free_bytes = kRingBytes - (head - tail);
    if (n + 4 > free_bytes) return false;
    write_bytes(head, reinterpret_cast<const uint8_t*>(&n), 4);
    write_bytes(head + 4, data, n);
    head_.store(head + 4 + n, std::memory_order_release);
    return true;
  }

  // Consumer: pop one record into out (must hold kRingBytes); 0 if empty.
  uint32_t pop(uint8_t* out) {
    size_t tail = tail_.load(std::memory_order_relaxed);
    size_t head = head_.load(std::memory_order_acquire);
    if (head == tail) return 0;
    uint32_t n;
    read_bytes(tail, reinterpret_cast<uint8_t*>(&n), 4);
    read_bytes(tail + 4, out, n);
    tail_.store(tail + 4 + n, std::memory_order_release);
    return n;
  }

 private:
  void write_bytes(size_t pos, const uint8_t* src, size_t n) {
    size_t off = pos % kRingBytes;
    size_t first = std::min(n, kRingBytes - off);
    memcpy(buf_ + off, src, first);
    if (first < n) memcpy(buf_, src + first, n - first);
  }
  void read_bytes(size_t pos, uint8_t* dst, size_t n) {
    size_t off = pos % kRingBytes;
    size_t first = std::min(n, kRingBytes - off);
    memcpy(dst, buf_ + off, first);
    if (first < n) memcpy(dst + first, buf_, n - first);
  }

  alignas(64) std::atomic<size_t> head_{0};  // producer-owned
  alignas(64) std::atomic<size_t> tail_{0};  // consumer-owned
  uint8_t buf_[kRingBytes];
};

class TimelineFile {
 public:
  explicit TimelineFile(const char* path) {
    file_ = fopen(path, "w");
    if (!file_) return;
    fputs("[\n", file_);
    thread_ = std::thread([this] { run(); });
  }

  bool ok() const { return file_ != nullptr; }

  int emit(char ph, int pid, double ts_us, const char* name,
           const char* args_json, const char* s) {
    uint8_t rec[4096];
    size_t off = 0;
    Record hdr{ph, pid, ts_us};
    memcpy(rec + off, &hdr, sizeof(hdr));
    off += sizeof(hdr);
    // oversized records and ring overflow both count as drops, so the
    // close-time dropped_events total is honest either way
    if (!pack_str(rec, sizeof(rec), off, name, 2) ||
        !pack_str(rec, sizeof(rec), off, args_json, 2) ||
        !pack_str(rec, sizeof(rec), off, s, 1) ||
        !ring_.push(rec, static_cast<uint32_t>(off))) {
      dropped_.fetch_add(1, std::memory_order_relaxed);
      return 1;
    }
    return 0;
  }

  void close() {
    if (!file_) return;
    closing_.store(true, std::memory_order_release);
    if (thread_.joinable()) thread_.join();
    long dropped = dropped_.load(std::memory_order_relaxed);
    if (dropped > 0) {
      fprintf(file_,
              "{\"ph\":\"M\",\"pid\":0,\"name\":\"dropped_events\","
              "\"args\":{\"count\":%ld}},\n",
              dropped);
    }
    fputs("{}]\n", file_);
    fclose(file_);
    file_ = nullptr;
  }

  ~TimelineFile() { close(); }

 private:
  static bool pack_str(uint8_t* rec, size_t cap, size_t& off,
                       const char* s, int len_bytes) {
    size_t n = s ? strlen(s) : 0;
    if (n > 0xFFFF) n = 0xFFFF;
    if (off + static_cast<size_t>(len_bytes) + n > cap) return false;
    if (len_bytes == 2) {
      uint16_t v = static_cast<uint16_t>(n);
      memcpy(rec + off, &v, 2);
      off += 2;
    } else {
      rec[off++] = static_cast<uint8_t>(n);
    }
    if (n) memcpy(rec + off, s, n);
    off += n;
    return true;
  }

  void run() {
    uint8_t rec[4096];
    std::string line;
    while (true) {
      uint32_t n = ring_.pop(rec);
      if (n == 0) {
        if (closing_.load(std::memory_order_acquire)) {
          // one final drain so no event races the shutdown flag
          n = ring_.pop(rec);
          if (n == 0) break;
        } else {
          std::this_thread::sleep_for(std::chrono::microseconds(500));
          continue;
        }
      }
      format(rec, line);
      fwrite(line.data(), 1, line.size(), file_);
    }
    fflush(file_);
  }

  void format(const uint8_t* rec, std::string& line) {
    Record hdr;
    memcpy(&hdr, rec, sizeof(hdr));
    size_t off = sizeof(hdr);
    auto take2 = [&](void) {
      uint16_t n;
      memcpy(&n, rec + off, 2);
      off += 2;
      const char* p = reinterpret_cast<const char*>(rec + off);
      off += n;
      return std::string(p, n);
    };
    std::string name = take2();
    std::string args = take2();
    uint8_t slen = rec[off++];
    std::string s(reinterpret_cast<const char*>(rec + off), slen);

    // ts is printed as integer-microseconds.fraction by hand: %.3f would
    // follow LC_NUMERIC and emit a decimal comma under some locales,
    // producing invalid JSON.
    long long ts_ns = static_cast<long long>(hdr.ts_us * 1000.0 + 0.5);
    char head[96];
    snprintf(head, sizeof(head),
             "{\"ph\":\"%c\",\"pid\":%d,\"ts\":%lld.%03lld", hdr.ph,
             hdr.pid, ts_ns / 1000, ts_ns % 1000);
    line.assign(head);
    if (!name.empty()) {
      line += ",\"name\":\"";
      append_escaped(line, name);
      line += '"';
    }
    if (!args.empty()) {
      line += ",\"args\":";
      line += args;  // caller-provided JSON, passed through
    }
    if (!s.empty()) {
      line += ",\"s\":\"";
      append_escaped(line, s);
      line += '"';
    }
    line += "},\n";
  }

  static void append_escaped(std::string& out, const std::string& in) {
    for (char c : in) {
      if (c == '"' || c == '\\') {
        out += '\\';
        out += c;
      } else if (static_cast<unsigned char>(c) < 0x20) {
        char buf[8];
        snprintf(buf, sizeof(buf), "\\u%04x", c);
        out += buf;
      } else {
        out += c;
      }
    }
  }

  FILE* file_ = nullptr;
  SpscRing ring_;
  std::thread thread_;
  std::atomic<bool> closing_{false};
  std::atomic<long> dropped_{0};
};

}  // namespace

extern "C" {

void* hvd_tl_open(const char* path) {
  auto* t = new TimelineFile(path);
  if (!t->ok()) {
    delete t;
    return nullptr;
  }
  return t;
}

int hvd_tl_emit(void* h, char ph, int pid, double ts_us, const char* name,
                const char* args_json, const char* s) {
  return static_cast<TimelineFile*>(h)->emit(ph, pid, ts_us, name,
                                             args_json, s);
}

void hvd_tl_close(void* h) {
  auto* t = static_cast<TimelineFile*>(h);
  t->close();
  delete t;
}

}  // extern "C"
