"""horovod_tpu.data — rank-sharded sampling + device prefetch.

The reference delegates input pipelines to the frameworks but fixes the
*convention* in every example: shard the dataset by rank so each worker
sees a disjoint 1/size slice, reshuffled per epoch with a common seed
(reference: examples/pytorch_mnist.py
``torch.utils.data.distributed.DistributedSampler(num_replicas=hvd.size(),
rank=hvd.rank())``; examples/keras_imagenet_resnet50.py per-rank
generators). This module provides that convention framework-free, plus the
TPU-idiomatic device side: an async prefetcher that keeps the next batches
in flight (host → HBM with the right sharding) so the step program never
waits on input — the jax analogue of the reference's framework loader
worker threads.

* :class:`ShardedSampler` — the DistributedSampler semantics: per-epoch
  deterministic shuffle shared by all workers, split into ``size`` equal
  shards (padded by wrap-around so every worker steps the same count —
  required for collective lockstep), ``set_epoch`` to reshuffle.
* :func:`prefetch_to_device` — wrap a host-batch iterator; batches are
  ``jax.device_put`` with a given sharding a configurable depth ahead, on
  a background thread. XLA's async dispatch overlaps the transfer with the
  running step.
* With the torch binding, ``torch.utils.data.distributed.DistributedSampler
  (num_replicas=hvd.size(), rank=hvd.rank())`` works as in the reference;
  tests/test_data.py pins that integration.
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import Iterable, Iterator, Optional

import jax
import numpy as np

__all__ = ["ShardedSampler", "prefetch_to_device"]


class ShardedSampler:
    """Per-worker view of a dataset: disjoint shards, equal length, common
    per-epoch shuffle (reference convention:
    torch DistributedSampler as used in examples/pytorch_mnist.py).

    ``len(dataset)`` need not divide ``num_replicas``: indices wrap around
    (the reference sampler's padding) so every worker yields exactly
    ``ceil(n / num_replicas)`` indices per epoch and collective calls stay
    in lockstep.
    """

    def __init__(self, dataset_size: int, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None, shuffle: bool = True,
                 seed: int = 0):
        from horovod_tpu.core import basics

        if num_replicas is None:
            num_replicas = basics.size()
        if rank is None:
            rank = basics.rank()
        if not 0 <= rank < num_replicas:
            raise ValueError(
                f"rank {rank} out of range for num_replicas {num_replicas}")
        if dataset_size <= 0:
            raise ValueError("dataset_size must be positive")
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.num_samples = -(-dataset_size // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle for a new epoch — same permutation on every worker
        (seed + epoch), different shard per rank."""
        self.epoch = int(epoch)

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            order = rng.permutation(self.dataset_size)
        else:
            order = np.arange(self.dataset_size)
        pad = self.total_size - self.dataset_size
        if pad:
            order = np.concatenate([order, order[:pad]])
        # interleaved shards of the common permutation (torch
        # DistributedSampler's rank::num_replicas striding)
        shard = order[self.rank::self.num_replicas]
        return iter(shard.tolist())

    def __len__(self) -> int:
        return self.num_samples


_END = object()


def prefetch_to_device(iterator: Iterable, size: int = 2, sharding=None):
    """Iterate ``iterator``'s batches with up to ``size`` batches already
    transferred to device (``jax.device_put`` pytree-wise, with ``sharding``
    if given — e.g. the batch sharding from ``make_train_step``).

    The transfer happens on a background thread and XLA's async dispatch
    overlaps it with the running step, so steady-state steps never wait on
    the host. Exceptions from the source iterator propagate to the
    consumer at the corresponding position. The generator's ``close()``
    (or garbage collection) stops the worker thread.
    """
    if size < 1:
        raise ValueError("prefetch size must be >= 1")

    q: queue_mod.Queue = queue_mod.Queue(maxsize=size)
    stop = threading.Event()

    def put(batch):
        if sharding is not None:
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), batch)
        return jax.tree_util.tree_map(jax.device_put, batch)

    def worker():
        try:
            for batch in iterator:
                if stop.is_set():
                    return
                q.put(put(batch))
                if stop.is_set():
                    return
            q.put(_END)
        except BaseException as exc:  # noqa: BLE001 — relayed to consumer
            q.put(exc)

    thread = threading.Thread(target=worker, daemon=True,
                              name="hvd-data-prefetch")

    def gen():
        # start lazily so a generator that is never consumed never spawns
        # (and never leaks) the worker or its in-flight device batches
        thread.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            # unblock a worker stuck on a full queue
            try:
                q.get_nowait()
            except queue_mod.Empty:
                pass

    return gen()
