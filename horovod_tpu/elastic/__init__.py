"""Elastic (fault-tolerant) training — TPU-native port of Horovod
Elastic (reference: horovod/common/elastic.py, horovod/run/elastic/).

Usage::

    import horovod_tpu as hvd
    from horovod_tpu import elastic

    hvd.init()
    state = elastic.ArrayState(params=params, optimizer=opt_state, step=0)

    @elastic.run
    def train(state):
        while state.step < total_steps:
            state.params, state.optimizer = train_step(...)
            state.step += 1
            state.commit()

    train(state)

On a worker failure the runtime raises
:class:`~horovod_tpu.exceptions.WorkersDownError`; the ``@elastic.run``
wrapper re-forms membership through the rendezvous KV store, rebuilds the
mesh, rolls back to the last ``commit()`` and calls ``train`` again. See
docs/elastic.md.
"""

from horovod_tpu.elastic.fault_inject import FaultSpec, maybe_inject
from horovod_tpu.elastic.runner import (
    Backoff,
    check_host_updates,
    restarts,
    run,
    start_heartbeat,
)
from horovod_tpu.elastic.state import ArrayState, ObjectState, State
from horovod_tpu.exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
    WorkerLostError,
    WorkersDownError,
    WorkerStallError,
)

__all__ = [
    "ArrayState", "ObjectState", "State",
    "run", "restarts", "Backoff",
    "start_heartbeat", "check_host_updates",
    "FaultSpec", "maybe_inject",
    "HorovodInternalError", "WorkersDownError", "WorkerLostError",
    "WorkerStallError", "HostsUpdatedInterrupt",
]
