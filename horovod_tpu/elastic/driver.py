"""Launcher-side elastic membership driver.

TPU-native analogue of the reference's ElasticDriver (reference:
horovod/run/elastic/driver.py): a background thread in the ``tpurun``
process that

* polls a ``--host-discovery-script`` (stdout: one ``hostname[:slots]``
  per line — the reference's contract) for the current host set,
* watches worker heartbeats in the rendezvous server's ``heartbeat``
  scope (workers beat via ``elastic.runner.start_heartbeat``; a beat
  older than the TTL marks the worker lost),
* publishes a host-change notice into the ``elastic.notice`` scope —
  workers observe it at their next commit and re-form membership
  (:func:`horovod_tpu.elastic.runner.check_host_updates`).

The driver never kills or spawns workers itself: the worker-side re-form
protocol owns membership, which keeps the driver a pure observer the job
can survive losing.
"""

from __future__ import annotations

import json
import shlex
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import _get_float

HOROVOD_ELASTIC_DISCOVERY_INTERVAL_SECONDS = \
    "HOROVOD_ELASTIC_DISCOVERY_INTERVAL_SECONDS"

_WORKERS_ADDED = _metrics().counter(
    "horovod_elastic_workers_added_total",
    "Hosts added to the job by the discovery script.")
_WORKERS_REMOVED = _metrics().counter(
    "horovod_elastic_workers_removed_total",
    "Workers lost across elastic re-forms, as seen by this process.")


class HostDiscoveryScript:
    """Run the user's discovery script; parse ``hostname[:slots]`` lines
    (reference: horovod/run/elastic/discovery.py HostDiscoveryScript)."""

    def __init__(self, script: str, default_slots: int = 1):
        from horovod_tpu.utils import resilience

        self.script = script
        self.default_slots = default_slots
        # a flaky discovery script (NFS blip, transient fork failure) must
        # not make the driver report an empty host set and trigger a
        # spurious re-form — retry briefly before surfacing the error
        self._retry = resilience.RetryPolicy.from_env(
            "driver", max_retries=2, deadline=30.0)

    def find_available_hosts(self) -> Dict[str, int]:
        out = self._retry.call(
            self._run_script, phase="discovery",
            classify=lambda e: isinstance(
                e, (subprocess.SubprocessError, OSError)))
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" in line:
                name, slots = line.rsplit(":", 1)
                hosts[name] = int(slots)
            else:
                hosts[line] = self.default_slots
        return hosts

    def _run_script(self) -> str:
        return subprocess.run(
            shlex.split(self.script), capture_output=True, text=True,
            timeout=60, check=True).stdout


class ElasticDriver:
    """Membership observer thread. ``rendezvous`` is the launcher's
    :class:`~horovod_tpu.run.rendezvous.RendezvousServer`."""

    def __init__(self, rendezvous, discovery: Optional[HostDiscoveryScript]
                 = None, min_workers: int = 1,
                 max_workers: Optional[int] = None,
                 discovery_interval: Optional[float] = None,
                 heartbeat_ttl: Optional[float] = None):
        self._rendezvous = rendezvous
        self._discovery = discovery
        self.min_workers = min_workers
        self.max_workers = max_workers
        self._interval = (discovery_interval if discovery_interval is not None
                          else _get_float(
                              HOROVOD_ELASTIC_DISCOVERY_INTERVAL_SECONDS, 2.0))
        self._heartbeat_ttl = heartbeat_ttl
        self._hosts: Dict[str, int] = {}
        self._live_workers: set = set()
        self._notice_seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- membership math (unit-tested directly) ----------------------------
    @staticmethod
    def diff_hosts(old: Dict[str, int], new: Dict[str, int]
                   ) -> Tuple[List[str], List[str]]:
        """(added, removed) hostnames between two discovery snapshots —
        a slot-count change counts as removed+added (the worker layout on
        that host must be rebuilt)."""
        added = sorted(h for h in new if old.get(h) != new[h])
        removed = sorted(h for h in old if new.get(h) != old[h])
        return added, removed

    def current_hosts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._hosts)

    def start(self) -> None:
        if self._discovery is not None:
            try:
                self._hosts = self._discovery.find_available_hosts()
            except Exception as exc:
                log.warning("elastic driver: initial host discovery "
                            "failed: %s", exc)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="hvd-elastic-driver")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._poll_once()
            except Exception as exc:
                log.warning("elastic driver poll failed: %s", exc)

    def _poll_once(self) -> None:
        changed: List[str] = []
        if self._discovery is not None:
            new_hosts = self._discovery.find_available_hosts()
            with self._lock:
                added, removed = self.diff_hosts(self._hosts, new_hosts)
                self._hosts = new_hosts
            if added:
                _WORKERS_ADDED.inc(len(added))
                changed.append(f"hosts added: {','.join(added)}")
            if removed:
                _WORKERS_REMOVED.inc(len(removed))
                changed.append(f"hosts removed: {','.join(removed)}")

        lost = self._check_heartbeats()
        if lost:
            changed.append(f"heartbeats lost: {','.join(sorted(lost))}")

        if changed:
            notice = "; ".join(changed)
            log.warning("elastic driver: %s", notice)
            self._publish_notice(notice)

    def _check_heartbeats(self) -> set:
        live = set(self._rendezvous.live_keys(
            "heartbeat", ttl=self._heartbeat_ttl))
        with self._lock:
            lost = self._live_workers - live
            self._live_workers = self._live_workers | live
            # a lost worker stays lost until it beats again
            self._live_workers -= lost
        if lost:
            _WORKERS_REMOVED.inc(len(lost))
        return lost

    def _publish_notice(self, notice: str) -> None:
        self._notice_seq += 1
        self._rendezvous.put(
            "elastic.notice", "update",
            json.dumps({"seq": self._notice_seq, "notice": notice,
                        "time": time.time()}).encode())
