"""Deterministic failure injection for elastic testing.

``HOROVOD_FAULT_INJECT=<spec>`` arms a one-shot fault on a chosen rank at
a chosen step, letting tests and ``tpurun --elastic`` smoke runs exercise
the recovery path without real hardware failures. The env var holds one
or more ``;``-separated clauses; this module owns the *process* faults
(kill/hang/slow) while the *network* faults (``partition``,
``kv_outage``, ``flaky``, ``netdelay``) are parsed and fired by
``horovod_tpu.utils.resilience`` inside the transports — both kinds
compose in one spec, e.g.
``kill:rank=1:step=3;kv_outage:5:on=reform``. Process-fault grammar::

    <action>:rank=<r>:step=<s>[:code=<c>][:seconds=<t>][:gen=<g>]

* ``action`` — ``kill`` (``os._exit``), ``hang`` (one long sleep, so the
  stall inspector / transport timeout must detect it), or ``slow``
  (sleep ``seconds`` at EVERY step >= ``step`` — a persistent straggler
  for attribution tests, not a one-shot fault).
* ``rank`` — the rank to fault, matched against the worker's ORIGINAL
  launch rank (survivors are renumbered on re-form; the fault must not
  re-fire on whoever inherited the number).
* ``step`` — fire when the state's step counter reaches this value.
* ``code`` — exit code for ``kill`` (default 1).
* ``seconds`` — hang duration (default 3600) or per-step slowdown.
* ``gen`` — generation (restart count) in which the fault is armed
  (default 0: only before the first recovery).

The hook point is :func:`maybe_inject`, called by
``elastic.State.commit()`` every step and directly by tests.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Optional

from horovod_tpu import flight_recorder
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils import logging as log

HOROVOD_FAULT_INJECT = "HOROVOD_FAULT_INJECT"

_FAULTS_INJECTED = _metrics().counter(
    "horovod_elastic_faults_injected_total",
    "Deterministic faults fired by the HOROVOD_FAULT_INJECT harness.")

_ACTIONS = ("kill", "hang", "slow")

# "slow" logs on its first firing only (it re-fires every step)
_slow_announced = False

# the worker's launch-time rank: captured before any elastic re-form
# renumbers HOROVOD_RANK in os.environ
_initial_rank: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    action: str
    rank: int
    step: int
    code: int = 1
    seconds: float = 3600.0
    generation: int = 0


def parse_spec(text: str) -> FaultSpec:
    parts = text.strip().split(":")
    action = parts[0].strip().lower()
    if action not in _ACTIONS:
        raise ValueError(
            f"{HOROVOD_FAULT_INJECT}: unknown action {action!r} "
            f"(expected one of {_ACTIONS})")
    fields = {"rank": None, "step": None, "code": 1,
              "seconds": 3600.0, "gen": 0}
    for part in parts[1:]:
        if "=" not in part:
            raise ValueError(
                f"{HOROVOD_FAULT_INJECT}: malformed clause {part!r} "
                f"(expected key=value)")
        key, value = part.split("=", 1)
        key = key.strip().lower()
        if key not in fields:
            raise ValueError(
                f"{HOROVOD_FAULT_INJECT}: unknown key {key!r} "
                f"(expected one of {sorted(fields)})")
        fields[key] = float(value) if key == "seconds" else int(value)
    if fields["rank"] is None or fields["step"] is None:
        raise ValueError(
            f"{HOROVOD_FAULT_INJECT}: spec must name rank= and step=")
    return FaultSpec(action=action, rank=fields["rank"], step=fields["step"],
                     code=fields["code"], seconds=fields["seconds"],
                     generation=fields["gen"])


def specs_from_env() -> tuple:
    """Every process-fault clause of the (possibly composite) env spec —
    a multi-rank chaos cell arms one kill per target rank, and each
    worker must see the clause naming ITS rank, not just the first.
    Network-fault clauses (partition/kv_outage/flaky/netdelay) belong to
    ``utils.resilience`` and data-corruption clauses (bitflip/nan) to
    ``integrity.inject``; both are skipped here, not rejected."""
    from horovod_tpu.integrity import inject as _integrity_inject
    from horovod_tpu.utils import resilience

    specs = []
    for clause in os.environ.get(HOROVOD_FAULT_INJECT, "").split(";"):
        clause = clause.strip()
        if not clause or resilience.is_net_clause(clause) \
                or _integrity_inject.is_integrity_clause(clause):
            continue
        specs.append(parse_spec(clause))
    return tuple(specs)


def spec_from_env() -> Optional[FaultSpec]:
    """First process-fault clause (see :func:`specs_from_env`)."""
    specs = specs_from_env()
    return specs[0] if specs else None


def initial_rank() -> int:
    """The rank this process launched with, frozen on first access —
    re-forms rewrite ``HOROVOD_RANK`` but must not re-target faults."""
    global _initial_rank
    if _initial_rank is None:
        _initial_rank = int(os.environ.get("HOROVOD_RANK", "0"))
    return _initial_rank


def maybe_inject(step: int, rank: Optional[int] = None,
                 generation: int = 0) -> None:
    """Fire the armed fault if (rank, step, generation) all match.

    ``kill`` and ``hang`` fire exactly at ``spec.step``; ``slow`` fires at
    every step >= ``spec.step`` (a persistent straggler)."""
    if rank is None:
        rank = initial_rank()
    for spec in specs_from_env():
        _fire(spec, step, rank, generation)


def _fire(spec: FaultSpec, step: int, rank: int, generation: int) -> None:
    global _slow_announced
    if rank != spec.rank or generation != spec.generation:
        return
    if spec.action == "slow":
        if step < spec.step:
            return
        _FAULTS_INJECTED.inc()
        if not _slow_announced:
            _slow_announced = True
            log.error("fault injection: slowing rank %d by %.3fs per step "
                      "from step %d on", rank, spec.seconds, spec.step)
        flight_recorder.emit("fault_inject", action="slow", rank=rank,
                             step=step, seconds=spec.seconds)
        time.sleep(spec.seconds)
        return
    if step != spec.step:
        return
    _FAULTS_INJECTED.inc()
    if spec.action == "kill":
        log.error("fault injection: killing rank %d at step %d "
                  "(exit code %d)", rank, step, spec.code)
        # os._exit bypasses atexit and signal handlers, so the flight
        # recorder must dump here or the postmortem loses the culprit
        flight_recorder.emit("fault_inject", action="kill", rank=rank,
                             step=step, code=spec.code)
        flight_recorder.dump_on_failure("fault_inject_kill")
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(spec.code)
    log.error("fault injection: hanging rank %d at step %d for %.0fs",
              rank, step, spec.seconds)
    flight_recorder.emit("fault_inject", action="hang", rank=rank,
                         step=step, seconds=spec.seconds)
    time.sleep(spec.seconds)
