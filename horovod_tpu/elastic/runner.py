"""Elastic runner: catch failures, re-form membership, resume.

TPU-native analogue of the reference's ``@hvd.elastic.run`` (reference:
horovod/common/elastic.py ``run_fn``): the decorated training function
takes a :class:`~horovod_tpu.elastic.state.State` first; when the runtime
raises :class:`~horovod_tpu.exceptions.WorkersDownError` (peer death,
transport loss, stall eviction) the runner

1. tears the framework down (``hvd.shutdown``),
2. re-forms membership through the rendezvous HTTP KV store — every
   survivor registers under a per-generation scope; after the membership
   quiesces, the LOWEST surviving old rank acts as leader, renumbers the
   survivors contiguously (itself becoming the new rank 0), binds a fresh
   coordinator port and publishes the assignment,
3. rebuilds the mesh (``core.basics.reinit``) from the rewritten env,
4. rolls state back to the last commit (``state.on_reset``) and
   re-broadcasts it from the new rank 0 (``state.sync``),

then calls the function again. Membership scans below
``HOROVOD_ELASTIC_MIN_WORKERS`` retry with bounded exponential backoff
(:class:`Backoff`). A :class:`~horovod_tpu.exceptions.HostsUpdatedInterrupt`
(driver host-change notice, checked at each commit) takes the same path
minus the rollback.
"""

from __future__ import annotations

import functools
import json
import os
import socket as socket_mod
import time
from typing import Iterator, List, Optional

from horovod_tpu import exceptions, flight_recorder
from horovod_tpu.core import basics
from horovod_tpu.elastic import fault_inject
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils import logging as log
from horovod_tpu.utils import resilience
from horovod_tpu.utils.env import _get_float, _get_int

HOROVOD_ELASTIC = "HOROVOD_ELASTIC"
HOROVOD_ELASTIC_MIN_WORKERS = "HOROVOD_ELASTIC_MIN_WORKERS"
HOROVOD_ELASTIC_MAX_RETRIES = "HOROVOD_ELASTIC_MAX_RETRIES"
HOROVOD_ELASTIC_SETTLE_SECONDS = "HOROVOD_ELASTIC_SETTLE_SECONDS"
HOROVOD_ELASTIC_REJOIN_TIMEOUT_SECONDS = \
    "HOROVOD_ELASTIC_REJOIN_TIMEOUT_SECONDS"
HOROVOD_ELASTIC_BACKOFF_BASE_SECONDS = "HOROVOD_ELASTIC_BACKOFF_BASE_SECONDS"
HOROVOD_ELASTIC_BACKOFF_MAX_SECONDS = "HOROVOD_ELASTIC_BACKOFF_MAX_SECONDS"
HOROVOD_ELASTIC_HEARTBEAT_SECONDS = "HOROVOD_ELASTIC_HEARTBEAT_SECONDS"

_RESTARTS_TOTAL = _metrics().counter(
    "horovod_elastic_restarts_total",
    "Successful elastic re-forms after a failure (per process).")
_WORKERS_REMOVED = _metrics().counter(
    "horovod_elastic_workers_removed_total",
    "Workers lost across elastic re-forms, as seen by this process.")
_GENERATION_GAUGE = _metrics().gauge(
    "horovod_elastic_generation",
    "Current membership generation (0 = original launch).")

_LOCAL_HOSTS = ("127.0.0.1", "localhost", "::1")

# process-local membership generation; bumped by every successful re-form
_generation = 0
_heartbeat_thread = None
_last_notice: Optional[str] = None


def restarts() -> int:
    """How many times this process has re-formed (the generation)."""
    return _generation


class Backoff:
    """Deterministic bounded exponential backoff schedule.

    ``delays()`` yields exactly ``retries`` sleep durations:
    ``base, base*factor, ...`` capped at ``max_delay`` — pure arithmetic,
    unit-testable without sleeping.
    """

    def __init__(self, base: float = 0.5, factor: float = 2.0,
                 max_delay: float = 10.0, retries: int = 5):
        if base <= 0 or factor < 1 or retries < 0:
            raise ValueError("base > 0, factor >= 1, retries >= 0 required")
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.retries = retries

    def delays(self) -> Iterator[float]:
        delay = self.base
        for _ in range(self.retries):
            yield min(delay, self.max_delay)
            delay *= self.factor

    def schedule(self) -> List[float]:
        return list(self.delays())

    @classmethod
    def from_env(cls) -> "Backoff":
        return cls(
            base=_get_float(HOROVOD_ELASTIC_BACKOFF_BASE_SECONDS, 0.5),
            max_delay=_get_float(HOROVOD_ELASTIC_BACKOFF_MAX_SECONDS, 10.0),
            retries=_get_int(HOROVOD_ELASTIC_MAX_RETRIES, 5))


def _kv_client(scope: str = "global"):
    """Worker-side rendezvous KV client, or None when the launcher did not
    provide the HTTP store (single-process / manual runs)."""
    addr = os.environ.get("HOROVOD_RENDEZVOUS_HTTP_ADDR")
    port = os.environ.get("HOROVOD_RENDEZVOUS_HTTP_PORT")
    if not addr or not port:
        return None
    from horovod_tpu.run.rendezvous import KVStoreClient

    timeout = _get_float(HOROVOD_ELASTIC_REJOIN_TIMEOUT_SECONDS, 60.0)
    return KVStoreClient(addr, int(port), scope=scope, timeout=timeout)


def _worker_uid() -> str:
    return f"{fault_inject.initial_rank()}-{os.getpid()}"


def _my_address() -> str:
    """Address peers can dial this worker's new coordinator on. Loopback
    jobs stay on loopback; otherwise the host's primary address."""
    old = os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
    if old in _LOCAL_HOSTS:
        return old
    try:
        return socket_mod.gethostbyname(socket_mod.gethostname())
    except OSError:
        return old


def _free_port() -> int:
    with socket_mod.socket() as s:
        s.bind(("0.0.0.0", 0))
        return s.getsockname()[1]


def start_heartbeat() -> None:
    """Begin announcing liveness into the rendezvous ``heartbeat`` scope
    (the elastic driver evicts workers whose beat exceeds the TTL)."""
    global _heartbeat_thread
    if _heartbeat_thread is not None and _heartbeat_thread.is_alive():
        return
    client = _kv_client(scope="heartbeat")
    if client is None:
        return
    import threading

    interval = _get_float(HOROVOD_ELASTIC_HEARTBEAT_SECONDS, 2.0)
    uid = _worker_uid()

    def _beat():
        while True:
            try:
                client.set(uid, json.dumps(
                    {"rank": int(os.environ.get("HOROVOD_RANK", "0")),
                     "generation": _generation}).encode())
            except OSError:
                pass  # launcher going away is the job-level teardown
            time.sleep(interval)

    _heartbeat_thread = threading.Thread(
        target=_beat, daemon=True, name="hvd-elastic-heartbeat")
    _heartbeat_thread.start()


def check_host_updates() -> None:
    """Raise :class:`HostsUpdatedInterrupt` if the driver published a new
    host-change notice since the last check (called from State.commit —
    the only boundary where re-forming is safe). The first observation
    only sets the baseline."""
    global _last_notice
    client = _kv_client(scope="elastic.notice")
    if client is None:
        return
    try:
        notice = client.get("update", wait=False).decode()
    except (KeyError, OSError):
        return
    if _last_notice is None:
        _last_notice = notice
        return
    if notice != _last_notice:
        _last_notice = notice
        raise exceptions.HostsUpdatedInterrupt(
            f"elastic driver notice: {notice}")


def _scan_members(client, scope: str, settle: float,
                  deadline: float) -> List[int]:
    """Poll the per-generation membership scope until it quiesces: no new
    registration for ``settle`` seconds (survivors discover the failure at
    different times — commit boundary vs transport timeout)."""
    members: List[int] = []
    last_change = time.monotonic()
    while True:
        now = time.monotonic()
        try:
            seen = sorted(int(k.split(".", 1)[1])
                          for k in client.keys(scope)
                          if k.startswith("member."))
        except OSError:
            # transient rendezvous outage (restart, kv_outage chaos):
            # keep polling until the rejoin deadline, don't lose quorum
            if now >= deadline:
                return members
            time.sleep(0.2)
            continue
        if seen != members:
            members, last_change = seen, now
        elif members and now - last_change >= settle:
            return members
        if now >= deadline:
            return members
        time.sleep(0.1)


def _reform(min_workers: int, backoff: Backoff) -> List[int]:
    """Re-form membership for generation ``_generation + 1`` and
    re-initialize the framework from the rewritten env. Returns the old
    ranks that did NOT make it into the new generation (the goodput
    incident's culprit candidates)."""
    global _generation
    client = _kv_client()
    if client is None:
        raise exceptions.WorkersDownError(
            "cannot re-form: no rendezvous KV store "
            "(HOROVOD_RENDEZVOUS_HTTP_ADDR/PORT unset)")

    gen = _generation + 1
    scope = f"elastic.g{gen}"
    old_rank = int(os.environ.get("HOROVOD_RANK", "0"))
    old_size = int(os.environ.get("HOROVOD_SIZE", "1"))
    settle = _get_float(HOROVOD_ELASTIC_SETTLE_SECONDS, 1.0)
    rejoin_timeout = _get_float(HOROVOD_ELASTIC_REJOIN_TIMEOUT_SECONDS, 60.0)

    basics.shutdown()
    _shutdown_jax_distributed()

    deadline = time.monotonic() + rejoin_timeout
    # registration must survive a rendezvous outage spanning the client
    # retry budget — keep trying for the whole rejoin window
    while True:
        try:
            client.set(f"member.{old_rank}", _worker_uid().encode(),
                       scope=scope)
            break
        except OSError as exc:
            if time.monotonic() >= deadline:
                raise exceptions.WorkersDownError(
                    f"elastic re-form failed: cannot register with the "
                    f"rendezvous store within {rejoin_timeout:g}s "
                    f"({exc})") from exc
            time.sleep(0.5)
    members = _scan_members(client, scope, settle, deadline)
    # retry the scan with backoff while below quorum (survivors discover
    # the failure at very different times: commit boundary vs transport
    # timeout — an early scan can quiesce with just this worker)
    for delay in backoff.delays():
        if len(members) >= min_workers:
            break
        log.warning(
            "elastic: %d/%d workers present; retrying in %.1fs",
            len(members), min_workers, delay)
        time.sleep(delay)
        members = _scan_members(
            client, scope, settle, time.monotonic() + rejoin_timeout)
    if len(members) < min_workers:
        raise exceptions.WorkersDownError(
            f"elastic re-form failed: {len(members)} workers "
            f"registered, HOROVOD_ELASTIC_MIN_WORKERS={min_workers} "
            f"(after {backoff.retries} retries)")
    # leadership decided AFTER the final scan — an early lone scanner
    # must not keep a stale claim once more survivors register, or two
    # leaders publish conflicting assignments
    if min(members) == old_rank:
        addr = _my_address()
        assignment = {
            "generation": gen,
            "size": len(members),
            # lowest surviving old rank -> new rank 0: the sync root owns
            # the authoritative committed state
            "ranks": {str(r): i for i, r in enumerate(members)},
            "addr": addr,
            "port": _free_port(),
            "coordinator": f"{addr}:{_free_port()}",
        }
        client.set("assign", json.dumps(assignment).encode(), scope=scope)
    try:
        assignment = json.loads(client.get("assign", scope=scope).decode())
    except (KeyError, TimeoutError) as exc:
        raise exceptions.WorkersDownError(
            f"elastic re-form failed: no assignment for generation {gen} "
            f"({exc})") from exc

    new_rank = assignment["ranks"].get(str(old_rank))
    if new_rank is None:
        raise exceptions.WorkersDownError(
            f"this worker (old rank {old_rank}) was not included in the "
            f"generation-{gen} assignment — exiting")

    new_size = int(assignment["size"])
    os.environ["HOROVOD_RANK"] = str(new_rank)
    os.environ["HOROVOD_SIZE"] = str(new_size)
    os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = assignment["addr"]
    os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(assignment["port"])
    # derived topology env recomputes from rank/size defaults
    for stale in ("HOROVOD_LOCAL_RANK", "HOROVOD_LOCAL_SIZE",
                  "HOROVOD_CROSS_RANK", "HOROVOD_CROSS_SIZE"):
        os.environ.pop(stale, None)
    if os.environ.get("HOROVOD_COORDINATOR_ADDR"):
        os.environ["HOROVOD_COORDINATOR_ADDR"] = assignment["coordinator"]
        os.environ["HOROVOD_NUM_PROCESSES"] = str(new_size)
        os.environ["HOROVOD_PROCESS_ID"] = str(new_rank)

    _generation = gen
    # publish the new generation to the resilience fence: any late reply
    # or error still in flight from the old epoch's communicator is now
    # discarded instead of delivered into the re-formed job
    resilience.set_generation(gen)
    _GENERATION_GAUGE.set(gen)
    if new_size < old_size:
        _WORKERS_REMOVED.inc(old_size - new_size)
    log.warning("elastic: re-formed generation %d — old rank %d -> "
                "new rank %d of %d", gen, old_rank, new_rank, new_size)
    # members/old_size let the postmortem name who did NOT make it into
    # the new generation (a partitioned rank never ships its own dump)
    flight_recorder.emit("elastic_reform", generation=gen,
                         old_rank=old_rank, new_rank=new_rank,
                         size=new_size, members=members,
                         old_size=old_size)
    basics.reinit()
    return sorted(set(range(old_size)) - set(members))


def _shutdown_jax_distributed() -> None:
    """Best-effort jax.distributed teardown before re-forming: the old
    coordinator may be the dead worker. Failure is survivable — socket
    mode (the tested elastic path) never initialized it."""
    try:
        import jax

        from horovod_tpu.core.basics import _jax_dist_initialized

        if _jax_dist_initialized():
            jax.distributed.shutdown()
    except Exception as exc:
        log.warning("jax.distributed shutdown during re-form failed: %s",
                    exc)


def run(func):
    """Decorator: elastic-retrying entry point (reference:
    horovod/common/elastic.py ``run``). The wrapped function's first
    argument must be a :class:`~horovod_tpu.elastic.state.State`."""

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        fault_inject.initial_rank()  # freeze before any re-form renumbers
        min_workers = _get_int(HOROVOD_ELASTIC_MIN_WORKERS, 1)
        start_heartbeat()
        rollback = False
        while True:
            if rollback is not False:
                backoff = Backoff.from_env()
                # goodput bracket: everything from quiesce through the
                # post-re-form sync is elastic_reform badput, and steps
                # rolled back to the last commit will be replayed —
                # charged to this incident, not to productive time
                t_reform = time.monotonic()
                step_before = getattr(state, "step", None)
                missing = _reform(min_workers, backoff)
                if rollback:  # failure path: roll back to the last commit
                    state.on_reset()
                # either way the new rank 0's copy becomes authoritative
                state.sync(root_rank=0)
                # sync consumed any neighbor replicas (zero.resync); the
                # old-rank tags are meaningless in the new membership
                try:
                    from horovod_tpu.ckpt import replica as _ckpt_replica
                    _ckpt_replica.clear("reform")
                except Exception:
                    pass
                if rollback:
                    _RESTARTS_TOTAL.inc()
                try:
                    from horovod_tpu import goodput

                    step_after = getattr(state, "step", None)
                    replay = 0
                    if isinstance(step_before, int) \
                            and isinstance(step_after, int):
                        replay = max(0, step_before - step_after)
                    goodput.note_incident(
                        "elastic_reform",
                        time.monotonic() - t_reform,
                        generation=_generation,
                        culprit_rank=missing[0] if missing else None,
                        replay_steps=replay,
                        linked_events=["elastic_reform", "workers_down"])
                except Exception:
                    pass  # accounting must never fail a re-form
                rollback = False
            try:
                return func(state, *args, **kwargs)
            except exceptions.HostsUpdatedInterrupt as exc:
                log.warning("elastic: %s — re-forming to fold in the new "
                            "host set", exc)
                flight_recorder.emit("hosts_updated", notice=str(exc)[:200])
                rollback = None  # re-form without rollback
            except exceptions.NumericalError as exc:
                # no worker is down: every rank raised the identical
                # digest/guard verdict together, so recovery is an
                # in-place rollback-and-replay — no membership re-form,
                # no process restart. handle_failure re-raises when the
                # HOROVOD_ROLLBACK_BUDGET is spent (supervised restart
                # takes over) and may exit a quarantined suspect.
                log.warning("elastic: integrity failure (%s) — rolling "
                            "back in place", exc)
                flight_recorder.dump_on_failure("integrity_violation")
                from horovod_tpu.integrity import rollback as _rollback

                _rollback.handle_failure(state, exc)
                continue
            except exceptions.WorkersDownError as exc:
                log.warning("elastic: workers down (%s) — attempting "
                            "recovery", exc)
                flight_recorder.emit(
                    "workers_down",
                    ranks=sorted(getattr(exc, "ranks", None) or []),
                    error=str(exc)[:200])
                flight_recorder.dump_on_failure(
                    "worker_stall"
                    if isinstance(exc, exceptions.WorkerStallError)
                    else "worker_lost")
                rollback = True
            except Exception as exc:
                # elastic OOM boundary: an XLA RESOURCE_EXHAUSTED raised
                # by user step code (not through the executor) still gets
                # forensics — ledger + top-k live arrays in the dump —
                # before propagating. Anything else re-raises untouched.
                from horovod_tpu import memory

                memory.maybe_record_oom(exc, where="elastic")
                raise

    return wrapper
