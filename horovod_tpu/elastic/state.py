"""Elastic training state: commit / restore / sync.

TPU-native port of the reference's elastic state objects (reference:
horovod/common/elastic.py — ``State.commit/restore/sync``,
horovod/torch/elastic/state.py ``TorchState``): the training loop keeps
its recoverable values (model params, optimizer state, step counter) in a
:class:`State`; ``commit()`` snapshots them in memory every step (and
optionally spills asynchronously to disk via :mod:`horovod_tpu.checkpoint`);
after a failure the elastic runner calls ``restore()`` to roll every
survivor back to its last snapshot and ``sync()`` to re-broadcast the
authoritative copy from the new rank 0 — which, by the re-form protocol
(runner.py), is the lowest surviving old rank.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from horovod_tpu import flight_recorder
from horovod_tpu.analysis import witness
from horovod_tpu.core import basics
from horovod_tpu.elastic import fault_inject
from horovod_tpu.metrics import COMMIT_BUCKETS, registry as _metrics
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import _get_bool

HOROVOD_ELASTIC_SPILL_DIR = "HOROVOD_ELASTIC_SPILL_DIR"
HOROVOD_ELASTIC_SPILL_SYNC = "HOROVOD_ELASTIC_SPILL_SYNC"
HOROVOD_CKPT_DIR = "HOROVOD_CKPT_DIR"

_COMMITS = _metrics().counter(
    "horovod_elastic_commits_total",
    "State.commit() snapshots taken (per process).")
_COMMIT_DURATION = _metrics().histogram(
    "horovod_elastic_commit_duration_seconds",
    "Wall time of one State.commit() (snapshot; excludes the async "
    "spill, which runs off-thread).", buckets=COMMIT_BUCKETS)


def _host_copy(tree):
    """A host-resident deep copy of an array pytree: snapshots must not
    alias live buffers the training loop keeps mutating."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: np.array(a) if hasattr(a, "shape") else a,
        jax.device_get(tree))


def broadcast_object_wire(obj: Any, root_rank: int = 0) -> Any:
    """Broadcast a picklable object over the collective wire.

    Unlike :func:`horovod_tpu.parallel.dp.broadcast_object` (identity
    without ``jax.distributed``), this rides the runtime's named-tensor
    lane — it works in socket-controller mode, which is exactly where the
    elastic re-form runs. Two-phase: length first (peers cannot know the
    root's payload size), then the padded payload. Collective: every rank
    must call it in the same order.
    """
    from horovod_tpu.ops import collectives

    st = basics._ensure_init()
    if st.size <= 1:
        return obj
    payload = pickle.dumps(obj) if st.rank == root_rank else b""
    n = int(np.asarray(collectives.broadcast(
        np.array([len(payload)], np.int64), root_rank))[0])
    buf = np.zeros((n,), np.uint8)
    if st.rank == root_rank:
        buf[:] = np.frombuffer(payload, np.uint8)
    out = np.asarray(collectives.broadcast(buf, root_rank))
    return pickle.loads(out.tobytes())


class State:
    """Base elastic state (reference: horovod/common/elastic.py State).

    Subclasses implement ``save``/``restore_snapshot``/``sync``;
    ``commit()`` wraps ``save`` with the fault-injection hook, metrics and
    the optional async disk spill. ``spill_dir`` (or
    ``HOROVOD_ELASTIC_SPILL_DIR``) enables the spill; rank 0 writes
    (checkpoint.py convention). ``HOROVOD_ELASTIC_SPILL_SYNC=1`` makes
    the spill synchronous (tests / strict durability).
    """

    def __init__(self, spill_dir: Optional[str] = None,
                 ckpt_dir: Optional[str] = None):
        self._spill_dir = spill_dir or os.environ.get(
            HOROVOD_ELASTIC_SPILL_DIR, "")
        self._spill_sync = _get_bool(HOROVOD_ELASTIC_SPILL_SYNC)
        self._spill_lock = witness.make_lock("State._spill_lock")
        self._spill_next: Optional[tuple] = None  # guarded-by: _spill_lock
        self._spill_thread: Optional[threading.Thread] = None  # guarded-by: _spill_lock
        self._reset_callbacks: list = []
        self._ckpt_dir = ckpt_dir or os.environ.get(HOROVOD_CKPT_DIR, "")
        self._ckpt = None  # CheckpointManager, created on first commit

    # -- subclass surface --------------------------------------------------
    def save(self) -> None:
        """Snapshot current values in memory."""
        raise NotImplementedError

    def restore_snapshot(self) -> None:
        """Roll values back to the last snapshot (process-local)."""
        raise NotImplementedError

    def sync(self, root_rank: int = 0) -> None:
        """Make ``root_rank``'s values authoritative everywhere."""
        raise NotImplementedError

    def _spill_payload(self):
        """(pytree, step) to persist on spill, or None to skip."""
        return None

    def _exchange_replicas(self, step: int) -> None:
        """Ship this rank's ZeRO shard bytes to its left neighbor
        (``ckpt.replica``). Runs BEFORE ``save()``: either the exchange
        and the snapshot both advance to ``step``, or neither does — a
        mid-commit death can never leave survivors whose replica and
        snapshot disagree about the rollback step. Base states hold no
        sharded leaves; ArrayState overrides."""

    # -- public API (reference names: commit / restore / on_reset) --------
    def commit(self) -> None:
        step = int(getattr(self, "step", 0))
        from horovod_tpu.elastic import runner as _runner

        fault_inject.maybe_inject(step, generation=_runner.restarts())
        t0 = time.monotonic()
        self._exchange_replicas(step)
        self.save()
        _COMMITS.inc()
        _COMMIT_DURATION.observe(time.monotonic() - t0)
        flight_recorder.emit("state_commit", step=step,
                             seconds=round(time.monotonic() - t0, 6))
        try:
            # goodput ledger: a commit is THE committed-step boundary —
            # claim the gap since the last accounted step as productive
            # (minus any badput spans inside it). The tracker frontier
            # dedups against the profiler step source when both run.
            from horovod_tpu import goodput

            goodput.record_step(step=step)
        except Exception:
            pass  # accounting must never fail a commit
        if self._ckpt_dir:
            self._ckpt_commit(step, _runner.restarts())
        elif self._spill_dir:
            payload = self._spill_payload()
            if payload is not None:
                self._spill(payload[0], payload[1])
        # commit is the one boundary where re-forming is safe: surface any
        # driver host-change notice here (raises HostsUpdatedInterrupt,
        # caught by @elastic.run AFTER this snapshot completed)
        _runner.check_host_updates()

    def _ckpt_commit(self, step: int, generation: int) -> None:
        """Hand the snapshot to the sharded two-phase checkpoint writer
        (:class:`horovod_tpu.ckpt.CheckpointManager`)."""
        payload = self._spill_payload()
        if payload is None:
            return
        if self._ckpt is None:
            from horovod_tpu import ckpt
            from horovod_tpu.elastic import runner as _runner

            self._ckpt = ckpt.CheckpointManager(
                self._ckpt_dir, generation_fn=_runner.restarts)
        # copy=False: _saved is replaced (never mutated) by each save(),
        # so the writer can serialize it in place — no redundant slab copy
        self._ckpt.commit(payload[0], payload[1], generation=generation,
                          copy=False)

    def checkpoint_wait(self) -> None:
        """Block until every handed-off checkpoint commit finished (or
        was abandoned) — end-of-training / test drains."""
        if self._ckpt is not None:
            self._ckpt.wait()

    def restore(self) -> None:
        self.restore_snapshot()
        flight_recorder.emit("state_restore",
                             step=int(getattr(self, "step", 0)))

    def register_reset_callbacks(self, callbacks) -> None:
        """Callables invoked after a re-form (reference:
        horovod/common/elastic.py register_reset_callbacks) — rebuild
        anything derived from world size (lr schedules, data shards)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self.restore()
        for cb in self._reset_callbacks:
            cb()

    # -- async spill -------------------------------------------------------
    def _spill(self, tree, step: int) -> None:
        from horovod_tpu import checkpoint

        if self._spill_sync:
            checkpoint.save(self._spill_dir, tree, step=step)
            return
        with self._spill_lock:
            # latest-wins: a slow disk must not queue unbounded snapshots
            self._spill_next = (tree, step)
            if self._spill_thread is None or not self._spill_thread.is_alive():
                self._spill_thread = threading.Thread(
                    target=self._spill_loop, daemon=True,
                    name="hvd-elastic-spill")
                self._spill_thread.start()

    def _spill_loop(self) -> None:
        from horovod_tpu import checkpoint

        while True:
            with self._spill_lock:
                item, self._spill_next = self._spill_next, None
                if item is None:
                    return
            try:
                checkpoint.save(self._spill_dir, item[0], step=item[1])
            except Exception as exc:
                log.warning("elastic spill to %s failed: %s",
                            self._spill_dir, exc)


class ObjectState(State):
    """Picklable-attribute state (reference: horovod/common/elastic.py
    ObjectState): every keyword becomes an attribute; commit snapshots
    them by value; sync ships rank 0's copies over the wire."""

    _INTERNAL = ("_spill_dir", "_spill_sync", "_spill_lock", "_spill_next",
                 "_spill_thread", "_reset_callbacks", "_saved",
                 "_ckpt_dir", "_ckpt")

    def __init__(self, spill_dir: Optional[str] = None, **kwargs):
        super().__init__(spill_dir=spill_dir)
        self._saved: Dict[str, bytes] = {}  # guarded-by: <owner-thread>
        for key, value in kwargs.items():
            setattr(self, key, value)
        self.save()

    def _public_attrs(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()
                if k not in self._INTERNAL}

    def save(self) -> None:
        # pickle round-trip = by-value snapshot of arbitrary objects
        self._saved = {k: pickle.dumps(v)
                       for k, v in self._public_attrs().items()}

    def restore_snapshot(self) -> None:
        for key, blob in self._saved.items():
            setattr(self, key, pickle.loads(blob))

    def sync(self, root_rank: int = 0) -> None:
        synced = broadcast_object_wire(self._public_attrs(), root_rank)
        for key, value in synced.items():
            setattr(self, key, value)
        self.save()


class ArrayState(State):
    """Array-pytree state for JAX training loops (the analogue of the
    reference's framework-specific ``TorchState``): holds ``params``,
    ``optimizer`` (opt_state) and the ``step`` counter, plus any extra
    array pytrees passed as keywords. The initial values are snapshot at
    construction, so a failure before the first ``commit()`` restores the
    starting point."""

    def __init__(self, params=None, optimizer=None, step: int = 0,
                 spill_dir: Optional[str] = None,
                 ckpt_dir: Optional[str] = None, **trees):
        super().__init__(spill_dir=spill_dir, ckpt_dir=ckpt_dir)
        self.params = params
        self.optimizer = optimizer
        self.step = int(step)
        self._tree_names = ["params", "optimizer"] + sorted(trees)
        for name, tree in trees.items():
            setattr(self, name, tree)
        self._saved: Dict[str, Any] = {}  # guarded-by: <owner-thread>
        self.save()

    def _leaf_key_base(self, name: str) -> int:
        """First global leaf index of tree ``name`` under the checkpoint
        subsystem's key scheme (``ckpt.writer.build_rank_payload``
        flattens the trees in sorted-name order)."""
        import jax

        from horovod_tpu.parallel import zero

        index = 0
        for n in sorted(self._tree_names):
            if n == name:
                return index
            tree = getattr(self, n, None)
            if tree is None:
                continue
            flat, _ = jax.tree_util.tree_flatten(
                tree, is_leaf=zero.is_sharded_state)
            index += len(flat)
        raise KeyError(name)

    def _exchange_replicas(self, step: int) -> None:
        from horovod_tpu.ckpt import replica
        from horovod_tpu.ckpt import writer as ckpt_writer

        if not replica.enabled():
            return
        st = basics._ensure_init()
        _items, _layout, exchange = ckpt_writer.build_rank_payload(
            {name: getattr(self, name) for name in self._tree_names},
            st.rank, st.size)
        # the exchange is a COLLECTIVE: every rank joins even with an
        # empty entry dict (small worlds can leave a rank owning no
        # replicated slice), or the owning ranks would deadlock
        replica.exchange(exchange, step)

    def save(self) -> None:
        self._saved = {name: _host_copy(getattr(self, name))
                       for name in self._tree_names}
        self._saved["step"] = int(self.step)

    def restore_snapshot(self) -> None:
        for name in self._tree_names:
            setattr(self, name, _host_copy(self._saved[name]))
        self.step = int(self._saved["step"])

    def sync(self, root_rank: int = 0) -> None:
        """Re-broadcast from ``root_rank`` (after a re-form: the lowest
        surviving rank, renumbered 0 — see runner._reform).

        ZeRO-sharded leaves (``zero.is_sharded_state``: stage-1 optimizer
        states, stage-2 ``ShardedGrads``, stage-3 ``ShardedParams``) are
        NOT broadcast — rank 0's shard would clobber every other rank's
        distinct shard; they re-shard collectively via ``zero.resync``
        against the just-synced params (``_tree_names`` orders params
        first, so the fp32-master refill sees synced values; a stage-3
        params tree re-shards first and later states gather from it)."""
        import jax

        from horovod_tpu.ckpt import replica
        from horovod_tpu.ops import collectives
        from horovod_tpu.parallel import dp, zero

        st = basics._ensure_init()
        for name in self._tree_names:
            tree = getattr(self, name)
            if tree is None:
                continue
            flat, treedef = jax.tree_util.tree_flatten(
                tree, is_leaf=zero.is_sharded_state)
            if any(zero.is_sharded_state(x) for x in flat):
                base = self._leaf_key_base(name)
                flat = [zero.resync(x, self.params, root_rank,
                                    replica=replica.lookup(
                                        f"{name}/{base + i}",
                                        step=int(self.step)))
                        if zero.is_sharded_state(x)
                        else dp.broadcast_parameters(x, root_rank=root_rank)
                        for i, x in enumerate(flat)]
                setattr(self, name,
                        jax.tree_util.tree_unflatten(treedef, flat))
            else:
                setattr(self, name,
                        dp.broadcast_parameters(tree, root_rank=root_rank))
        if st.size > 1:
            self.step = int(np.asarray(collectives.broadcast(
                np.array([self.step], np.int64), root_rank))[0])
        self.save()

    def _spill_payload(self):
        return ({name: self._saved[name] for name in self._tree_names},
                int(self._saved.get("step", 0)))

    def load_latest(self, directory: Optional[str] = None) -> Optional[int]:
        """Restore the newest consistent checkpoint cut from
        ``directory`` (default: this state's ``HOROVOD_CKPT_DIR``) into
        this state — sharded leaves re-scatter into the CURRENT world
        size via the manifest's recorded layout. Returns the restored
        step, or None when the directory holds no checkpoint."""
        from horovod_tpu import ckpt

        directory = directory or self._ckpt_dir
        if not directory:
            return None
        trees, step = ckpt.restore_latest(
            directory,
            {name: getattr(self, name) for name in self._tree_names})
        if step is None:
            return None
        for name, tree in trees.items():
            setattr(self, name, tree)
        self.step = int(step)
        self.save()
        flight_recorder.emit("ckpt_state_loaded", step=int(step),
                             directory=directory)
        return int(step)
