"""Typed failure hierarchy for the elastic subsystem.

The reference's elastic mode recovers by catching a dedicated exception
family raised out of the collective layer (reference:
horovod/common/exceptions.py — ``HorovodInternalError`` /
``HostsUpdatedInterrupt``) instead of letting a peer death abort the
process. Everything here subclasses :class:`RuntimeError` so existing
callers that catch ``RuntimeError`` around ``hvd.synchronize`` keep
working; elastic-aware callers (``@hvd.elastic.run``) catch the narrower
:class:`WorkersDownError` and re-form the job.
"""

from __future__ import annotations

from typing import Optional, Sequence


class HorovodInternalError(RuntimeError):
    """Internal framework failure surfaced to a caller thread (reference:
    horovod/common/exceptions.py HorovodInternalError)."""


class WorkersDownError(HorovodInternalError):
    """One or more workers left the job (died, hung past the stall
    shutdown threshold, or closed their transport). Recoverable under
    ``@hvd.elastic.run``: survivors re-form membership and resume from the
    last committed state."""

    def __init__(self, message: str,
                 ranks: Optional[Sequence[int]] = None) -> None:
        super().__init__(message)
        #: ranks believed down, when the failure path could tell; else ()
        self.ranks = tuple(ranks or ())


class WorkerLostError(WorkersDownError):
    """A peer's transport died mid-collective (connection reset, short
    read, coordinator unreachable)."""


class WorkerStallError(WorkersDownError):
    """The stall inspector crossed HOROVOD_STALL_SHUTDOWN_TIME_SECONDS:
    some ranks stopped submitting tensors — treated as down so the
    elastic layer can evict them and continue."""


class CheckpointCorruptError(HorovodInternalError):
    """A checkpoint failed its integrity check on restore: a truncated
    shard, a CRC mismatch on a leaf, or an unparseable container. Carries
    the offending file and (when the damage is attributable) the leaf
    path, so the operator knows whether to distrust one tensor or the
    whole file. Raised instead of whatever decoding error the serializer
    would have thrown — restore callers get one typed failure mode for
    every flavor of torn write or bit rot."""

    def __init__(self, message: str, path: Optional[str] = None,
                 leaf: Optional[str] = None) -> None:
        super().__init__(message)
        #: filesystem path of the damaged checkpoint file
        self.path = path
        #: pytree leaf key whose bytes failed verification, when known
        self.leaf = leaf


class NumericalError(HorovodInternalError):
    """A payload or training statistic went numerically bad: non-finite
    values entered a collective, or the step guard's spike budget was
    exhausted. Deliberately NOT a :class:`WorkersDownError` — no worker
    is down; the elastic runner handles it by rolling back to the last
    committed state and replaying instead of re-forming membership."""

    def __init__(self, message: str, bucket: Optional[str] = None,
                 tensor: Optional[str] = None,
                 suspect_rank: Optional[int] = None) -> None:
        super().__init__(message)
        #: fusion bucket / lane the bad payload traveled in, when known
        self.bucket = bucket
        #: tensor (or group member) name carrying non-finite values
        self.tensor = tensor
        #: rank whose local payload was non-finite, when attributable
        self.suspect_rank = suspect_rank


class CollectiveIntegrityError(NumericalError):
    """Cross-rank digest disagreement on a collective's *result*: the
    replicated output differs between ranks, i.e. silent data corruption
    (a flipped bit, a divergent reduction) somewhere in the data plane.
    Carries the digest vote's minority rank as ``suspect_rank`` so the
    rollback path can optionally quarantine it."""


class HostsUpdatedInterrupt(Exception):
    """The elastic driver announced a host-set change (reference:
    horovod/common/exceptions.py HostsUpdatedInterrupt). Not an error:
    deliberately OUTSIDE the RuntimeError family so generic error
    handlers never swallow it; the elastic runner catches it at the next
    commit boundary and re-forms membership to fold new hosts in."""

    def __init__(self, message: str = "host set updated") -> None:
        super().__init__(message)
