"""Flight recorder: postmortem-grade crash-time evidence for the runtime.

Live metrics (metrics.py) and the Chrome timeline (timeline.py) answer
"how is the job doing *now*"; nothing answered "what was the runtime
doing in the seconds before it died". The flight recorder is an
always-on, bounded, lock-cheap ring buffer of structured events emitted
from every plane — controller negotiation (begin/end, per-rank request
arrival, STALE_HIT invalidations), executor dispatch/complete/fail with
bucket + bytes, pipeline-depth changes, elastic membership generations,
commit/restore, worker loss — and a dumper that writes the last N events
plus a full metrics snapshot and the in-flight pending-op state as JSON
whenever the process is about to become unreadable: fatal signals, stall
shutdown, ``WorkerLostError``/``WorkerStallError``, a background-cycle
abort, injected faults, and on demand (``hvd.dump_debug_state()`` or
``GET /debug`` on the metrics server).

The hot path mirrors the metrics registry's philosophy: ``emit`` is one
``deque.append`` on a ``maxlen``-bounded deque — atomic under the GIL,
no lock, old events overwritten in O(1) — so instrumentation never
contends with the cycle it records. Dump-side work (snapshotting,
file IO, shipping to the rendezvous server) happens only on failure or
explicit request.

Dumps are additionally *shipped* to the launcher's rendezvous KV server
(scope ``flight``) when one is configured, so ``tpurun`` can print a
merged cross-rank postmortem even for workers whose filesystem died with
them. Event timestamps are ``time.time()`` epoch seconds; at dump time
each rank estimates its clock offset against the rendezvous server's
``/_time`` endpoint so the merged postmortem can interleave events from
different hosts on one axis.

Knobs: ``HOROVOD_FLIGHT_RECORDER`` (default on; ``0`` disables; an
integer > 1 sets the ring capacity, default 2048),
``HOROVOD_FLIGHT_RECORDER_DIR`` (directory for ``flight-rank-N.json``
dumps; unset = no files, shipping + ``/debug`` still work).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from horovod_tpu.analysis import witness
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import (DEFAULT_FLIGHT_RECORDER_CAPACITY,
                                   HOROVOD_FLIGHT_RECORDER,
                                   HOROVOD_FLIGHT_RECORDER_DIR,
                                   parse_flight_recorder)

SCHEMA = "horovod-flight-recorder-v1"
# rendezvous KV scope where workers ship their dumps for the launcher
RENDEZVOUS_SCOPE = "flight"
DUMP_PREFIX = "flight-rank-"

_EVENTS_TOTAL = _metrics().counter(
    "horovod_flight_recorder_events_total",
    "Structured events recorded into the flight-recorder ring buffer.")
_DUMPS_TOTAL = _metrics().counter(
    "horovod_flight_recorder_dumps_total",
    "Flight-recorder snapshots produced (file dumps, shipped dumps, "
    "/debug requests and hvd.dump_debug_state calls).")


def _rendezvous_addr() -> Optional[Tuple[str, int]]:
    addr = os.environ.get("HOROVOD_RENDEZVOUS_HTTP_ADDR")
    port = os.environ.get("HOROVOD_RENDEZVOUS_HTTP_PORT")
    if not addr or not port:
        return None
    try:
        return addr, int(port)
    except ValueError:
        return None


def _estimate_clock_offset() -> Optional[float]:
    """Offset such that ``local_time + offset == launcher_time``, from the
    rendezvous server's ``/_time`` endpoint (NTP-style: the sample with
    the smallest round trip wins, server time compared to the midpoint).
    None when no rendezvous server is configured or reachable."""
    dest = _rendezvous_addr()
    if dest is None:
        return None
    from urllib.request import urlopen

    best_rtt, best_offset = None, None
    for _ in range(3):
        try:
            t0 = time.time()
            with urlopen("http://%s:%d/_time" % dest, timeout=2) as resp:
                server = float(resp.read())
            t1 = time.time()
        except (OSError, ValueError):
            return best_offset
        rtt = t1 - t0
        if best_rtt is None or rtt < best_rtt:
            best_rtt, best_offset = rtt, server - (t0 + t1) / 2.0
    return best_offset


class FlightRecorder:
    """Bounded ring of structured events + the dump machinery.

    ``emit`` must stay cheap enough for the cycle hot path: build one
    small dict, append to a maxlen deque. Everything else — state
    providers, metrics snapshot, clock-offset estimation, file writes,
    shipping — runs only at dump time.
    """

    def __init__(self) -> None:
        enabled, capacity = parse_flight_recorder(
            os.environ.get(HOROVOD_FLIGHT_RECORDER))
        self.enabled = enabled
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        # the rank this process was LAUNCHED as — stable across elastic
        # re-forms (renumbering), so per-process dump files never collide
        self.launch_rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        self.rank = self.launch_rank
        self.dir = os.environ.get(HOROVOD_FLIGHT_RECORDER_DIR, "")
        self._providers: Dict[str, Callable[[], Any]] = {}
        self._dump_history: List[dict] = []  # guarded-by: _dump_lock
        self._clock_offset: Optional[float] = None
        self._offset_checked = False
        self._dump_lock = witness.make_lock("FlightRecorder._dump_lock")
        self._last_failure_dump = 0.0

    # -- hot path -----------------------------------------------------------
    def emit(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        ev = {"t": time.time(), "kind": kind}
        ev.update(fields)
        self._events.append(ev)  # GIL-atomic; maxlen evicts the oldest
        _EVENTS_TOTAL.inc()

    def events(self) -> List[dict]:
        return list(self._events)

    # -- configuration ------------------------------------------------------
    def configure(self, rank: Optional[int] = None) -> None:
        """Re-read the env knobs (called from ``hvd.init()`` — including
        elastic re-init, where the rank may have changed)."""
        enabled, capacity = parse_flight_recorder(
            os.environ.get(HOROVOD_FLIGHT_RECORDER))
        self.enabled = enabled
        if capacity != self.capacity:
            self._events = deque(self._events, maxlen=capacity)
            self.capacity = capacity
        self.dir = os.environ.get(HOROVOD_FLIGHT_RECORDER_DIR, "")
        if rank is not None:
            self.rank = rank

    def set_state_provider(self, name: str,
                           fn: Optional[Callable[[], Any]]) -> None:
        """Register a callable whose return value is embedded under
        ``state[name]`` in every dump. Re-registering replaces (so a
        re-initialized runtime simply supersedes the dead one); ``None``
        unregisters."""
        if fn is None:
            self._providers.pop(name, None)
        else:
            self._providers[name] = fn

    # -- dump side ----------------------------------------------------------
    def clock_offset(self) -> Optional[float]:
        if not self._offset_checked:
            self._offset_checked = True
            try:
                self._clock_offset = _estimate_clock_offset()
            except Exception:
                self._clock_offset = None
        return self._clock_offset

    def snapshot(self, reason: str) -> dict:
        """The full postmortem document: ring contents, provider state,
        metrics, identity, and enough clock metadata to merge dumps
        across hosts."""
        state = {}
        for name, fn in list(self._providers.items()):
            try:
                state[name] = fn()
            except Exception as exc:  # a dying runtime must not block dumps
                state[name] = "<state provider failed: %s>" % (exc,)
        _DUMPS_TOTAL.inc()
        return {
            "schema": SCHEMA,
            "rank": self.rank,
            "launch_rank": self.launch_rank,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "reason": reason,
            "wall_time": time.time(),
            "clock_offset_seconds": self.clock_offset(),
            "dump_history": list(self._dump_history),
            "events": self.events(),
            "state": state,
            "metrics": _metrics().snapshot(),
        }

    def _dump_path(self, target: str) -> str:
        if "{rank}" in target:
            return target.replace("{rank}", str(self.launch_rank))
        if target.endswith(".json"):
            return target
        return os.path.join(target,
                            "%s%d.json" % (DUMP_PREFIX, self.launch_rank))

    def dump(self, reason: str, path: Optional[str] = None,
             ship: bool = True) -> dict:
        """Snapshot and persist: write ``flight-rank-N.json`` (last dump
        wins; earlier reasons survive in ``dump_history``) and ship the
        JSON to the launcher's rendezvous store when one is configured.
        Never raises — this runs on paths that are already failing."""
        # Build the snapshot before taking the lock: the first snapshot
        # estimates the clock offset over HTTP, and concurrent dumpers
        # (signal handler, stall shutdown, dying cycle thread) must not
        # queue behind that round-trip.
        snap = self.snapshot(reason)
        payload = None
        target = path or self.dir
        with self._dump_lock:
            # history carries the EARLIER dumps only — the current
            # reason is already in snap["reason"], and including it
            # here would make every dump read as its own predecessor
            snap["dump_history"] = list(self._dump_history)
            self._dump_history.append(
                {"reason": reason, "t": snap["wall_time"]})
            if target:
                # File write stays serialized so concurrent dumps are
                # last-wins whole files, never interleaved.
                try:
                    out = self._dump_path(target)
                    parent = os.path.dirname(out)
                    if parent:
                        os.makedirs(parent, exist_ok=True)
                    payload = json.dumps(snap)
                    with open(out, "w") as f:
                        f.write(payload)
                    log.debug("flight recorder: wrote %s (%s)", out, reason)
                except (OSError, TypeError, ValueError) as exc:
                    log.warning("flight recorder: dump to %r failed: %s",
                                target, exc)
        # Shipping is a rendezvous HTTP round-trip — never under the lock.
        if ship:
            try:
                self._ship(payload if payload is not None
                           else json.dumps(snap))
            except Exception as exc:
                log.debug("flight recorder: ship failed: %s", exc)
        return snap

    def _ship(self, payload: str) -> None:
        dest = _rendezvous_addr()
        if dest is None:
            return
        from horovod_tpu.run.rendezvous import KVStoreClient
        from horovod_tpu.utils import resilience

        # a dump usually ships while the job is already unhealthy — retry
        # briefly (a hiccup must not lose the postmortem), but bound the
        # whole attempt so shipping never delays process teardown long
        client = KVStoreClient(
            dest[0], dest[1], scope=RENDEZVOUS_SCOPE, timeout=5.0,
            retry=resilience.RetryPolicy.from_env("flight", deadline=5.0))
        client.set("rank.%d" % self.launch_rank, payload)


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    return _recorder


def emit(kind: str, **fields) -> None:
    """Record one structured event (module-level hot-path entry point)."""
    _recorder.emit(kind, **fields)


def set_state_provider(name: str, fn: Optional[Callable[[], Any]]) -> None:
    _recorder.set_state_provider(name, fn)


def configure(rank: Optional[int] = None) -> None:
    _recorder.configure(rank=rank)


def debug_state() -> dict:
    """Snapshot for the metrics server's ``/debug`` endpoint."""
    return _recorder.snapshot("debug_endpoint")


def dump_debug_state(path: Optional[str] = None,
                     reason: str = "on_demand") -> dict:
    """Public API (``hvd.dump_debug_state()``): return the full debug
    snapshot, and persist it when ``path`` or
    ``HOROVOD_FLIGHT_RECORDER_DIR`` names a destination."""
    if path or _recorder.dir:
        return _recorder.dump(reason, path=path)
    return _recorder.snapshot(reason)


def dump_on_failure(reason: str) -> None:
    """Best-effort dump from failure paths (cycle abort, stall shutdown,
    worker loss, fatal signal). Rate-limited so a failure loop can't turn
    into an IO storm; never raises."""
    try:
        if not _recorder.enabled:
            return
        now = time.monotonic()
        if _recorder._last_failure_dump and \
                now - _recorder._last_failure_dump < 1.0:
            return
        _recorder._last_failure_dump = now
        _recorder.dump(reason)
    except Exception as exc:
        try:
            log.warning("flight recorder: failure dump (%s) failed: %s",
                        reason, exc)
        except Exception:
            pass


# -- fatal-signal hook ------------------------------------------------------
_signals_installed = False
_prev_handlers: Dict[int, Any] = {}


def install_signal_handlers() -> None:
    """Dump on SIGTERM (then chain to the previous disposition) and on
    SIGUSR1 (dump and keep running — `kill -USR1` a live job to inspect
    it). No-op off the main thread or when the recorder is disabled."""
    global _signals_installed
    if _signals_installed or not _recorder.enabled:
        return
    import signal

    def _fatal(signum, frame):
        dump_on_failure("signal:%s" % signal.Signals(signum).name)
        prev = _prev_handlers.get(signum)
        if prev is signal.SIG_IGN:
            return
        if callable(prev):
            prev(signum, frame)
            return
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    def _inspect(signum, frame):
        dump_on_failure("signal:SIGUSR1")

    try:
        _prev_handlers[signal.SIGTERM] = signal.signal(signal.SIGTERM,
                                                       _fatal)
        if hasattr(signal, "SIGUSR1"):
            signal.signal(signal.SIGUSR1, _inspect)
        _signals_installed = True
    except ValueError:
        pass  # not the main thread (embedded init): skip, dumps still
        # fire from the runtime/elastic failure paths


# -- cross-rank postmortem (launcher side) ----------------------------------
def load_dumps(directory: str) -> List[dict]:
    """Read every ``flight-rank-*.json`` in ``directory`` (unreadable or
    truncated files are skipped with a warning, not fatal — a crash may
    have cut one short)."""
    dumps = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return dumps
    for name in names:
        if not (name.startswith(DUMP_PREFIX) and name.endswith(".json")):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                dumps.append(json.load(f))
        except (OSError, ValueError) as exc:
            log.warning("flight recorder: skipping unreadable dump %s: %s",
                        path, exc)
    return dumps


def merge_events(dumps: List[dict]) -> List[dict]:
    """Interleave events across ranks on one time axis: each rank's
    events are shifted by its estimated clock offset (when it had one)
    so cross-host ordering is meaningful to ~RTT precision."""
    merged = []
    for d in dumps:
        offset = d.get("clock_offset_seconds") or 0.0
        rank = d.get("launch_rank", d.get("rank", "?"))
        for ev in d.get("events", ()):
            e = dict(ev)
            e["rank"] = rank
            e["t_merged"] = float(ev.get("t", 0.0)) + offset
            merged.append(e)
    merged.sort(key=lambda e: e["t_merged"])
    return merged


def suspect_culprit(dumps: List[dict]) -> Optional[Tuple[Any, str]]:
    """Best-effort culprit attribution: explicit evidence first (a rank
    that recorded its own injected kill; ranks named by workers_down /
    stall_shutdown events), then the straggler lag EWMA from any
    coordinator dump."""
    for d in dumps:
        for ev in d.get("events", ()):
            if ev.get("kind") == "fault_inject" and ev.get("action") == \
                    "kill":
                return ev.get("rank"), "recorded its own injected kill"
    # integrity plane (integrity/): a digest vote that convicted a rank
    # is direct evidence — stronger than any absence/straggler heuristic
    for d in dumps:
        for ev in d.get("events", ()):
            if ev.get("kind") in ("integrity_violation", "rollback") \
                    and ev.get("suspect") is not None:
                return ev.get("suspect"), (
                    "voted out by collective digest disagreement")
    named: Dict[Any, int] = {}
    for d in dumps:
        for ev in d.get("events", ()):
            if ev.get("kind") in ("workers_down", "stall_shutdown",
                                  "collective_timeout"):
                for r in (ev.get("ranks") or ev.get("missing") or ()):
                    named[r] = named.get(r, 0) + 1
    if named:
        rank = max(named, key=lambda r: named[r])
        return rank, ("named missing/lost by %d workers_down/stall event(s)"
                      % named[rank])
    # A partitioned rank never ships its own dump and a transport error
    # names no peer — but the survivors' re-form does: whoever was in the
    # old generation and absent from the new membership is the suspect.
    for d in dumps:
        for ev in d.get("events", ()):
            if ev.get("kind") != "elastic_reform":
                continue
            members = ev.get("members")
            old_size = ev.get("old_size")
            if members is None or old_size is None:
                continue
            missing = sorted(set(range(int(old_size))) - set(members))
            if missing:
                return missing[0], (
                    "absent from the generation-%s re-form (%d of %d old "
                    "ranks rejoined)" % (ev.get("generation", "?"),
                                         len(members), int(old_size)))
    best = None
    for d in dumps:
        lag = d.get("metrics", {}).get("horovod_straggler_lag_seconds")
        for row in (lag or {}).get("values", ()):
            value = row.get("value", 0.0)
            if best is None or value > best[1]:
                best = (row.get("labels", {}).get("rank"), value)
    # same-cycle arrival jitter is microseconds; a real straggler lags by
    # whole cycles — below that, naming a rank would be noise-as-blame
    if best is not None and best[1] >= 0.05:
        return best[0], ("highest straggler lag EWMA (%.3fs)" % best[1])
    return None


def load_restart_lineage(directory: str) -> Optional[dict]:
    """The supervised-restart lineage ``tpurun --supervise`` records
    next to the flight dumps (``restart-lineage.json``), or None."""
    path = os.path.join(directory, "restart-lineage.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def format_postmortem(dumps: List[dict], last_n: int = 40,
                      lineage: Optional[dict] = None) -> str:
    """Human-readable merged postmortem: per-rank dump inventory, the
    last ``last_n`` interleaved events, and the suspected culprit.
    ``lineage`` (from :func:`load_restart_lineage`) prefixes the
    supervised-restart history so a dump can be placed in its attempt."""
    lines = ["=== flight-recorder postmortem (%d dump%s) ==="
             % (len(dumps), "" if len(dumps) == 1 else "s")]
    for att in (lineage or {}).get("attempts", ()):
        dur = float(att.get("ended", 0)) - float(att.get("started", 0))
        lines.append(
            "restart attempt %s/%s: exit=%s duration=%.1fs" % (
                att.get("attempt", "?"),
                att.get("restart_budget", "?"),
                att.get("exit_code", "?"), max(dur, 0.0)))
    for d in sorted(dumps, key=lambda d: d.get("launch_rank", 0)):
        offset = d.get("clock_offset_seconds")
        lines.append(
            "rank %s: reason=%s host=%s pid=%s events=%d%s" % (
                d.get("launch_rank", d.get("rank", "?")),
                d.get("reason", "?"), d.get("host", "?"), d.get("pid", "?"),
                len(d.get("events", ())),
                (" clock_offset=%+.4fs" % offset) if offset is not None
                else ""))
    merged = merge_events(dumps)
    tail = merged[-last_n:]
    if len(merged) > len(tail):
        lines.append("... %d earlier events omitted ..."
                     % (len(merged) - len(tail)))
    for ev in tail:
        t = ev["t_merged"]
        stamp = time.strftime("%H:%M:%S", time.localtime(t)) + \
            (".%03d" % int((t % 1) * 1000))
        extras = " ".join(
            "%s=%s" % (k, v) for k, v in ev.items()
            if k not in ("t", "t_merged", "kind", "rank"))
        lines.append("%s [rank %s] %s%s"
                     % (stamp, ev["rank"], ev["kind"],
                        (" " + extras) if extras else ""))
    culprit = suspect_culprit(dumps)
    if culprit is not None:
        lines.append("suspected culprit: rank %s (%s)" % culprit)
    else:
        lines.append("suspected culprit: none identified")
    try:
        # cross-rank memory report from the dumps' "memory" state (PR 13;
        # empty for pre-memory-plane dumps). Lazy: memory.py imports this
        # module.
        from horovod_tpu import memory

        report = memory.format_memory_report(dumps)
        if report:
            lines.append("")
            lines.append(report)
    except Exception:
        pass  # the postmortem renders even if the memory plane is broken
    try:
        # cross-rank SLO report from the dumps' "slo" state (tracing.py;
        # empty for pre-tracing dumps): burn rates, budgets, and the
        # slowest-request exemplars with their victim trace ids. Lazy:
        # tracing.py imports this module.
        from horovod_tpu import tracing

        report = tracing.format_slo_report(dumps)
        if report:
            lines.append("")
            lines.append(report)
    except Exception:
        pass  # likewise if the tracing plane is broken
    try:
        # cross-rank comms report from the dumps' "comms" state (comms.py;
        # empty for pre-comms dumps): per-lane busbw vs roofline, the
        # slowest lane, and the rank furthest below its roofline. Lazy:
        # comms.py imports this module.
        from horovod_tpu import comms

        report = comms.format_comms_report(dumps)
        if report:
            lines.append("")
            lines.append(report)
    except Exception:
        pass  # likewise if the comms plane is broken
    try:
        # cross-rank goodput report from the dumps' "goodput" state
        # (goodput.py; empty for pre-goodput dumps): fleet goodput %,
        # the dominant badput category, and the costliest incident with
        # its culprit rank. Lazy: goodput.py imports this module.
        from horovod_tpu import goodput

        report = goodput.format_goodput_report(dumps)
        if report:
            lines.append("")
            lines.append(report)
    except Exception:
        pass  # likewise if the goodput plane is broken
    return "\n".join(lines)
