"""Goodput ledger: fleet-wide productive-time accounting, badput
attribution, and incident forensics.

Every earlier observability plane answers a local question — the
profiler "where did this step's time go", the memory ledger "where did
the bytes sit", the comms observatory "how fast is the wire". This
module answers the one that dominates fleet economics (MegaScale-style
goodput accounting, OPT-175B-style incident logbooks): **what fraction
of wall-clock since ``hvd.init()`` was productive, and which disruption
ate the rest?**

One process-wide :class:`GoodputTracker` partitions each rank's
wall-clock into ``productive`` time (committed optimizer steps fed from
the profiler's step phases and from ``elastic.State.commit``; served
decode blocks on the serve plane) and the badput categories in
:data:`BADPUT_CATEGORIES`, fed by hooks at the existing instrumentation
points:

* ``startup_compile`` — derived: the gap between ``hvd.init()``
  returning and the first attributed work (warmup + first-step
  compilation);
* ``ckpt_stall`` — inline training-thread seconds inside
  ``CheckpointWriter.commit`` (ckpt/writer.py);
* ``rollback`` — restore time AND replayed steps after an integrity
  rollback (integrity/rollback.py), replay attributed to the incident
  that caused it;
* ``elastic_reform`` — quiesce + re-form + re-sync bracket around
  ``_reform`` in the ``@elastic.run`` wrapper (elastic/runner.py);
* ``collective_stall`` — retry-backoff sleeps in the transport retry
  policy (utils/resilience.py);
* ``straggler_wait`` / ``exposed_comm`` — stall-watch waits and the
  profiler's exposed-communication phase;
* ``serve_queue_idle`` / ``serve_preempted`` — empty serve-loop
  iterations and preempted decode work (serve/replica.py), preemption
  re-attributed from productive using an EWMA per-token decode cost;
* ``input_idle`` — the unattributed remainder, so the categories sum
  to wall-clock **exactly** (over-attribution is scaled down
  proportionally, the profiler phase idiom).

Each disruption becomes a first-class **incident record** — cause,
generation, duration, steps lost/replayed, culprit rank when the
straggler/suspect attribution names one, linked flight-event kinds — in
a bounded ledger (``HOROVOD_GOODPUT_INCIDENTS`` records). A disruption
that replays N steps arms a countdown: the next N step records are
badput charged to that incident's cause, not productive time.

Surfaces (mirroring the established planes end-to-end):
``horovod_goodput_*`` metric families + ``GET /goodput`` (metrics.py); a
``goodput`` flight-recorder state provider in every dump; a per-rank
"goodput fraction" counter track and an incident instant lane in the
merged Perfetto trace (profiler.merge_profile_dir); a goodput/incident
panel in tools/hvd_top.py; :func:`format_goodput_report` — the
cross-rank postmortem section naming fleet goodput %, the dominant
badput category, and the costliest incident (``tpurun --postmortem``);
and a ``goodput_fraction`` headline in bench.py rows gated
higher-is-better by bench_compare.py.

Env knobs (registered in utils/env.py, table in docs/goodput.md):
``HOROVOD_GOODPUT`` (accounting on/off, default on),
``HOROVOD_GOODPUT_INCIDENTS`` (incident ledger capacity, default 64),
``HOROVOD_GOODPUT_REPORT_SECONDS`` (periodic log report, default 0 =
off).
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Dict, List, Optional

from horovod_tpu.analysis import witness
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils.env import _get_bool, _get_float, _get_int

log = logging.getLogger("horovod_tpu")

HOROVOD_GOODPUT = "HOROVOD_GOODPUT"
HOROVOD_GOODPUT_INCIDENTS = "HOROVOD_GOODPUT_INCIDENTS"
HOROVOD_GOODPUT_REPORT_SECONDS = "HOROVOD_GOODPUT_REPORT_SECONDS"

DEFAULT_INCIDENT_CAPACITY = 64
DEFAULT_REPORT_SECONDS = 0.0
_SAMPLE_RING = 512  # bounded fraction trail for the trace counter track

PRODUCTIVE = "productive"
BADPUT_CATEGORIES = (
    "startup_compile",
    "ckpt_stall",
    "rollback",
    "elastic_reform",
    "collective_stall",
    "straggler_wait",
    "exposed_comm",
    "input_idle",
    "serve_queue_idle",
    "serve_preempted",
)
CATEGORIES = (PRODUCTIVE,) + BADPUT_CATEGORIES

_FRACTION = _metrics().gauge(
    "horovod_goodput_fraction",
    "Productive fraction of wall-clock since hvd.init() on this rank "
    "(committed step + served decode time / total).")
_SECONDS = _metrics().counter(
    "horovod_goodput_seconds_total",
    "Wall-clock seconds attributed per goodput category on this rank.",
    labelnames=("category",))
_STEPS = _metrics().counter(
    "horovod_goodput_steps_total",
    "Optimizer steps accounted by kind: productive (committed once) or "
    "replayed (re-run after a rollback/re-form, charged as badput).",
    labelnames=("kind",))
_INCIDENTS = _metrics().counter(
    "horovod_goodput_incidents_total",
    "Disruption incidents recorded in the goodput ledger, per cause.",
    labelnames=("cause",))


class GoodputTracker:
    """Process-wide productive-time ledger.

    Hot-path cost per record is one short lock: a few float adds and a
    deque append; metric updates and flight events happen AFTER the
    tracker lock is released (lock hygiene: emit paths take the
    recorder's own lock). The epoch is pinned at the FIRST
    ``configure()`` (the first ``hvd.init()``) and survives elastic
    ``reinit()`` — re-form downtime must land in the same ledger it
    disrupted."""

    def __init__(self) -> None:
        self._lock = witness.make_lock("GoodputTracker._lock")
        self._epoch: Optional[float] = None       # guarded-by: _lock
        self._epoch_wall: Optional[float] = None  # guarded-by: _lock
        self._cat: Dict[str, float] = {}          # guarded-by: _lock
        # monotonic start of the first attributed work (startup boundary)
        self._first_mark: Optional[float] = None  # guarded-by: _lock
        # monotonic frontier of step attribution (double-count guard
        # between the profiler and State.commit step sources)
        self._step_mark: Optional[float] = None   # guarded-by: _lock
        # non-step seconds attributed since _step_mark: a commit-style
        # step claims its inter-commit gap MINUS these, so a re-form or
        # ckpt stall inside the gap is not double-counted as productive
        self._other_since_step = 0.0              # guarded-by: _lock
        self._steps_productive = 0                # guarded-by: _lock
        self._steps_replayed = 0                  # guarded-by: _lock
        self._serve_blocks = 0                    # guarded-by: _lock
        self._serve_token_cost: Optional[float] = None  # guarded-by: _lock
        self._replay_remaining = 0                # guarded-by: _lock
        self._replay_incident: Optional[dict] = None  # guarded-by: _lock
        self._incidents: deque = deque(
            maxlen=DEFAULT_INCIDENT_CAPACITY)     # guarded-by: _lock
        self._incident_counts: Dict[str, int] = {}  # guarded-by: _lock
        self._samples: deque = deque(maxlen=_SAMPLE_RING)  # guarded-by: _lock
        self._last_report = 0.0                   # guarded-by: _lock
        self.enabled = True
        self.rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        self.world = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        self.report_seconds = DEFAULT_REPORT_SECONDS

    # -- epoch -------------------------------------------------------------
    def start_epoch(self) -> None:
        """Pin the ledger epoch to now — idempotent, so elastic
        ``reinit()`` keeps the original clock."""
        with self._lock:
            if self._epoch is None:
                self._epoch = time.monotonic()
                self._epoch_wall = time.time()

    def _fraction_locked(self, now: float) -> Optional[float]:
        if self._epoch is None:
            return None
        wall = now - self._epoch
        if wall <= 0:
            return None
        return min(1.0, self._cat.get(PRODUCTIVE, 0.0) / wall)

    def _first_mark_start(self, now: float, seconds: float) -> float:
        """Monotonic start of the first attributed work — callers assign
        the result to ``_first_mark`` while holding ``_lock``."""
        if self._first_mark is not None:
            return self._first_mark
        start = now - max(seconds, 0.0)
        if self._epoch is not None:
            start = max(start, self._epoch)
        return start

    # -- recording ---------------------------------------------------------
    def record_span(self, category: str, seconds: float) -> None:
        """Attribute ``seconds`` of wall-clock to one category. Unknown
        categories are dropped (a stale hook must not corrupt the sum)."""
        if not self.enabled:
            return
        seconds = float(seconds)
        if seconds <= 0 or category not in CATEGORIES:
            return
        now = time.monotonic()
        with self._lock:
            self._first_mark = self._first_mark_start(now, seconds)
            self._cat[category] = self._cat.get(category, 0.0) + seconds
            if category != PRODUCTIVE:
                self._other_since_step += seconds
        _SECONDS.labels(category=category).inc(seconds)

    def record_step(self, seconds: Optional[float] = None,
                    exposed_comm: float = 0.0,
                    step: Optional[int] = None) -> None:
        """Account one optimizer step.

        ``seconds`` is the measured step wall (profiler source); pass
        ``None`` for the commit source (``elastic.State.commit``), which
        claims the whole gap since the last accounted step minus any
        badput spans recorded inside it. Either way the claim is clamped
        to the unattributed gap, so BOTH sources can feed the same
        process without exceeding elapsed time. While a replay countdown
        is armed (see :meth:`note_incident`), the step is charged to the
        arming incident's cause instead of productive time."""
        if not self.enabled:
            return
        now = time.monotonic()
        sample = None
        report = None
        with self._lock:
            ref = self._step_mark
            if ref is None:
                ref = self._epoch if seconds is None else now
            gap = max(0.0, now - ref - self._other_since_step) \
                if ref is not None else 0.0
            if seconds is None:
                claimed = gap
            else:
                claimed = max(0.0, float(seconds))
                if self._step_mark is not None:
                    claimed = min(claimed, gap)
            self._step_mark = now
            self._other_since_step = 0.0
            if claimed <= 0:
                return
            self._first_mark = self._first_mark_start(now, claimed)
            exposed = min(max(float(exposed_comm), 0.0), claimed)
            if self._replay_remaining > 0:
                cause = "rollback"
                if self._replay_incident is not None:
                    cause = self._replay_incident.get("cause", cause)
                    self._replay_incident["steps_replayed"] = \
                        self._replay_incident.get("steps_replayed", 0) + 1
                    self._replay_incident["replayed_seconds"] = round(
                        self._replay_incident.get("replayed_seconds", 0.0)
                        + claimed, 6)
                if cause not in CATEGORIES:
                    cause = "rollback"
                self._replay_remaining -= 1
                if self._replay_remaining <= 0:
                    self._replay_incident = None
                self._steps_replayed += 1
                self._cat[cause] = self._cat.get(cause, 0.0) + claimed
                kind, cat, amount = "replayed", cause, claimed
            else:
                self._steps_productive += 1
                self._cat[PRODUCTIVE] = \
                    self._cat.get(PRODUCTIVE, 0.0) + (claimed - exposed)
                if exposed > 0:
                    self._cat["exposed_comm"] = \
                        self._cat.get("exposed_comm", 0.0) + exposed
                kind, cat, amount = PRODUCTIVE, PRODUCTIVE, claimed - exposed
            frac = self._fraction_locked(now)
            if frac is not None:
                self._samples.append((time.time(), round(frac, 6)))
                sample = frac
            report = self._maybe_report_locked(now)
            if report:
                self._last_report = now
        _STEPS.labels(kind=kind).inc()
        _SECONDS.labels(category=cat).inc(amount)
        if kind == PRODUCTIVE and exposed > 0:
            _SECONDS.labels(category="exposed_comm").inc(exposed)
        if sample is not None:
            _FRACTION.set(round(sample, 6))
        if report:
            log.info("%s", report)

    def record_serve_step(self, seconds: float, tokens: int = 0) -> None:
        """Account one serve-plane decode block as productive time and
        refresh the EWMA per-token decode cost (the exchange rate
        :meth:`note_serve_preempted` uses to price discarded work)."""
        if not self.enabled:
            return
        seconds = float(seconds)
        if seconds <= 0:
            return
        now = time.monotonic()
        sample = None
        with self._lock:
            self._first_mark = self._first_mark_start(now, seconds)
            self._cat[PRODUCTIVE] = \
                self._cat.get(PRODUCTIVE, 0.0) + seconds
            self._serve_blocks += 1
            if tokens and tokens > 0:
                cost = seconds / float(tokens)
                prev = self._serve_token_cost
                self._serve_token_cost = cost if prev is None \
                    else 0.75 * prev + 0.25 * cost
            frac = self._fraction_locked(now)
            if frac is not None:
                self._samples.append((time.time(), round(frac, 6)))
                sample = frac
        _STEPS.labels(kind=PRODUCTIVE).inc()
        _SECONDS.labels(category=PRODUCTIVE).inc(seconds)
        if sample is not None:
            _FRACTION.set(round(sample, 6))

    def note_serve_preempted(self, tokens: int) -> None:
        """Re-attribute a preempted slot's already-decoded tokens from
        productive to ``serve_preempted`` — net zero on the wall-clock
        sum: the seconds were spent, they just bought nothing."""
        if not self.enabled or tokens <= 0:
            return
        with self._lock:
            cost = self._serve_token_cost
            if not cost:
                return
            wasted = min(self._cat.get(PRODUCTIVE, 0.0),
                         float(tokens) * cost)
            if wasted <= 0:
                return
            self._cat[PRODUCTIVE] -= wasted
            self._cat["serve_preempted"] = \
                self._cat.get("serve_preempted", 0.0) + wasted
        _SECONDS.labels(category="serve_preempted").inc(wasted)

    def note_incident(self, cause: str, seconds: float,
                      generation: Optional[int] = None,
                      culprit_rank: Optional[int] = None,
                      replay_steps: int = 0,
                      linked_events: Optional[List[str]] = None,
                      detail: Optional[str] = None) -> None:
        """Record one disruption: its downtime lands in the ``cause``
        category, a record enters the bounded incident ledger, and — when
        the disruption forces ``replay_steps`` steps to be re-run — the
        countdown arms so those steps are charged to this incident."""
        if not self.enabled:
            return
        seconds = max(float(seconds), 0.0)
        cause = cause if cause in BADPUT_CATEGORIES else "rollback"
        now = time.monotonic()
        record = {
            "cause": cause,
            "wall_time": time.time(),
            "duration_s": round(seconds, 6),
            "generation": generation,
            "culprit_rank": culprit_rank,
            "steps_replayed": 0,
            "replayed_seconds": 0.0,
            "linked_events": list(linked_events or ()),
            "detail": detail,
        }
        with self._lock:
            self._first_mark = self._first_mark_start(now, seconds)
            if seconds > 0:
                self._cat[cause] = self._cat.get(cause, 0.0) + seconds
                self._other_since_step += seconds
            self._incidents.append(record)
            self._incident_counts[cause] = \
                self._incident_counts.get(cause, 0) + 1
            if replay_steps > 0:
                self._replay_remaining = int(replay_steps)
                self._replay_incident = record
        _INCIDENTS.labels(cause=cause).inc()
        if seconds > 0:
            _SECONDS.labels(category=cause).inc(seconds)
        from horovod_tpu import flight_recorder

        flight_recorder.emit(
            "goodput_incident", cause=cause, seconds=round(seconds, 4),
            generation=generation, culprit_rank=culprit_rank,
            replay_steps=int(replay_steps))

    def _maybe_report_locked(self, now: float) -> Optional[str]:
        if self.report_seconds <= 0 or self._epoch is None:
            return None
        if now - self._last_report < self.report_seconds:
            return None
        frac = self._fraction_locked(now)
        if frac is None:
            return None
        badput = {c: s for c, s in self._cat.items()
                  if c != PRODUCTIVE and s > 0}
        top = max(badput, key=badput.get) if badput else "none"
        return ("goodput: %.1f%% productive over %.0fs; top badput %s; "
                "%d incident(s)" % (
                    100.0 * frac, now - self._epoch, top,
                    sum(self._incident_counts.values())))

    # -- snapshots ---------------------------------------------------------
    def ledger(self) -> dict:
        """Full accounting snapshot — the payload of the flight-recorder
        ``goodput`` state provider, so every dump carries it. Categories
        sum to wall-clock EXACTLY: derived startup + explicit spans are
        proportionally scaled down if they over-claim (clock skew between
        hook sites), and the remainder lands in ``input_idle``."""
        now = time.monotonic()
        with self._lock:
            wall = max(0.0, now - self._epoch) \
                if self._epoch is not None else 0.0
            cats = {c: s for c, s in self._cat.items() if s > 0}
            startup = 0.0
            if self._epoch is not None:
                if self._first_mark is not None:
                    startup = max(0.0, self._first_mark - self._epoch)
                elif not cats:
                    startup = wall  # nothing attributed yet: all warmup
            if startup > 0:
                cats["startup_compile"] = \
                    cats.get("startup_compile", 0.0) + startup
            attributed = sum(cats.values())
            if attributed > wall > 0:
                scale = wall / attributed
                cats = {c: s * scale for c, s in cats.items()}
                attributed = wall
            idle = max(0.0, wall - attributed)
            if idle > 0:
                cats["input_idle"] = cats.get("input_idle", 0.0) + idle
            productive = cats.get(PRODUCTIVE, 0.0)
            goodput = (productive / wall) if wall > 0 else 0.0
            accounted = ((wall - idle) / wall) if wall > 0 else 0.0
            badput = {c: round(s, 6) for c, s in cats.items()
                      if c != PRODUCTIVE}
            return {
                "rank": self.rank,
                "world": self.world,
                "wall_time": time.time(),
                "epoch_wall_time": self._epoch_wall,
                "enabled": self.enabled,
                "wall_seconds": round(wall, 6),
                "goodput_fraction": round(goodput, 6),
                "accounted_fraction": round(accounted, 6),
                "productive_seconds": round(productive, 6),
                "badput_seconds": badput,
                "steps_productive": self._steps_productive,
                "steps_replayed": self._steps_replayed,
                "serve_blocks": self._serve_blocks,
                "incident_counts": dict(self._incident_counts),
                "incidents": [dict(i) for i in self._incidents],
            }

    def samples(self) -> List[list]:
        """The [wall_time, goodput_fraction] trail — the merged-trace
        "goodput fraction" counter track reads this."""
        with self._lock:
            return [list(s) for s in self._samples]

    def incidents(self) -> List[dict]:
        with self._lock:
            return [dict(i) for i in self._incidents]

    def set_incident_capacity(self, capacity: int) -> None:
        capacity = max(1, int(capacity))
        with self._lock:
            if self._incidents.maxlen != capacity:
                self._incidents = deque(self._incidents, maxlen=capacity)

    def reset(self) -> None:
        """Drop all accumulated state (tests and bench A/B harnesses)."""
        with self._lock:
            self._epoch = None
            self._epoch_wall = None
            self._cat.clear()
            self._first_mark = None
            self._step_mark = None
            self._other_since_step = 0.0
            self._steps_productive = 0
            self._steps_replayed = 0
            self._serve_blocks = 0
            self._serve_token_cost = None
            self._replay_remaining = 0
            self._replay_incident = None
            self._incidents.clear()
            self._incident_counts.clear()
            self._samples.clear()
            self._last_report = 0.0


_tracker = GoodputTracker()


def tracker() -> GoodputTracker:
    return _tracker


def record_span(category: str, seconds: float) -> None:
    """Module-level shorthand for instrumentation points; no-op when the
    tracker is disabled."""
    _tracker.record_span(category, seconds)


def record_step(seconds: Optional[float] = None, exposed_comm: float = 0.0,
                step: Optional[int] = None) -> None:
    _tracker.record_step(seconds, exposed_comm=exposed_comm, step=step)


def record_serve_step(seconds: float, tokens: int = 0) -> None:
    _tracker.record_serve_step(seconds, tokens=tokens)


def note_serve_preempted(tokens: int) -> None:
    _tracker.note_serve_preempted(tokens)


def note_incident(cause: str, seconds: float, **fields) -> None:
    _tracker.note_incident(cause, seconds, **fields)


def configure(rank: Optional[int] = None,
              world: Optional[int] = None) -> None:
    """Adopt the rank/world, parse the ``HOROVOD_GOODPUT_*`` knobs, pin
    the ledger epoch (first call only — elastic re-inits keep the
    original clock), and register the flight-recorder ``goodput`` state
    provider. Called from ``hvd.init()``."""
    t = _tracker
    if rank is not None:
        t.rank = int(rank)
    if world is not None:
        t.world = int(world)
    t.enabled = _get_bool(HOROVOD_GOODPUT, True)
    t.report_seconds = max(0.0, _get_float(
        HOROVOD_GOODPUT_REPORT_SECONDS, DEFAULT_REPORT_SECONDS))
    t.set_incident_capacity(_get_int(
        HOROVOD_GOODPUT_INCIDENTS, DEFAULT_INCIDENT_CAPACITY))
    if t.enabled:
        t.start_epoch()
    from horovod_tpu import flight_recorder

    if t.enabled:
        flight_recorder.set_state_provider("goodput", t.ledger)
    else:
        flight_recorder.set_state_provider("goodput", None)


def goodput_state() -> dict:
    """Document for the metrics server's ``GET /goodput`` route: the
    ledger + the recent goodput-fraction sample trail."""
    state = _tracker.ledger()
    state["samples"] = _tracker.samples()[-64:]
    return state


# -- cross-rank postmortem ----------------------------------------------------

def format_goodput_report(dumps: List[dict]) -> str:
    """Cross-rank goodput report from flight-recorder dumps' ``goodput``
    state: per-rank goodput and top badput, the fleet time-weighted
    goodput %, the dominant badput category, and the costliest incident
    (with its culprit rank when attribution named one). Empty string
    when no dump carries a goodput ledger (pre-goodput-plane dumps)."""
    ranks = []
    for d in dumps:
        gp = (d.get("state") or {}).get("goodput")
        if not isinstance(gp, dict) or not gp.get("wall_seconds"):
            continue
        ranks.append((d.get("launch_rank", d.get("rank", "?")), gp))
    if not ranks:
        return ""
    lines = ["=== goodput report (%d rank%s) ==="
             % (len(ranks), "" if len(ranks) == 1 else "s")]
    fleet_wall = fleet_productive = 0.0
    fleet_badput: Dict[str, float] = {}
    costliest = None  # (seconds, rank, incident)
    for rank, gp in sorted(ranks, key=lambda r: str(r[0])):
        wall = float(gp.get("wall_seconds", 0.0))
        productive = float(gp.get("productive_seconds", 0.0))
        fleet_wall += wall
        fleet_productive += productive
        badput = gp.get("badput_seconds") or {}
        top = max(badput, key=badput.get) if badput else None
        for cat, secs in badput.items():
            fleet_badput[cat] = fleet_badput.get(cat, 0.0) + float(secs)
        replayed = int(gp.get("steps_replayed", 0))
        lines.append(
            "rank %s: goodput %.1f%% of %.1fs (accounted %.1f%%)%s%s" % (
                rank, 100.0 * float(gp.get("goodput_fraction", 0.0)),
                wall, 100.0 * float(gp.get("accounted_fraction", 0.0)),
                ("; top badput %s %.1fs" % (top, badput[top]))
                if top else "",
                ("; %d step(s) replayed" % replayed) if replayed else ""))
        for inc in gp.get("incidents") or ():
            if not isinstance(inc, dict):
                continue
            cost = float(inc.get("duration_s", 0.0)) \
                + float(inc.get("replayed_seconds", 0.0))
            if costliest is None or cost > costliest[0]:
                costliest = (cost, rank, inc)
    if fleet_wall > 0:
        lines.append("fleet goodput: %.1f%% (time-weighted across %d "
                     "rank%s)" % (100.0 * fleet_productive / fleet_wall,
                                  len(ranks),
                                  "" if len(ranks) == 1 else "s"))
    if fleet_badput:
        dominant = max(fleet_badput, key=fleet_badput.get)
        lines.append("dominant badput: %s (%.1fs, %.1f%% of fleet wall)"
                     % (dominant, fleet_badput[dominant],
                        100.0 * fleet_badput[dominant] / fleet_wall
                        if fleet_wall > 0 else 0.0))
    if costliest is not None:
        cost, rank, inc = costliest
        extras = []
        if inc.get("generation") is not None:
            extras.append("gen %s" % inc["generation"])
        if inc.get("steps_replayed"):
            extras.append("%d step(s) replayed" % inc["steps_replayed"])
        if inc.get("culprit_rank") is not None:
            extras.append("culprit rank %s" % inc["culprit_rank"])
        lines.append("costliest incident: %s on rank %s — %.1fs%s" % (
            inc.get("cause", "?"), rank, cost,
            (" (%s)" % ", ".join(extras)) if extras else ""))
    return "\n".join(lines)
