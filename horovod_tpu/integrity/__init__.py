"""Numerical integrity plane: in-band collective digests, NaN/SDC
guards, and automatic rollback-and-replay.

PRs 2/8/9 made the system survive *loud* failures — killed ranks,
network partitions, torn checkpoints. This subsystem defends against
*silent* ones: a bit flipped in a collective result, a NaN that poisons
every replica through allreduce, or one divergent rank corrupting the
globally-averaged weights ("Silent Data Corruptions at Scale", Dinh et
al. 2022; "Cores that don't count", Hochschild et al. 2021). Three
cooperating layers, all off by default and armed by ``HOROVOD_INTEGRITY``:

* :mod:`~horovod_tpu.integrity.digest` — per-fusion-bucket payload
  digests (non-finite count on the *input*, checksum of the *result*)
  computed in band with the existing fused programs every
  ``HOROVOD_INTEGRITY_INTERVAL`` dispatches, plus the cross-rank
  digest-agreement exchange and majority vote that names the suspect
  rank.
* :mod:`~horovod_tpu.integrity.guards` — EWMA loss/grad-norm spike
  detection and the skip-step policy hooked into
  ``DistributedOptimizer`` and ``training.make_train_step``.
* :mod:`~horovod_tpu.integrity.rollback` — on a typed integrity
  failure, restore the last committed checkpoint in place (no process
  restart), optionally quarantine the voted-out rank, and replay under
  ``HOROVOD_ROLLBACK_BUDGET``.

:mod:`~horovod_tpu.integrity.inject` extends the PR-2 fault harness
with the silent-corruption fault kinds (``bitflip:<rank>[:after=N]``,
``nan:<rank>[:after=N]``) that validate the whole loop end to end.
"""

from __future__ import annotations

from horovod_tpu.utils.env import _get_bool, _get_int

# Master switch for the digest/guard machinery. Injection
# (integrity/inject.py) is armed by HOROVOD_FAULT_INJECT alone so a
# chaos run can prove that *undetected* corruption really corrupts.
HOROVOD_INTEGRITY = "HOROVOD_INTEGRITY"
# Digest cadence in fused dispatches per lane; 0 disables digests while
# leaving the step guards armed.
HOROVOD_INTEGRITY_INTERVAL = "HOROVOD_INTEGRITY_INTERVAL"
DEFAULT_INTEGRITY_INTERVAL = 32


def enabled() -> bool:
    """Whether the integrity plane is armed (read per call: tests and
    the elastic re-form rewrite env between generations)."""
    return _get_bool(HOROVOD_INTEGRITY)


def interval() -> int:
    """Digest cadence in dispatches (<=0 disables digest checks)."""
    return _get_int(HOROVOD_INTEGRITY_INTERVAL, DEFAULT_INTEGRITY_INTERVAL)


# Submodules import after the knob helpers they read; importing them
# here registers the horovod_integrity_* metrics family on package
# import so snapshots show zeros instead of missing families.
from horovod_tpu.integrity import digest  # noqa: E402
from horovod_tpu.integrity import guards  # noqa: E402
from horovod_tpu.integrity import inject  # noqa: E402
from horovod_tpu.integrity import rollback  # noqa: E402
from horovod_tpu.integrity.guards import StepGuard  # noqa: E402,F401

__all__ = [
    "HOROVOD_INTEGRITY", "HOROVOD_INTEGRITY_INTERVAL",
    "DEFAULT_INTEGRITY_INTERVAL", "enabled", "interval",
    "digest", "guards", "inject", "rollback", "StepGuard",
]
