"""In-band collective payload digests and the cross-rank agreement vote.

Two complementary digests ride each checked fused dispatch:

* **input non-finite count** — computed on the rank's *local* payload
  before the reduction. A NaN/Inf that enters a sum/avg collective
  poisons every replica's result identically, so the post-reduce output
  cannot name the origin; the pre-reduce count can, and the agreement
  exchange turns it into a typed :class:`~horovod_tpu.exceptions.
  NumericalError` carrying ``suspect_rank``.
* **result checksum** — CRC-32 of the reduced bytes each rank holds.
  The reduction's output is replicated by construction, so any
  disagreement is silent data corruption (a flipped bit, a divergent
  reduction order) on the minority rank; the majority vote names it and
  raises :class:`~horovod_tpu.exceptions.CollectiveIntegrityError`.

The exchange itself is one small ``allgatherv`` of a fixed 12-byte
record per rank, run only every ``HOROVOD_INTEGRITY_INTERVAL`` checked
dispatches, on the same thread and in the same negotiated order as the
payload traffic — in band, never racing the transport. Every rank
computes the identical verdict from the identical gathered records, so
all ranks raise together and the elastic rollback stays lockstep.
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu import exceptions
from horovod_tpu.metrics import registry as _metrics

_CHECKS = _metrics().counter(
    "horovod_integrity_checks_total",
    "Digest checks performed on fused collective payloads.")
_VIOLATIONS = _metrics().counter(
    "horovod_integrity_violations_total",
    "Integrity violations detected (non-finite payloads, cross-rank "
    "digest divergence, guard-budget exhaustion).",
    labelnames=("kind",))

# one record per rank on the wire: int64 non-finite count + uint32 CRC
_RECORD = struct.Struct("<qI")

# per-lane dispatch counters for the eager call sites (collectives /
# zero) that have no executor to hang cadence state on
_cadence: Dict[str, int] = {}  # guarded-by: <owner-thread>


def nonfinite_count(arr) -> int:
    """Count of NaN/Inf elements in ``arr``; 0 for non-float dtypes
    (integer payloads cannot go non-finite)."""
    a = np.asarray(arr)
    if a.dtype.kind not in ("f", "c", "V"):
        return 0
    if a.dtype.kind == "V":  # ml_dtypes (bf16) registers as void to numpy
        a = a.astype(np.float32)
    return int(np.sum(~np.isfinite(a)))


def checksum(arr) -> int:
    """CRC-32 of the array's bytes. Bitwise, not numeric: two results
    that differ only in NaN payload bits or -0.0 vs 0.0 still diverge,
    which is exactly the SDC signal wanted here."""
    a = np.ascontiguousarray(arr)
    return zlib.crc32(a.view(np.uint8).tobytes()) & 0xFFFFFFFF


def cadence_due(key: str, interval: Optional[int] = None) -> bool:
    """Per-lane dispatch cadence for eager call sites: True on the
    first and every ``interval``-th call for ``key``. Deterministic
    across ranks because the call sites execute in program order."""
    from horovod_tpu import integrity

    if not integrity.enabled():
        return False
    if interval is None:
        interval = integrity.interval()
    if interval <= 0:
        return False
    n = _cadence.get(key, 0)
    _cadence[key] = n + 1
    return n % interval == 0


def reset() -> None:
    """Forget cadence state (tests; elastic re-form)."""
    _cadence.clear()


def exchange(net, nf_count: int, crc: int) -> List[Tuple[int, int]]:
    """Gather every rank's (non-finite count, result CRC) record.

    Must run on the thread that owns ``net`` (the cycle thread for the
    executor paths), in the same negotiated order on every rank."""
    blobs = net.allgatherv(_RECORD.pack(int(nf_count), int(crc) & 0xFFFFFFFF))
    return [_RECORD.unpack(bytes(blob)) for blob in blobs]


def vote(crcs: Sequence[int]) -> Tuple[bool, Optional[int]]:
    """Majority vote over result checksums.

    Returns ``(diverged, suspect_rank)``: ``suspect_rank`` is the rank
    holding a minority checksum when the minority is a single rank,
    else None (a split vote is still a violation, just unattributable).
    """
    tally: Dict[int, List[int]] = {}
    for rank, crc in enumerate(crcs):
        tally.setdefault(crc, []).append(rank)
    if len(tally) <= 1:
        return False, None
    sizes = sorted(len(ranks) for ranks in tally.values())
    # attributable only when a UNIQUE single-rank minority exists — a
    # 1-vs-1 split (world of 2) or a multi-rank minority cannot say who
    # corrupted
    if sizes[0] != 1 or (len(sizes) > 1 and sizes[1] == 1):
        return True, None
    minority = min(tally.values(), key=len)
    return True, minority[0]


def verify(records: Sequence[Tuple[int, int]], bucket: str,
           tensor: Optional[str] = None) -> None:
    """Turn gathered digest records into the typed verdict.

    Every rank holds identical ``records`` (the exchange is an
    allgather), computes the identical verdict, and raises together —
    the elastic runner's rollback therefore stays lockstep with no
    extra barrier. Non-finite inputs outrank checksum divergence: a NaN
    propagates through the reduction and *causes* CRC agreement (every
    rank reduces to the same NaN), so the input digest is the only
    attribution signal for that class."""
    _CHECKS.inc()
    bad = [(rank, nf) for rank, (nf, _) in enumerate(records) if nf > 0]
    if bad:
        _VIOLATIONS.labels(kind="nonfinite").inc()
        suspect, count = bad[0]
        _emit_violation("nonfinite", bucket, tensor, suspect,
                        detail=f"{count} non-finite elements "
                               f"({len(bad)} rank(s) affected)")
        raise exceptions.NumericalError(
            f"non-finite payload entered collective bucket {bucket!r}: "
            f"rank {suspect} contributed {count} NaN/Inf element(s)",
            bucket=bucket, tensor=tensor, suspect_rank=suspect)
    diverged, suspect = vote([crc for _, crc in records])
    if diverged:
        _VIOLATIONS.labels(kind="divergence").inc()
        _emit_violation("divergence", bucket, tensor, suspect,
                        detail="result checksum disagreement "
                               f"{[hex(c) for _, c in records]}")
        raise exceptions.CollectiveIntegrityError(
            f"collective result diverged across ranks in bucket "
            f"{bucket!r} (checksums {[hex(c) for _, c in records]}); "
            f"suspect rank {suspect}",
            bucket=bucket, tensor=tensor, suspect_rank=suspect)


def verify_local(nf_count: int, bucket: str, tensor: Optional[str] = None,
                 suspect_rank: Optional[int] = None) -> None:
    """Single-copy verdict for paths with no cross-rank exchange (the
    single-controller fused program, the ZeRO sharded update): a
    non-finite count alone convicts, no vote needed."""
    _CHECKS.inc()
    if nf_count <= 0:
        return
    _VIOLATIONS.labels(kind="nonfinite").inc()
    _emit_violation("nonfinite", bucket, tensor, suspect_rank,
                    detail=f"{nf_count} non-finite elements")
    raise exceptions.NumericalError(
        f"non-finite payload in collective bucket {bucket!r}"
        + (f" from rank {suspect_rank}" if suspect_rank is not None else "")
        + f": {nf_count} NaN/Inf element(s)",
        bucket=bucket, tensor=tensor, suspect_rank=suspect_rank)


def _emit_violation(kind: str, bucket: str, tensor: Optional[str],
                    suspect: Optional[int], detail: str) -> None:
    from horovod_tpu import flight_recorder

    flight_recorder.emit("integrity_violation", violation=kind,
                         bucket=bucket, tensor=tensor, suspect=suspect,
                         detail=detail)
