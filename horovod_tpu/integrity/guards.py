"""Step-level numerical guards: EWMA spike detection + skip-step policy.

The digests (digest.py) catch corruption *in the collective*; the step
guard catches what they structurally cannot — a numerically-poisoned
batch, an exploding loss, a gradient blow-up that is finite but wrong.
:class:`StepGuard` keeps an exponentially-weighted mean/variance of a
scalar stream (loss or global grad-norm) and flags observations that
are non-finite or spike above ``mean + sigma * std``. A flagged step is
*skipped* (the optimizer update suppressed, the data consumed) up to
``HOROVOD_INTEGRITY_SKIP_STEPS`` consecutive times; past the budget the
guard raises :class:`~horovod_tpu.exceptions.NumericalError` so the
elastic runner rolls back instead of letting a persistent divergence
eat the run.

Determinism note: the guard observes *globally-reduced* scalars (the
allreduced loss / grad norm), so every rank sees the same stream, makes
the same skip decision, and raises on the same step — no extra
agreement traffic needed.
"""

from __future__ import annotations

import math
from typing import Optional

from horovod_tpu import exceptions
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import _get_float, _get_int

HOROVOD_INTEGRITY_SPIKE_SIGMA = "HOROVOD_INTEGRITY_SPIKE_SIGMA"
HOROVOD_INTEGRITY_SKIP_STEPS = "HOROVOD_INTEGRITY_SKIP_STEPS"
DEFAULT_SPIKE_SIGMA = 6.0
DEFAULT_SKIP_STEPS = 3

_SKIPPED = _metrics().counter(
    "horovod_integrity_skipped_steps_total",
    "Optimizer steps suppressed by the integrity spike guard.")


class StepGuard:
    """EWMA spike detector over one scalar training statistic.

    ``observe(v)`` returns True to accept the step, False to skip it;
    raises :class:`NumericalError` when ``skip_budget`` consecutive
    steps have been skipped. State is single-threaded (the training
    loop's thread).
    """

    def __init__(self, sigma: Optional[float] = None,
                 skip_budget: Optional[int] = None,
                 warmup: int = 5, decay: float = 0.9,
                 name: str = "loss") -> None:
        self.sigma = sigma if sigma is not None else _get_float(
            HOROVOD_INTEGRITY_SPIKE_SIGMA, DEFAULT_SPIKE_SIGMA)
        self.skip_budget = skip_budget if skip_budget is not None \
            else _get_int(HOROVOD_INTEGRITY_SKIP_STEPS, DEFAULT_SKIP_STEPS)
        self.warmup = warmup
        self.decay = decay
        self.name = name
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self.consecutive_skips = 0

    def _is_spike(self, v: float) -> bool:
        if not math.isfinite(v):
            return True
        if self.n < self.warmup:
            return False
        std = math.sqrt(max(self.var, 0.0))
        # one-sided upward test with a small relative slack: a constant
        # stream has std ~= 0 and must not trip on float jitter, and a
        # *drop* in loss is progress, never a spike
        slack = 1e-6 + 1e-3 * abs(self.mean)
        return v > self.mean + self.sigma * std + slack

    def observe(self, v: float) -> bool:
        v = float(v)
        if self._is_spike(v):
            self.consecutive_skips += 1
            _SKIPPED.inc()
            self._emit_spike(v)
            log.warning(
                "integrity guard: %s spike (%r vs mean %.6g std %.3g), "
                "skipping step (%d/%d consecutive)", self.name, v,
                self.mean, math.sqrt(max(self.var, 0.0)),
                self.consecutive_skips, self.skip_budget)
            if self.consecutive_skips > self.skip_budget:
                raise exceptions.NumericalError(
                    f"integrity guard: {self.name} spiked on "
                    f"{self.consecutive_skips} consecutive steps "
                    f"(budget {self.skip_budget}); last value {v!r}, "
                    f"EWMA mean {self.mean:.6g}", tensor=self.name)
            return False
        self.consecutive_skips = 0
        # EW moments (West-style update): first observation seeds the mean
        if self.n == 0:
            self.mean = v
        else:
            diff = v - self.mean
            incr = (1.0 - self.decay) * diff
            self.mean += incr
            self.var = self.decay * (self.var + diff * incr)
        self.n += 1
        return True

    def reset(self) -> None:
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self.consecutive_skips = 0

    def _emit_spike(self, v: float) -> None:
        from horovod_tpu import flight_recorder

        flight_recorder.emit(
            "integrity_spike", stat=self.name, value=repr(v),
            mean=self.mean, std=math.sqrt(max(self.var, 0.0)),
            consecutive=self.consecutive_skips, budget=self.skip_budget)


# process-default guard for the DistributedOptimizer hook: one stream of
# global grad norms per process
_default_guard: Optional[StepGuard] = None  # guarded-by: <owner-thread>


def default_guard() -> StepGuard:
    global _default_guard
    if _default_guard is None:
        _default_guard = StepGuard(name="grad_norm")
    return _default_guard


def reset() -> None:
    """Drop the process-default guard (tests; elastic re-form)."""
    global _default_guard
    _default_guard = None


def guard_gradients(tree) -> bool:
    """Observe the global gradient norm of an (already allreduced)
    gradient pytree; True = apply the update, False = skip it.

    The squared-norm accumulation propagates NaN/Inf, so a single bad
    leaf flags the whole step."""
    import jax
    import numpy as np

    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.asarray(leaf)
        if a.dtype.kind not in ("f", "c", "V"):
            continue
        if a.dtype.kind == "V":
            a = a.astype(np.float32)
        total += float(np.sum(np.square(a.astype(np.float64))))
    return default_guard().observe(math.sqrt(total))
