"""Silent-data-corruption fault kinds for the HOROVOD_FAULT_INJECT
harness.

The PR-2 process faults (kill/hang/slow) and PR-8 network faults
(partition/kv_outage/...) are *loud*; these are the silent ones that the
integrity plane exists to catch. Grammar (composes with the other kinds
in one ``;``-separated spec)::

    bitflip:<rank>[:after=<n>]
    nan:<rank>[:after=<n>]

* ``nan`` poisons one element of the target rank's *input* payload in
  the executor pack path, before the reduction — the NaN then spreads
  to every replica through sum/avg, modeling a poisoned gradient.
* ``bitflip`` flips one bit in the target rank's *local copy of the
  reduced result* after the collective, modeling SDC on the readback
  path — the other ranks hold the correct bytes, so only the cross-rank
  checksum vote can see it.
* ``after`` counts eligible fused dispatches to skip before the
  one-shot fires (default 0: the first checked dispatch).

Injection is armed by HOROVOD_FAULT_INJECT alone, independent of
``HOROVOD_INTEGRITY`` — a chaos run with detection disabled proves that
undetected corruption really corrupts.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Tuple

import numpy as np

from horovod_tpu.utils import logging as log

INTEGRITY_FAULT_KINDS = ("bitflip", "nan")

# armed one-shot specs, parsed lazily from the env; None = not parsed yet
_specs: "Optional[List[FaultSpec]]" = None  # guarded-by: <owner-thread>


@dataclasses.dataclass
class FaultSpec:
    action: str
    rank: int
    after: int = 0      # eligible dispatches to skip before firing
    fired: bool = False  # one-shot latch


def is_integrity_clause(clause: str) -> bool:
    """Whether a HOROVOD_FAULT_INJECT clause belongs to this module (so
    ``fault_inject.spec_from_env`` skips it rather than rejecting)."""
    return clause.strip().split(":", 1)[0].strip().lower() \
        in INTEGRITY_FAULT_KINDS


def parse_clause(clause: str) -> FaultSpec:
    parts = [p.strip() for p in clause.strip().split(":")]
    action = parts[0].lower()
    if action not in INTEGRITY_FAULT_KINDS:
        raise ValueError(
            f"HOROVOD_FAULT_INJECT: unknown integrity action {action!r} "
            f"(expected one of {INTEGRITY_FAULT_KINDS})")
    if len(parts) < 2 or not parts[1].lstrip("-").isdigit():
        raise ValueError(
            f"HOROVOD_FAULT_INJECT: {action} clause must name a rank, "
            f"got {clause!r}")
    rank = int(parts[1])
    after = 0
    for part in parts[2:]:
        key, _, value = part.partition("=")
        if key.strip().lower() != "after" or not value:
            raise ValueError(
                f"HOROVOD_FAULT_INJECT: malformed integrity clause part "
                f"{part!r} (expected after=<n>)")
        after = int(value)
    return FaultSpec(action=action, rank=rank, after=after)


def specs_from_env() -> List[FaultSpec]:
    """All armed integrity clauses, parsed once and cached so the
    ``after`` countdown and one-shot latch persist across dispatches."""
    global _specs
    if _specs is None:
        _specs = [
            parse_clause(clause)
            for clause in os.environ.get("HOROVOD_FAULT_INJECT", "")
            .split(";")
            if clause.strip() and is_integrity_clause(clause)
        ]
    return _specs


def reset() -> None:
    """Re-read the env and forget countdown state (tests)."""
    global _specs
    _specs = None


def _plan(rank_filter: Optional[int]) -> Optional[Tuple[str, int]]:
    """Advance every armed spec's countdown by one eligible dispatch and
    return ``(action, spec_rank)`` for the first spec that fires now."""
    fire = None
    for spec in specs_from_env():
        if spec.fired:
            continue
        if rank_filter is not None and spec.rank != rank_filter:
            continue
        if spec.after > 0:
            spec.after -= 1
            continue
        if fire is None:
            spec.fired = True
            fire = (spec.action, spec.rank)
    return fire


def plan_dispatch() -> Optional[str]:
    """Multi-process paths: fire when this worker's *launch* rank is
    the clause target (re-forms renumber ranks; faults must not
    re-target). Returns the action or None."""
    from horovod_tpu.elastic import fault_inject

    if not specs_from_env():
        return None
    fire = _plan(fault_inject.initial_rank())
    if fire is None:
        return None
    _announce(fire[0], fire[1])
    return fire[0]


def plan_dispatch_any() -> Optional[Tuple[str, int]]:
    """Single-controller path: one process owns every rank's rows, so
    the clause rank selects the *row* instead of filtering the process.
    Returns ``(action, row)`` or None."""
    if not specs_from_env():
        return None
    fire = _plan(None)
    if fire is not None:
        _announce(fire[0], fire[1])
    return fire


def corrupt_nan(buf: np.ndarray) -> None:
    """Poison element 0 of a float buffer in place (pre-reduce input)."""
    flat = buf.reshape(-1)
    if flat.dtype.kind == "V":  # ml_dtypes bf16
        flat.view(np.uint16)[0] = 0x7FC1  # bf16 quiet NaN
    else:
        flat[0] = np.nan


def corrupt_bitflip(buf: np.ndarray) -> None:
    """Flip the lowest bit of byte 0 in place (post-reduce local copy)."""
    raw = buf.reshape(-1).view(np.uint8)
    raw[0] ^= 0x01


def _announce(action: str, rank: int) -> None:
    from horovod_tpu import flight_recorder
    from horovod_tpu.elastic import fault_inject

    log.error("fault injection: %s corruption armed for rank %d fires now",
              action, rank)
    fault_inject._FAULTS_INJECTED.inc()
    flight_recorder.emit("fault_inject", action=action, rank=rank)
