"""Automatic rollback-and-replay for integrity failures.

When the digest plane (or the step guard's exhausted skip budget)
raises a typed :class:`~horovod_tpu.exceptions.NumericalError`, every
rank raises it together at the same dispatch — so recovery needs no
membership re-form, no process restart, and no extra barrier: each
rank restores the last committed checkpoint *in place* and the elastic
runner re-enters the training function to replay the lost steps.

Policy knobs:

* ``HOROVOD_ROLLBACK_BUDGET`` — in-place replays allowed per process
  lifetime (default 2). An exhausted budget re-raises the integrity
  error so the PR-9 supervised-restart path takes over; corruption that
  survives N replays is not transient and needs a human (or new
  hardware).
* ``HOROVOD_INTEGRITY_QUARANTINE`` — when the digest vote named *this*
  rank as the corruption source, exit instead of replaying; the PR-2
  elastic reform then re-forms the survivors without the suspect
  machine. Off by default: a single flipped bit is usually transient.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Optional

from horovod_tpu import flight_recorder
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import _get_bool, _get_int

HOROVOD_ROLLBACK_BUDGET = "HOROVOD_ROLLBACK_BUDGET"
HOROVOD_INTEGRITY_QUARANTINE = "HOROVOD_INTEGRITY_QUARANTINE"
DEFAULT_ROLLBACK_BUDGET = 2

_ROLLBACKS = _metrics().counter(
    "horovod_integrity_rollbacks_total",
    "In-place rollback-and-replay recoveries from integrity failures.")

_replays = 0  # guarded-by: <owner-thread>


def replays() -> int:
    return _replays


def reset() -> None:
    """Forget the replay count (tests)."""
    global _replays
    _replays = 0


def should_quarantine(exc: Exception) -> bool:
    """Whether this process is the digest vote's suspect and quarantine
    is armed."""
    if not _get_bool(HOROVOD_INTEGRITY_QUARANTINE):
        return False
    suspect = getattr(exc, "suspect_rank", None)
    if suspect is None:
        return False
    from horovod_tpu.elastic import fault_inject

    return suspect == fault_inject.initial_rank()


def quarantine_self(exc: Exception) -> None:
    """Leave the job so the elastic reform excludes this rank. Exits
    the process (the PR-2 path treats it like a worker loss)."""
    log.error("integrity quarantine: this rank (%s) was voted the "
              "corruption source — exiting so the job re-forms without "
              "it (%s)", getattr(exc, "suspect_rank", "?"), exc)
    flight_recorder.emit("integrity_quarantine",
                         suspect=getattr(exc, "suspect_rank", None),
                         error=str(exc)[:200])
    flight_recorder.dump_on_failure("integrity_quarantine")
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(1)


def handle_failure(state, exc: Exception) -> Optional[int]:
    """Restore the last committed state in place and account the replay.

    Called by the elastic runner's ``NumericalError`` clause on every
    rank (all ranks raise the identical verdict together). Re-raises
    ``exc`` when the rollback budget is exhausted so the supervised
    restart (PR 9) takes over. Returns the restored step when a
    checkpoint cut was reloaded, else None (memory-snapshot restore).
    """
    global _replays
    if should_quarantine(exc):
        quarantine_self(exc)  # does not return
    budget = _get_int(HOROVOD_ROLLBACK_BUDGET, DEFAULT_ROLLBACK_BUDGET)
    if _replays >= budget:
        log.error("integrity rollback budget exhausted (%d/%d) — "
                  "escalating to supervised restart", _replays, budget)
        flight_recorder.emit("rollback_budget_exhausted",
                             replays=_replays, budget=budget,
                             error=str(exc)[:200])
        flight_recorder.dump_on_failure("rollback_budget_exhausted")
        raise exc
    _replays += 1
    _ROLLBACKS.inc()
    failing_step = getattr(state, "step", None)
    t_restore = time.monotonic()
    restored_step = None
    # prefer the durable PR-9 cut (bit-identical, survives a poisoned
    # in-memory snapshot); fall back to the commit-time memory snapshot
    if getattr(state, "_ckpt_dir", None):
        wait = getattr(state, "checkpoint_wait", None)
        if wait is not None:
            wait()  # an in-flight async commit must land before restore
        restored_step = state.load_latest()
    if restored_step is None:
        state.on_reset()
        restored_step = getattr(state, "step", None)
    log.warning("integrity rollback %d/%d: restored step %s after %s: %s",
                _replays, budget, restored_step, type(exc).__name__, exc)
    flight_recorder.emit("rollback", replay=_replays, budget=budget,
                         restored_step=restored_step,
                         suspect=getattr(exc, "suspect_rank", None),
                         error="%s: %s" % (type(exc).__name__,
                                           str(exc)[:200]))
    try:
        # goodput ledger: the restore is rollback badput, and the steps
        # between the restored cut and the failure will be re-run —
        # charged to this incident, not counted productive twice
        from horovod_tpu import goodput

        # +1: the step that was IN FLIGHT at the failure is re-executed
        # too — its aborted first attempt is wasted work even when the
        # restore lands exactly on the last commit
        replay_steps = 1
        if isinstance(failing_step, int) and isinstance(restored_step, int):
            replay_steps = max(0, failing_step - restored_step) + 1
        goodput.note_incident(
            "rollback", time.monotonic() - t_restore,
            culprit_rank=getattr(exc, "suspect_rank", None),
            replay_steps=replay_steps,
            linked_events=["rollback", "integrity_violation"])
    except Exception:
        pass  # accounting must never fail a rollback
    return restored_step
