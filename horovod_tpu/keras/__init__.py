"""horovod_tpu.keras — high-level fit/evaluate/predict training surface.

Rebuild of the reference's Keras binding (reference: horovod/keras/
__init__.py, horovod/_keras/__init__.py:35-126, _keras/callbacks.py): the
reference wraps a Keras optimizer and drives training through callbacks;
the TPU-native analogue is a small ``Trainer`` over a flax module that
packages the same conventions — DistributedOptimizer wrapping, initial
broadcast, per-epoch metric averaging, LR warmup scheduling, rank-0
checkpointing with optimizer-rewrapping restore (the reference's
``load_model``, keras/__init__.py:117-160).

    import horovod_tpu.keras as hvd_keras

    trainer = hvd_keras.Trainer(model, optax.adam(1e-3 * hvd.size()),
                                input_shape=(1, 28, 28, 1))
    history = trainer.fit(images, labels, epochs=3, batch_size=64,
                          callbacks=[hvd_keras.MetricAverageCallback()])
    trainer.save("ckpts", step=3)
    trainer = hvd_keras.Trainer.load("ckpts", model, optax.adam(1e-3),
                                     input_shape=(1, 28, 28, 1))
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu import checkpoint as ckpt_mod
from horovod_tpu import training
from horovod_tpu.callbacks import (  # noqa: F401 — reference callback suite
    BroadcastGlobalVariablesCallback,
    Callback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    average_metrics,
    warmup_scaled_schedule,
)
from horovod_tpu.core import basics
from horovod_tpu.parallel.dp import DistributedOptimizer


class Trainer:
    """Compact fit/evaluate/predict loop over a flax module with the
    reference's distributed conventions baked in."""

    def __init__(self, model, optimizer, input_shape,
                 loss_fn: Optional[Callable] = None,
                 compression=None,
                 input_dtype=jnp.float32,
                 rng: Optional[jax.Array] = None,
                 _state: Optional[training.TrainState] = None):
        self.model = model
        if not _is_distributed(optimizer):
            kwargs = {"compression": compression} if compression else {}
            optimizer = DistributedOptimizer(optimizer, **kwargs)
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.input_shape = tuple(input_shape)
        self.input_dtype = input_dtype
        self.state = _state or training.create_train_state(
            model, optimizer, input_shape, rng=rng,
            input_dtype=input_dtype)
        self._step, self.batch_sharding = training.make_train_step(
            model, optimizer, loss_fn=loss_fn, donate=False)
        self._predict_fn = None

    # -- training ---------------------------------------------------------

    def fit(self, inputs, labels, *, epochs: int = 1, batch_size: int = 32,
            callbacks: Sequence[Callback] = (), initial_epoch: int = 0,
            shuffle: bool = True, verbose: int = 1) -> dict:
        """Explicit epoch/batch loop; ``batch_size`` is per worker.
        Returns a history dict of per-epoch averaged metrics."""
        inputs = np.asarray(inputs)
        labels = np.asarray(labels)
        global_batch = batch_size * basics.size()
        steps = len(inputs) // global_batch
        if steps == 0:
            raise ValueError(
                f"dataset of {len(inputs)} examples is smaller than one "
                f"global batch ({global_batch})")

        tree = self._tree()
        for cb in callbacks:
            tree = cb.on_train_begin(tree)
        self._set_tree(tree)

        history: dict = {"loss": []}
        tree = self._tree()
        for epoch in range(initial_epoch, epochs):
            for cb in callbacks:
                tree = cb.on_epoch_begin(epoch, tree)
            order = (np.random.RandomState(epoch).permutation(len(inputs))
                     if shuffle else np.arange(len(inputs)))
            losses = []
            for i in range(steps):
                for cb in callbacks:
                    tree = cb.on_batch_begin(i, tree)
                tree = self._apply_callback_lr(tree, callbacks)
                idx = order[i * global_batch:(i + 1) * global_batch]
                xb = jax.device_put(inputs[idx], self.batch_sharding)
                yb = jax.device_put(labels[idx], self.batch_sharding)
                loss, params, stats, opt_state = self._step(
                    tree["params"], tree["batch_stats"], tree["opt_state"],
                    xb, yb)
                tree = {"params": params, "batch_stats": stats,
                        "opt_state": opt_state}
                losses.append(float(loss))
            metrics = {"loss": float(np.mean(losses))}
            for cb in callbacks:
                tree, metrics = cb.on_epoch_end(epoch, tree, metrics)
            self._set_tree(tree)
            self.state.step = epoch
            for k, v in metrics.items():
                history.setdefault(k, []).append(float(v))
            if verbose and basics.rank() == 0:
                shown = ", ".join(f"{k}: {float(v):.4f}"
                                  for k, v in metrics.items())
                print(f"Epoch {epoch + 1}/{epochs} - {shown}")
        return history

    def _apply_callback_lr(self, tree, callbacks):
        """Honor eager LR callbacks (reference: _keras/callbacks.py sets
        the Keras optimizer's lr): the last callback exposing ``.lr`` wins,
        written into the optimizer's injected hyperparams."""
        lr = None
        for cb in callbacks:
            if hasattr(cb, "lr"):
                lr = float(cb.lr)
        if lr is None:
            return tree
        found = False

        def set_lr(node):
            nonlocal found
            hp = getattr(node, "hyperparams", None)
            if isinstance(hp, dict) and "learning_rate" in hp:
                found = True
                hp["learning_rate"] = jnp.asarray(
                    lr, jnp.asarray(hp["learning_rate"]).dtype)
            return node

        jax.tree_util.tree_map(
            set_lr, tree["opt_state"],
            is_leaf=lambda n: hasattr(n, "hyperparams"))
        if not found:
            raise ValueError(
                "an LR callback is active but the optimizer exposes no "
                "injected 'learning_rate' hyperparameter; build it with "
                "optax.inject_hyperparams (e.g. "
                "optax.inject_hyperparams(optax.sgd)(learning_rate=lr)) "
                "or use a schedule (warmup_scaled_schedule) instead")
        return tree

    # -- inference --------------------------------------------------------

    def predict(self, inputs, batch_size: Optional[int] = None):
        """Forward pass (train=False); returns host logits."""
        if self._predict_fn is None:
            self._predict_fn = jax.jit(
                lambda v, x: self.model.apply(v, x, train=False))
        variables = {"params": self.state.params}
        if self.state.batch_stats:
            variables["batch_stats"] = self.state.batch_stats
        return np.asarray(self._predict_fn(variables, jnp.asarray(inputs)))

    def evaluate(self, inputs, labels) -> dict:
        """Loss + accuracy over the given data, averaged across workers
        (the reference's MetricAverageCallback convention)."""
        logits = self.predict(inputs)
        labels = np.asarray(labels)
        loss = float(np.mean(
            optax.softmax_cross_entropy_with_integer_labels(
                jnp.asarray(logits), jnp.asarray(labels))))
        acc = float(np.mean(np.argmax(logits, axis=-1) == labels))
        return {k: float(v) for k, v in
                average_metrics({"loss": loss, "accuracy": acc}).items()}

    # -- persistence (reference: keras load_model with optimizer rewrap,
    # keras/__init__.py:117-160) -----------------------------------------

    def save(self, directory: str, step: int = 0,
             keep: Optional[int] = None):
        """Rank-0 checkpoint of params/stats/optimizer state."""
        return ckpt_mod.save(directory, self._tree(), step=step, keep=keep)

    @classmethod
    def load(cls, directory: str, model, optimizer, input_shape,
             loss_fn: Optional[Callable] = None,
             input_dtype=jnp.float32) -> "Trainer":
        """Rebuild a trainer from the newest checkpoint, rewrapping the
        (fresh) optimizer in DistributedOptimizer — weights AND optimizer
        state restore, broadcast from rank 0."""
        trainer = cls(model, optimizer, input_shape, loss_fn=loss_fn,
                      input_dtype=input_dtype)
        tree, step = ckpt_mod.restore_latest(directory, trainer._tree())
        trainer._set_tree(tree)
        if step is not None:
            trainer.state.step = step
        return trainer

    # -- helpers ----------------------------------------------------------

    def _tree(self) -> dict:
        return {"params": self.state.params,
                "batch_stats": self.state.batch_stats,
                "opt_state": self.state.opt_state}

    def _set_tree(self, tree: dict) -> None:
        self.state = training.TrainState(
            tree["params"], tree["batch_stats"], tree["opt_state"],
            step=self.state.step)


def _is_distributed(optimizer) -> bool:
    # DistributedOptimizer returns a GradientTransformationExtraArgs whose
    # update closure lives in parallel/dp.py
    update = getattr(optimizer, "update", None)
    code = getattr(update, "__code__", None)
    return bool(code and "dp.py" in code.co_filename)
