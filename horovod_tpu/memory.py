"""Memory telemetry plane: per-subsystem HBM/host accounting + OOM forensics.

The time plane (metrics.py, flight_recorder.py, profiler.py, PRs 1/4/6)
answers "where did the time go?"; this module answers "where did the
bytes go?". One process-wide :class:`MemoryTracker` holds a ledger of
live-bytes and high-watermarks per byte-holding subsystem:

* ``params`` / ``grads`` — pushed by the eager ``DistributedOptimizer``
  update path (:mod:`horovod_tpu.parallel.dp`);
* ``optimizer_shards`` — pushed by the ZeRO-1 state accounting
  (:mod:`horovod_tpu.parallel.zero`);
* ``grad_shards`` / ``param_shards`` — pushed by the ZeRO-2/3 shard
  accounting (:mod:`horovod_tpu.parallel.zero`): gradients living only
  as the local 1/N shard after a reduce-scatter release, and parameters
  sharded at rest between on-demand gathers;
* ``fusion`` / ``ckpt_staging`` — pulled from the fusion-buffer slab
  registry (:func:`horovod_tpu.runtime.fusion_buffer.bytes_by_purpose`),
  which distinguishes resident slab bytes from *leased* (live) bytes so
  a leaked lease is visible;
* ``serve_kv`` — pulled from the live :class:`~horovod_tpu.serve.
  kv_cache.DecodeEngine` registry;
* ``kv_pages`` — pulled from the paged KV-cache pool registry
  (:func:`horovod_tpu.serve.paging.total_pool_bytes`; the
  ``HOROVOD_SERVE_PAGED`` serving path);
* ``program_cache`` — pulled from the executors' compiled-program caches
  (estimated from the bucket-stable cache keys: rows x capacity x
  itemsize per program);
* ``host_rss`` — the process VmRSS from ``/proc/self/status``.

Claimed bytes are reconciled against **device truth** on a sampling
cadence (``HOROVOD_MEMORY_SAMPLE_SECONDS``): ``jax.Device.
memory_stats()`` where the backend reports it (TPU/GPU), a
``jax.live_arrays()`` sweep otherwise (the CPU backend under tier-1).
The drift between claimed and actual device bytes is itself a gauge
(``horovod_memory_reconcile_drift_ratio``) — accounting rot shows up as
a metric, not a surprise at the next OOM.

Surfaces (each mirrors where the time plane already lives):

* ``horovod_memory_*`` metric families + ``GET /memory`` on the metrics
  server (docs/memory.md);
* a ``memory`` flight-recorder state provider — every dump (crash,
  stall, SIGUSR1) carries the ledger;
* per-step ``peak_hbm_bytes`` in the profiler breakdown and a memory
  counter track in the merged Perfetto trace;
* OOM forensics: :func:`is_oom` / :func:`record_oom` catch
  ``RESOURCE_EXHAUSTED`` at the executor and elastic boundaries and dump
  the ledger + top-k live arrays (shape/dtype/owner);
  :func:`format_memory_report` renders the cross-rank postmortem section
  naming the dominant subsystem and the rank nearest its HBM ceiling
  (``tpurun --postmortem``).

Env knobs (registered in utils/env.py, table in docs/memory.md):
``HOROVOD_MEMORY`` (sampler on/off, default on),
``HOROVOD_MEMORY_SAMPLE_SECONDS`` (cadence, default 10),
``HOROVOD_MEMORY_TOPK`` (live arrays in forensics dumps, default 8).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from horovod_tpu.analysis import witness
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils.env import _get_bool, _get_float, _get_int

HOROVOD_MEMORY = "HOROVOD_MEMORY"
HOROVOD_MEMORY_SAMPLE_SECONDS = "HOROVOD_MEMORY_SAMPLE_SECONDS"
HOROVOD_MEMORY_TOPK = "HOROVOD_MEMORY_TOPK"

DEFAULT_SAMPLE_SECONDS = 10.0
DEFAULT_TOPK = 8
_SAMPLE_RING = 512  # bounded: ~85 min of samples at the default cadence

_BYTES = _metrics().gauge(
    "horovod_memory_bytes",
    "Live bytes claimed per subsystem (params, grads, param_shards, "
    "grad_shards, optimizer_shards, fusion, ckpt_staging, serve_kv, "
    "kv_pages, program_cache, host_rss).",
    labelnames=("subsystem",))
_PEAK_BYTES = _metrics().gauge(
    "horovod_memory_peak_bytes",
    "High watermark of the per-subsystem live bytes since process start.",
    labelnames=("subsystem",))
_DEVICE_BYTES = _metrics().gauge(
    "horovod_memory_device_bytes_in_use",
    "Device truth: bytes_in_use from jax.Device.memory_stats() (or the "
    "jax.live_arrays() sum where the backend reports no stats).")
_DEVICE_PEAK = _metrics().gauge(
    "horovod_memory_device_peak_bytes",
    "Device truth: peak_bytes_in_use high watermark.")
_DEVICE_LIMIT = _metrics().gauge(
    "horovod_memory_device_limit_bytes",
    "Device HBM ceiling (bytes_limit from memory_stats; 0 when the "
    "backend does not report one).")
_HOST_RSS = _metrics().gauge(
    "horovod_memory_host_rss_bytes",
    "Process resident set size (VmRSS from /proc/self/status).")
_DRIFT = _metrics().gauge(
    "horovod_memory_reconcile_drift_ratio",
    "Relative drift between claimed device-resident bytes and device "
    "truth: (actual - claimed) / actual. Accounting rot is a metric.")
_SAMPLES = _metrics().counter(
    "horovod_memory_samples_total",
    "Reconciliation sweeps completed by the memory sampler.")
_OOMS = _metrics().counter(
    "horovod_memory_oom_total",
    "RESOURCE_EXHAUSTED errors caught and turned into forensics dumps.")

# subsystems whose bytes live in device memory (HBM) — the reconciliation
# set; everything else (fusion slabs, ckpt staging, host_rss) is host-side
DEVICE_SUBSYSTEMS = ("params", "grads", "param_shards", "grad_shards",
                     "optimizer_shards", "serve_kv", "kv_pages")


def host_rss_bytes() -> int:
    """VmRSS of this process, 0 when /proc is unavailable (non-Linux)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return 0


def device_memory_stats() -> Dict[str, int]:
    """``memory_stats()`` of the first local device, ``{}`` when the
    backend (e.g. CPU) does not implement it."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return {}
    if not isinstance(stats, dict):
        return {}
    return {k: int(v) for k, v in stats.items()
            if isinstance(v, (int, float))}


def live_array_bytes() -> int:
    """Total bytes of every live jax.Array on this process — the device
    truth of last resort (works on every backend, including CPU)."""
    try:
        import jax

        return sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in jax.live_arrays())
    except Exception:
        return 0


class MemoryTracker:
    """Process-wide byte ledger: push gauges, pull providers, watermarks,
    a reconciliation sampler, and the OOM forensics state.

    Hot-path cost when idle is one attribute read (``enabled``); push
    updates are a dict store + two gauge sets under a short lock."""

    def __init__(self) -> None:
        self._lock = witness.make_lock("MemoryTracker._lock")
        self._claimed: Dict[str, int] = {}       # guarded-by: _lock
        self._peaks: Dict[str, int] = {}         # guarded-by: _lock
        self._providers: Dict[str, Callable[[], int]] = {}  # guarded-by: _lock
        # id -> (weakref, subsystem) for adopted arrays; jax.Array is
        # unhashable, so ownership is keyed by id with a removal callback
        self._owned: Dict[int, Any] = {}         # guarded-by: _lock
        self._samples: deque = deque(maxlen=_SAMPLE_RING)  # guarded-by: _lock
        self._last_oom: Optional[dict] = None    # guarded-by: _lock
        self._sampler: Optional[threading.Thread] = None  # guarded-by: _lock
        self._stop = threading.Event()
        self.enabled = True   # accounting; the sampler thread is separate
        self.rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        self.sample_seconds = DEFAULT_SAMPLE_SECONDS
        self.topk = DEFAULT_TOPK

    # -- accounting (push) -------------------------------------------------
    def set_bytes(self, subsystem: str, nbytes: int) -> None:
        """Record ``subsystem``'s current live bytes and roll its peak."""
        if not self.enabled:
            return
        nbytes = int(nbytes)
        with self._lock:
            self._claimed[subsystem] = nbytes
            peak = self._peaks.get(subsystem, 0)
            if nbytes > peak:
                peak = nbytes
                self._peaks[subsystem] = peak
        _BYTES.labels(subsystem=subsystem).set(nbytes)
        _PEAK_BYTES.labels(subsystem=subsystem).set(peak)

    def note_tree_bytes(self, subsystem: str, tree) -> None:
        """``set_bytes`` over a pytree's array leaves (cheap: shape math
        only, no device transfer)."""
        if not self.enabled:
            return
        try:
            import jax

            total = sum(int(getattr(leaf, "nbytes", 0) or 0)
                        for leaf in jax.tree_util.tree_leaves(tree))
        except Exception:
            return
        self.set_bytes(subsystem, total)

    # -- accounting (pull) -------------------------------------------------
    def register(self, subsystem: str,
                 fn: Optional[Callable[[], int]]) -> None:
        """Register a live-bytes provider polled at each sample/snapshot;
        ``None`` unregisters. Providers run OUTSIDE the tracker lock (a
        provider typically takes its own subsystem lock)."""
        with self._lock:
            if fn is None:
                self._providers.pop(subsystem, None)
            else:
                self._providers[subsystem] = fn

    # -- ownership attribution --------------------------------------------
    def adopt(self, subsystem: str, tree) -> None:
        """Tag the array leaves of ``tree`` as owned by ``subsystem`` so
        :func:`top_live_arrays` can attribute them. Weakref-tracked: a
        freed array drops out of the registry automatically."""
        if not self.enabled:
            return
        try:
            import jax

            leaves = jax.tree_util.tree_leaves(tree)
        except Exception:
            return
        for leaf in leaves:
            if not hasattr(leaf, "nbytes"):
                continue
            key = id(leaf)
            try:
                ref = weakref.ref(leaf, lambda _r, _k=key: self._disown(_k))
            except TypeError:
                continue  # not weakref-able (e.g. plain numpy scalar)
            with self._lock:
                self._owned[key] = (ref, subsystem)

    def _disown(self, key: int) -> None:
        with self._lock:
            self._owned.pop(key, None)

    def owner_of(self, arr) -> Optional[str]:
        with self._lock:
            entry = self._owned.get(id(arr))
        if entry is None:
            return None
        ref, subsystem = entry
        return subsystem if ref() is arr else None

    # -- snapshots ---------------------------------------------------------
    def _collect(self) -> Dict[str, int]:
        """Merged claimed-bytes map: pushed values + polled providers +
        the built-in sources (fusion slabs, serve KV, program caches,
        host RSS). Providers run outside the lock."""
        with self._lock:
            claimed = dict(self._claimed)
            providers = list(self._providers.items())
        for name, fn in providers:
            try:
                claimed[name] = int(fn())
            except Exception:
                pass  # a dying subsystem must not break accounting
        try:
            from horovod_tpu.runtime import fusion_buffer

            for purpose, rec in fusion_buffer.bytes_by_purpose().items():
                claimed[purpose] = int(rec["allocated_bytes"])
        except Exception:
            pass
        try:
            from horovod_tpu.serve import kv_cache

            claimed["serve_kv"] = int(kv_cache.total_cache_bytes())
        except Exception:
            pass
        try:
            from horovod_tpu.serve import paging

            claimed["kv_pages"] = int(paging.total_pool_bytes())
        except Exception:
            pass
        try:
            from horovod_tpu.runtime import executor as executor_mod

            claimed["program_cache"] = int(
                executor_mod.program_cache_bytes())
        except Exception:
            pass
        claimed["host_rss"] = host_rss_bytes()
        # fold polled values back through the peak/gauge bookkeeping
        for name, nbytes in claimed.items():
            self.set_bytes(name, nbytes)
        return claimed

    def ledger(self) -> dict:
        """The per-subsystem ledger + device truth + drift — the payload
        of the flight-recorder ``memory`` state provider, so every dump
        carries it."""
        claimed = self._collect()
        device = device_memory_stats()
        actual = int(device.get("bytes_in_use", 0)) or live_array_bytes()
        claimed_device = sum(claimed.get(s, 0) for s in DEVICE_SUBSYSTEMS)
        drift = None
        if actual > 0:
            drift = (actual - claimed_device) / actual
            _DRIFT.set(round(drift, 6))
        _DEVICE_BYTES.set(actual)
        if device.get("peak_bytes_in_use"):
            _DEVICE_PEAK.set(int(device["peak_bytes_in_use"]))
        if device.get("bytes_limit"):
            _DEVICE_LIMIT.set(int(device["bytes_limit"]))
        _HOST_RSS.set(claimed.get("host_rss", 0))
        with self._lock:
            peaks = dict(self._peaks)
            last_oom = self._last_oom
        return {
            "rank": self.rank,
            "wall_time": time.time(),
            "subsystems": {
                name: {"bytes": nbytes, "peak_bytes": peaks.get(name, nbytes)}
                for name, nbytes in sorted(claimed.items())},
            "total_claimed_bytes": sum(claimed.values())
            - claimed.get("host_rss", 0),
            "claimed_device_bytes": claimed_device,
            "device": {
                "bytes_in_use": int(device.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(device.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(device.get("bytes_limit", 0)),
                "live_array_bytes": (actual if not device.get("bytes_in_use")
                                     else live_array_bytes()),
            },
            "reconcile_drift_ratio": drift,
            "last_oom": last_oom,
        }

    def top_live_arrays(self, k: Optional[int] = None) -> List[dict]:
        """The top-k live jax arrays by size, with shape/dtype/owner —
        the forensic core of an OOM dump."""
        k = self.topk if k is None else int(k)
        try:
            import jax

            arrays = list(jax.live_arrays())
        except Exception:
            return []
        arrays.sort(key=lambda a: int(getattr(a, "nbytes", 0) or 0),
                    reverse=True)
        out = []
        for a in arrays[:k]:
            out.append({
                "bytes": int(getattr(a, "nbytes", 0) or 0),
                "shape": list(getattr(a, "shape", ())),
                "dtype": str(getattr(a, "dtype", "?")),
                "owner": self.owner_of(a) or "unattributed",
            })
        return out

    def peak_hbm_bytes(self) -> int:
        """High watermark for the profiler's per-step breakdown: device
        peak_bytes_in_use where reported, the claimed-total watermark
        otherwise (CPU backend)."""
        device = device_memory_stats()
        if device.get("peak_bytes_in_use"):
            return int(device["peak_bytes_in_use"])
        with self._lock:
            return sum(v for k, v in self._peaks.items()
                       if k in DEVICE_SUBSYSTEMS)

    def samples(self) -> List[list]:
        """The sampler's ring: [wall_time, claimed_device, actual_device]
        rows — the merged-trace memory counter track reads this."""
        with self._lock:
            return [list(s) for s in self._samples]

    # -- sampler -----------------------------------------------------------
    def sample(self) -> dict:
        """One reconciliation sweep; appends to the sample ring."""
        led = self.ledger()
        with self._lock:
            self._samples.append((led["wall_time"],
                                  led["claimed_device_bytes"],
                                  led["device"]["bytes_in_use"]
                                  or led["device"]["live_array_bytes"]))
        _SAMPLES.inc()
        return led

    def start(self, interval: Optional[float] = None) -> None:
        """Start the sampling thread (idempotent)."""
        if interval is not None:
            self.sample_seconds = float(interval)
        with self._lock:
            if self._sampler is not None and self._sampler.is_alive():
                return
            self._stop.clear()
            self._sampler = threading.Thread(
                target=self._sample_loop, daemon=True, name="hvd-memory")
            self._sampler.start()

    def stop(self) -> None:
        with self._lock:
            sampler, self._sampler = self._sampler, None
        self._stop.set()
        if sampler is not None:
            sampler.join(timeout=5.0)

    def _sample_loop(self) -> None:
        while not self._stop.wait(self.sample_seconds):
            try:
                self.sample()
            except Exception:  # the sampler must never kill the process
                pass

    # -- OOM forensics -----------------------------------------------------
    def record_oom(self, exc: Exception, where: str) -> dict:
        """Turn a RESOURCE_EXHAUSTED into forensics: ledger + top-k live
        arrays + dominant subsystem, stored on the tracker (so the
        flight-recorder ``memory`` provider embeds it in the dump that
        follows) and emitted as a flight event."""
        _OOMS.inc()
        try:
            led = self.ledger()
        except Exception:
            led = {"subsystems": {}}
        top = self.top_live_arrays()
        subsystems = led.get("subsystems", {})
        dominant = None
        if subsystems:
            dominant = max(
                (s for s in subsystems if s != "host_rss"),
                key=lambda s: subsystems[s]["bytes"], default=None)
        forensics = {
            "where": where,
            "error": str(exc)[:2000],
            "wall_time": time.time(),
            "dominant_subsystem": dominant,
            "top_live_arrays": top,
            "subsystems": subsystems,
        }
        with self._lock:
            self._last_oom = forensics
        from horovod_tpu import flight_recorder

        flight_recorder.emit(
            "oom", where=where, dominant_subsystem=dominant,
            device_bytes_in_use=led.get("device", {}).get("bytes_in_use"),
            error=str(exc)[:200])
        flight_recorder.dump_on_failure("oom")
        return forensics

    def last_oom(self) -> Optional[dict]:
        with self._lock:
            return self._last_oom


_tracker = MemoryTracker()


def tracker() -> MemoryTracker:
    return _tracker


def configure(rank: Optional[int] = None) -> None:
    """Adopt the rank, parse the ``HOROVOD_MEMORY_*`` knobs, register the
    flight-recorder ``memory`` state provider, and start the sampler.
    Called from ``hvd.init()`` (idempotent across elastic re-inits)."""
    t = _tracker
    if rank is not None:
        t.rank = int(rank)
    t.enabled = _get_bool(HOROVOD_MEMORY, True)
    t.sample_seconds = _get_float(HOROVOD_MEMORY_SAMPLE_SECONDS,
                                  DEFAULT_SAMPLE_SECONDS)
    t.topk = _get_int(HOROVOD_MEMORY_TOPK, DEFAULT_TOPK)
    from horovod_tpu import flight_recorder

    if t.enabled:
        flight_recorder.set_state_provider("memory", t.ledger)
        t.start()
    else:
        flight_recorder.set_state_provider("memory", None)
        t.stop()


def memory_state() -> dict:
    """Document for the metrics server's ``GET /memory`` route: the
    ledger + top live arrays + the recent sample trail."""
    t = _tracker
    state = t.ledger()
    state["top_live_arrays"] = t.top_live_arrays()
    state["samples"] = t.samples()[-64:]
    state["sample_seconds"] = t.sample_seconds
    return state


# -- OOM detection -----------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM when allocating")


def is_oom(exc: BaseException) -> bool:
    """True for XLA allocator exhaustion (``XlaRuntimeError`` with
    RESOURCE_EXHAUSTED, or any allocator OOM text — the message is the
    only stable contract across jaxlib versions)."""
    if exc is None:
        return False
    if type(exc).__name__ == "XlaRuntimeError":
        return any(m in str(exc) for m in _OOM_MARKERS)
    return any(m in str(exc) for m in _OOM_MARKERS[:1]) or \
        "MemoryError" == type(exc).__name__


def maybe_record_oom(exc: BaseException, where: str) -> bool:
    """The executor/elastic boundary hook: one call, no-op unless the
    exception is an OOM. Never raises (runs on failing paths)."""
    try:
        if not is_oom(exc):
            return False
        _tracker.record_oom(exc, where)
        return True
    except Exception:
        return False


# -- cross-rank postmortem ----------------------------------------------------

def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return ("%.1f %s" % (n, unit)) if unit != "B" \
                else ("%d B" % int(n))
        n /= 1024.0
    return "%d B" % int(n)


def format_memory_report(dumps: List[dict]) -> str:
    """Cross-rank memory report from flight-recorder dumps' ``memory``
    state: per-rank claimed/actual bytes, the dominant subsystem across
    the fleet, and the rank nearest its HBM ceiling. Empty string when no
    dump carries a memory ledger (pre-PR-13 dumps)."""
    ranks = []
    for d in dumps:
        mem = (d.get("state") or {}).get("memory")
        if not isinstance(mem, dict):
            continue
        ranks.append((d.get("launch_rank", d.get("rank", "?")), mem))
    if not ranks:
        return ""
    lines = ["=== memory report (%d rank%s) ==="
             % (len(ranks), "" if len(ranks) == 1 else "s")]
    totals: Dict[str, int] = {}
    nearest = None  # (rank, headroom_ratio, in_use, limit)
    for rank, mem in sorted(ranks, key=lambda r: str(r[0])):
        subs = mem.get("subsystems", {})
        for name, rec in subs.items():
            if name == "host_rss":
                continue
            totals[name] = totals.get(name, 0) + int(rec.get("bytes", 0))
        device = mem.get("device", {})
        in_use = int(device.get("bytes_in_use", 0)) \
            or int(device.get("live_array_bytes", 0))
        limit = int(device.get("bytes_limit", 0))
        ratio = (in_use / limit) if limit else None
        drift = mem.get("reconcile_drift_ratio")
        top = ", ".join(
            "%s=%s" % (n, _fmt_bytes(r.get("bytes", 0)))
            for n, r in sorted(subs.items(),
                               key=lambda kv: -int(kv[1].get("bytes", 0)))
            if n != "host_rss")[:200]
        lines.append(
            "rank %s: device %s in use%s, host rss %s%s%s" % (
                rank, _fmt_bytes(in_use),
                (" / %s limit (%.1f%%)" % (_fmt_bytes(limit),
                                           100.0 * ratio))
                if ratio is not None else "",
                _fmt_bytes(subs.get("host_rss", {}).get("bytes", 0)),
                ("  drift=%+.1f%%" % (100.0 * drift))
                if isinstance(drift, (int, float)) else "",
                ("  [%s]" % top) if top else ""))
        oom = mem.get("last_oom")
        if isinstance(oom, dict):
            lines.append(
                "rank %s: OOM at %s — dominant subsystem %s" % (
                    rank, oom.get("where", "?"),
                    oom.get("dominant_subsystem", "?")))
            for arr in (oom.get("top_live_arrays") or ())[:3]:
                lines.append(
                    "    live array %s %s %s (%s)" % (
                        _fmt_bytes(arr.get("bytes", 0)),
                        tuple(arr.get("shape", ())),
                        arr.get("dtype", "?"),
                        arr.get("owner", "unattributed")))
        key = ratio if ratio is not None else float(in_use)
        if nearest is None or key > nearest[1]:
            nearest = (rank, key, in_use, limit)
    if totals:
        dominant = max(totals, key=lambda k: totals[k])
        lines.append("dominant subsystem: %s (%s across %d rank%s)"
                     % (dominant, _fmt_bytes(totals[dominant]), len(ranks),
                        "" if len(ranks) == 1 else "s"))
    if nearest is not None:
        rank, _key, in_use, limit = nearest
        lines.append(
            "nearest HBM ceiling: rank %s (%s in use%s)" % (
                rank, _fmt_bytes(in_use),
                (" of %s, %.1f%% full" % (_fmt_bytes(limit),
                                          100.0 * in_use / limit))
                if limit else ""))
    return "\n".join(lines)
