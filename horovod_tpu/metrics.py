"""Process-wide runtime metrics: counters, gauges and fixed-bucket
histograms with Prometheus exposition.

The quantitative observability plane next to the Chrome-trace timeline
(timeline.py) and the stall inspector (stall.py): where the timeline
answers "what happened to tensor X at time T", this answers "what is my
cache hit rate, fusion-buffer utilization, cycle latency distribution and
allreduce bytes/sec right now" — the layer the reference leaves to
external profilers but a production deployment needs for autotuning,
capacity planning and alerting.

Design constraints:

* **Lock-cheap hot path.** Observations are plain int/float/dict updates
  (a counter ``inc`` is one integer add; a histogram ``observe`` is a
  bisect + two adds). Under CPython these are effectively atomic enough
  for monitoring data — a vanishingly rare lost increment is acceptable,
  a lock on every enqueued tensor is not. Locks guard only metric
  *creation* and snapshot iteration.
* **Zero cost when idle.** No thread, socket or file exists unless
  ``HOROVOD_METRICS_PORT`` / ``HOROVOD_METRICS_DUMP`` ask for one.

Four consumers (wired in core/basics.py, runtime/runtime.py, run/run.py):

* ``hvd.metrics()`` — JSON-serializable nested snapshot dict;
* ``HOROVOD_METRICS_PORT`` — Prometheus text format over stdlib
  ``http.server`` on a daemon thread, ``GET /metrics``;
* Chrome-trace ``"C"`` counter events emitted through the Timeline writer
  each cycle (same epoch clock domain as the per-tensor trace);
* ``HOROVOD_METRICS_DUMP`` + ``tpurun --metrics-summary`` — per-rank JSON
  dumps at shutdown, aggregated into a cross-rank min/median/max table.
"""

from __future__ import annotations

import json
import os
import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

# Default latency buckets (seconds): 100us .. 10s, roughly log-spaced.
LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0)
# Count buckets (tensors per cycle and similar small cardinalities).
COUNT_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)
# Ratio buckets (utilization in [0, 1]; >1 spills to +Inf).
RATIO_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
# Elastic commit buckets (seconds): a commit is a host-side snapshot of the
# full model, so the interesting range sits well above collective latency —
# 1ms .. 60s.
COMMIT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                  10.0, 30.0, 60.0)
# Durable-checkpoint commit buckets (seconds): serialization + disk +
# the cross-rank barrier, so the tail stretches past COMMIT_BUCKETS —
# a slow shared filesystem or a barrier riding a KV outage can
# legitimately take minutes without being an anomaly.
CKPT_COMMIT_BUCKETS = (0.001, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                       10.0, 30.0, 60.0, 120.0, 300.0)


# Every route the HTTP endpoint serves, with a one-line description —
# the payload of GET / and GET /debug/routes, so tooling (hvd_top)
# discovers which panels this endpoint can back instead of probing 404s.
HTTP_ROUTES: Dict[str, str] = {
    "/metrics": "Prometheus text exposition of every registered family",
    "/debug": "flight-recorder ring events, in-flight ops, metrics",
    "/debug/routes": "this route index",
    "/serve": "serving-plane replica sets, queue depths, cache warmth",
    "/profile": "step-profiler phase breakdowns and summary",
    "/memory": "memory-plane ledger: live bytes, watermarks, drift",
    "/comms": "collective-transport busbw vs roofline per lane",
    "/slo": "SLO burn rates, latency percentiles, slow exemplars",
    "/goodput": "goodput ledger: productive vs badput, incidents",
    "/healthz": "readiness gate (200 once init ran / replica alive)",
}


def route_index() -> dict:
    """The JSON document served at ``GET /`` and ``/debug/routes``."""
    return {"routes": dict(HTTP_ROUTES)}


class Counter:
    """Monotonic counter; ``inc`` is the whole hot path."""

    kind = "counter"
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: float = 1) -> None:
        self.value += n

    def snapshot(self):
        return self.value


class Gauge:
    """Point-in-time value (queue depth, buffer fill, ...)."""

    kind = "gauge"
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1) -> None:
        self.value += n

    def dec(self, n: float = 1) -> None:
        self.value -= n

    def snapshot(self):
        return self.value


class Histogram:
    """Fixed-bucket histogram with Prometheus ``le`` semantics: bucket i
    counts observations ``v <= bounds[i]``; an implicit +Inf bucket
    catches the rest. Exposition renders the counts cumulatively."""

    kind = "histogram"
    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float]) -> None:
        self.bounds: Tuple[float, ...] = tuple(sorted(buckets))
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def snapshot(self):
        cum = 0
        buckets = []
        for bound, c in zip(self.bounds, self.counts):
            cum += c
            buckets.append([bound, cum])
        buckets.append(["+Inf", cum + self.counts[-1]])
        return {"count": self.count, "sum": self.sum, "buckets": buckets}


class _Family:
    """One named metric family; holds one child per label-value set (the
    empty set for unlabeled metrics). Child creation is locked; child
    lookup on the hot path is a plain dict get."""

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...],
                 factory) -> None:
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._factory = factory
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()
        if not labelnames:
            self._children[()] = factory()

    @property
    def kind(self) -> str:
        with self._lock:
            if self._children:
                return next(iter(self._children.values())).kind
        return self._factory().kind

    def labels(self, **labelvalues):
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._factory())
        return child

    # unlabeled convenience: family proxies its single child
    def inc(self, n: float = 1) -> None:
        self._children[()].inc(n)

    def set(self, v: float) -> None:
        self._children[()].set(v)

    def dec(self, n: float = 1) -> None:
        self._children[()].dec(n)

    def observe(self, v: float) -> None:
        self._children[()].observe(v)

    @property
    def value(self):
        return self._children[()].value

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Create-once registry of metric families + the optional HTTP
    exposition endpoint. One process-wide instance (``registry()``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: "Dict[str, _Family]" = {}
        self._http: Optional[tuple] = None  # (server, thread)

    # -- metric creation (idempotent by name) ------------------------------
    def _family(self, name: str, help: str, labelnames, factory) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, help, tuple(labelnames), factory)
                self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, help, labelnames, Counter)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, help, labelnames, Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS,
                  labelnames: Sequence[str] = ()) -> _Family:
        b = tuple(buckets)
        return self._family(name, help, labelnames, lambda: Histogram(b))

    # -- snapshot ----------------------------------------------------------
    def snapshot(self) -> dict:
        """Nested JSON-serializable dict of every family and child."""
        with self._lock:
            families = list(self._families.values())
        out = {}
        for fam in families:
            values = []
            for key, child in fam.children():
                values.append({
                    "labels": dict(zip(fam.labelnames, key)),
                    "value": child.snapshot(),
                })
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "values": values}
        return out

    # -- Prometheus exposition --------------------------------------------
    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            children = fam.children()
            if not children:
                continue
            kind = children[0][1].kind
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {kind}")
            for key, child in children:
                labels = list(zip(fam.labelnames, key))
                if kind == "histogram":
                    snap = child.snapshot()
                    for bound, cum in snap["buckets"]:
                        le = bound if isinstance(bound, str) \
                            else _fmt_value(bound)
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_fmt_labels(labels + [('le', le)])} {cum}")
                    lines.append(f"{fam.name}_sum{_fmt_labels(labels)} "
                                 f"{_fmt_value(snap['sum'])}")
                    lines.append(f"{fam.name}_count{_fmt_labels(labels)} "
                                 f"{snap['count']}")
                else:
                    lines.append(f"{fam.name}{_fmt_labels(labels)} "
                                 f"{_fmt_value(child.value)}")
        return "\n".join(lines) + "\n"

    # -- HTTP endpoint (HOROVOD_METRICS_PORT) ------------------------------
    def serve(self, port: int) -> int:
        """Start (or return) the /metrics endpoint on a daemon thread;
        returns the bound port (useful with port 0)."""
        with self._lock:
            if self._http is not None:
                return self._http[0].server_address[1]
        import http.server

        reg = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                route = self.path.split("?")[0].rstrip("/")
                if route == "/metrics":
                    body = reg.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route in ("", "/debug/routes"):
                    # route index: which surfaces THIS endpoint serves,
                    # so tooling (hvd_top) discovers panels instead of
                    # hardcoding them — the bare root used to 404
                    body = json.dumps(route_index(), default=repr).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route == "/debug":
                    # flight-recorder debug state: ring-buffer events,
                    # in-flight ops and the metrics snapshot, as JSON
                    from horovod_tpu import flight_recorder

                    body = json.dumps(
                        flight_recorder.debug_state(),
                        default=repr).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route == "/serve":
                    # serving-plane status: live replica sets, queue
                    # depths, program-cache warmth (serve.serve_state)
                    from horovod_tpu.serve import api as serve_api

                    body = json.dumps(
                        serve_api.serve_state(),
                        default=repr).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route == "/profile":
                    # step-profiler state: the last N per-step phase
                    # breakdowns + summary (rate-limited snapshot, see
                    # profiler.profile_state)
                    from horovod_tpu import profiler

                    body = json.dumps(
                        profiler.profile_state(),
                        default=repr).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route == "/memory":
                    # memory-plane ledger: per-subsystem live bytes +
                    # watermarks, device truth, drift, top live arrays
                    # (memory.memory_state; docs/memory.md)
                    from horovod_tpu import memory

                    body = json.dumps(
                        memory.memory_state(),
                        default=repr).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route == "/comms":
                    # collective-transport ledger: per-lane busbw vs
                    # roofline, per-(op,lane,bucket) windows, degradation
                    # state (comms.comms_state; docs/comms.md)
                    from horovod_tpu import comms

                    body = json.dumps(
                        comms.comms_state(),
                        default=repr).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route == "/slo":
                    # SLO plane: per-objective burn rate / error budget,
                    # latency percentiles, slow-request exemplars
                    # (tracing.slo_state; docs/tracing.md)
                    from horovod_tpu import tracing

                    body = json.dumps(
                        tracing.slo_state(),
                        default=repr).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route == "/goodput":
                    # goodput ledger: wall-clock partition into
                    # productive vs badput categories, incident records
                    # (goodput.goodput_state; docs/goodput.md)
                    from horovod_tpu import goodput

                    body = json.dumps(
                        goodput.goodput_state(),
                        default=repr).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route == "/healthz":
                    # readiness gate for external load balancers: 200
                    # only once hvd.init() ran and — when serving — a
                    # replica proved alive (tracing.healthz_state)
                    from horovod_tpu import tracing

                    state = tracing.healthz_state()
                    body = json.dumps(state).encode()
                    self.send_response(200 if state["ready"] else 503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def log_message(self, *args):  # keep worker logs clean
                pass

        server = http.server.ThreadingHTTPServer(("", port), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True,
                                  name="hvd-metrics-http")
        thread.start()
        with self._lock:
            self._http = (server, thread)
        return server.server_address[1]

    @property
    def http_port(self) -> Optional[int]:
        with self._lock:
            return None if self._http is None \
                else self._http[0].server_address[1]

    def stop_server(self) -> None:
        with self._lock:
            http, self._http = self._http, None
        if http is not None:
            server, thread = http
            server.shutdown()
            server.server_close()
            thread.join(timeout=5.0)

    # -- per-rank dump (HOROVOD_METRICS_DUMP) ------------------------------
    def dump(self, path: str, rank: int = 0) -> str:
        """Write this rank's snapshot as JSON. ``path`` may contain a
        ``{rank}`` placeholder or name a ``.json`` file directly; anything
        else is treated as a directory receiving
        ``metrics-rank-<rank>.json``. Returns the written path."""
        if "{rank}" in path:
            out = path.format(rank=rank)
        elif path.endswith(".json"):
            out = path
        else:
            os.makedirs(path, exist_ok=True)
            out = os.path.join(path, f"metrics-rank-{rank}.json")
        parent = os.path.dirname(out)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(out, "w") as f:
            json.dump({"rank": rank, "metrics": self.snapshot()}, f)
        return out

    def reset(self) -> None:
        """Drop every family (tests only — production counters are
        cumulative for the life of the process)."""
        self.stop_server()
        with self._lock:
            self._families.clear()


def _fmt_labels(pairs: List[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in pairs)
    return "{" + inner + "}"


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_value(v) -> str:
    if isinstance(v, int):
        return str(v)
    return format(float(v), ".10g")


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry


# ---------------------------------------------------------------------------
# Cross-rank aggregation (tpurun --metrics-summary)
# ---------------------------------------------------------------------------

def flatten_snapshot(snap: dict) -> Dict[str, float]:
    """Scalar leaves of a snapshot: counters/gauges become
    ``name{labels}``; histograms contribute ``.count``/``.sum``/``.mean``."""
    flat: Dict[str, float] = {}
    for name, fam in snap.items():
        for entry in fam.get("values", []):
            labels = entry.get("labels") or {}
            key = name
            if labels:
                inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                key = f"{name}{{{inner}}}"
            v = entry.get("value")
            if isinstance(v, dict):  # histogram
                count, total = v.get("count", 0), v.get("sum", 0.0)
                flat[key + ".count"] = count
                flat[key + ".sum"] = total
                if count:
                    flat[key + ".mean"] = total / count
            elif isinstance(v, (int, float)):
                flat[key] = v
    return flat


def summarize_dumps(paths: Sequence[str]) -> List[tuple]:
    """Aggregate per-rank JSON dumps into (metric, min, median, max) rows,
    sorted by metric name. A metric missing from some ranks aggregates
    over the ranks that reported it."""
    import statistics

    per_rank = []
    for p in paths:
        with open(p) as f:
            data = json.load(f)
        per_rank.append(flatten_snapshot(data.get("metrics", data)))
    keys = sorted(set().union(*per_rank)) if per_rank else []
    rows = []
    for k in keys:
        vals = [flat[k] for flat in per_rank if k in flat]
        rows.append((k, min(vals), statistics.median(vals), max(vals)))
    return rows


def format_summary(rows: List[tuple], n_ranks: int) -> str:
    """Render summarize_dumps rows as an aligned min/median/max table."""
    header = ("metric", "min", "median", "max")
    body = [(name, _fmt_value(lo), _fmt_value(mid), _fmt_value(hi))
            for name, lo, mid, hi in rows]
    width0 = max([len(header[0])] + [len(r[0]) for r in body])
    widths = [max([len(header[i])] + [len(r[i]) for r in body])
              for i in (1, 2, 3)]
    lines = [f"cross-rank metrics summary ({n_ranks} rank"
             f"{'s' if n_ranks != 1 else ''})"]
    fmt = "{:<%d}  {:>%d}  {:>%d}  {:>%d}" % (width0, *widths)
    lines.append(fmt.format(*header))
    lines.extend(fmt.format(*r) for r in body)
    return "\n".join(lines)
