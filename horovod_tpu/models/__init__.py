"""Model zoo for the acceptance workloads (SURVEY.md §2.8, BASELINE.md).

All flax, all TPU-first: NHWC convs / flash-attention transformers,
bfloat16 compute with float32 parameters.
"""

from horovod_tpu.models.mnist import MnistConvNet
from horovod_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from horovod_tpu.models.vgg import VGG, VGG11, VGG13, VGG16, VGG19
from horovod_tpu.models.inception import InceptionV3
from horovod_tpu.models import moe
from horovod_tpu.models.transformer import (
    BertBase,
    BertLarge,
    GPT2Medium,
    GPT2Small,
    Transformer,
    causal_lm_loss,
    masked_lm_loss,
    random_tokens,
)

__all__ = [
    "MnistConvNet",
    "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101", "ResNet152",
    "VGG", "VGG11", "VGG13", "VGG16", "VGG19", "InceptionV3", "moe",
    "Transformer", "BertBase", "BertLarge", "GPT2Small", "GPT2Medium",
    "causal_lm_loss", "masked_lm_loss", "random_tokens",
]
