"""Inception V3 (flax), TPU-first.

The reference's other headline scaling model (reference:
docs/benchmarks.rst:13-14 — 90% efficiency at 512 GPUs). Fresh
implementation of the standard Inception-V3 topology (stem + A/B/C/D/E
mixed blocks), NHWC, bf16 compute / f32 params. Canonical input is
(299, 299, 3).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from horovod_tpu.ops.pallas.conv_bn_act import FusedBatchNormAct


class ConvBN(nn.Module):
    features: int
    kernel: Sequence[int] = (3, 3)
    strides: Sequence[int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16
    fused: bool = True  # fused BN+ReLU epilogue (same variables/math)

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, tuple(self.kernel),
                    strides=tuple(self.strides), padding=self.padding,
                    use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        if self.fused:
            return FusedBatchNormAct(momentum=0.9, epsilon=1e-3,
                                     dtype=self.dtype,
                                     name="BatchNorm_0")(
                x, use_running_average=not train)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         param_dtype=jnp.float32)(x)
        return nn.relu(x)


class SpaceToDepthStem(nn.Module):
    """Inception's 3x3/2 VALID stem conv on (299,299,3), reparametrized
    for the MXU like ResNet's (models/resnet.py SpaceToDepthConvInit,
    tools/conv0_s2d.py): pad the 299 image one row/col at the END to
    300, 2x2 space-to-depth to (150,150,12), and fold the 3x3 stride-2
    kernel into a 2x2 stride-1 kernel over 12 channels — output is the
    identical 149x149x32 (the folded tap that would read the padded
    row/col carries a zero weight), with 4x the contraction depth per
    MXU pass. The parameter KEEPS the canonical (3,3,3,filters) shape so
    checkpoints interchange with the direct stem."""

    filters: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        w3 = self.param("kernel", nn.initializers.he_normal(),
                        (3, 3, 3, self.filters), jnp.float32)
        # fold: pad to (4,4) at the END, then
        # w2[t,s, 6a+3b+c] = w3[2t+a, 2s+b, c] (u=3 / v=3 taps are zero)
        w4 = jnp.pad(w3, ((0, 1), (0, 1), (0, 0), (0, 0)))
        w2 = w4.reshape(2, 2, 2, 2, 3, self.filters) \
            .transpose(0, 2, 1, 3, 4, 5).reshape(2, 2, 12, self.filters)
        n, h, w, c = x.shape
        if h % 2 or w % 2:  # canonical 299: one zero row/col at the end
            x = jnp.pad(x, ((0, 0), (0, h % 2), (0, w % 2), (0, 0)))
            n, h, w, c = x.shape
        y = x.reshape(n, h // 2, 2, w // 2, 2, c) \
            .transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
        return jax.lax.conv_general_dilated(
            y.astype(self.dtype), w2.astype(self.dtype), (1, 1),
            "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(64, (1, 1))(x, train)
        b2 = c(64, (5, 5))(c(48, (1, 1))(x, train), train)
        b3 = c(96, (3, 3))(c(96, (3, 3))(c(64, (1, 1))(x, train), train),
                           train)
        b4 = c(self.pool_features, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = c(96, (3, 3), strides=(2, 2), padding="VALID")(
            c(96, (3, 3))(c(64, (1, 1))(x, train), train), train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = partial(ConvBN, dtype=self.dtype)
        c7 = self.channels_7x7
        b1 = c(192, (1, 1))(x, train)
        b2 = c(192, (7, 1))(c(c7, (1, 7))(c(c7, (1, 1))(x, train), train),
                            train)
        b3 = x
        for k, f in (((1, 1), c7), ((7, 1), c7), ((1, 7), c7),
                     ((7, 1), c7), ((1, 7), 192)):
            b3 = c(f, k)(b3, train)
        b4 = c(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (3, 3), strides=(2, 2), padding="VALID")(
            c(192, (1, 1))(x, train), train)
        b2 = c(192, (1, 1))(x, train)
        b2 = c(192, (1, 7))(b2, train)
        b2 = c(192, (7, 1))(b2, train)
        b2 = c(192, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (1, 1))(x, train)
        b2 = c(384, (1, 1))(x, train)
        b2 = jnp.concatenate([c(384, (1, 3))(b2, train),
                              c(384, (3, 1))(b2, train)], axis=-1)
        b3 = c(384, (3, 3))(c(448, (1, 1))(x, train), train)
        b3 = jnp.concatenate([c(384, (1, 3))(b3, train),
                              c(384, (3, 1))(b3, train)], axis=-1)
        b4 = c(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    space_to_depth: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # stem
        if self.space_to_depth and x.shape[1] >= 4 and x.shape[3] == 3:
            x = SpaceToDepthStem(32, self.dtype)(x)
            x = FusedBatchNormAct(momentum=0.9, epsilon=1e-3,
                                  dtype=self.dtype)(
                x, use_running_average=not train)
        else:
            x = c(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = c(32, (3, 3), padding="VALID")(x, train)
        x = c(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = c(80, (1, 1), padding="VALID")(x, train)
        x = c(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # mixed blocks
        x = InceptionA(32, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionB(dtype=self.dtype)(x, train)
        x = InceptionC(128, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        x = InceptionC(192, dtype=self.dtype)(x, train)
        x = InceptionD(dtype=self.dtype)(x, train)
        x = InceptionE(dtype=self.dtype)(x, train)
        x = InceptionE(dtype=self.dtype)(x, train)
        # head
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="classifier")(x)
        return x.astype(jnp.float32)
