"""Small MNIST convnet — the smoke-test workload.

Analogue of the reference's MNIST examples (reference:
examples/pytorch_mnist.py Net, examples/tensorflow_mnist.py conv_model):
the first BASELINE config is a 1-process CPU allreduce smoke test on this
model.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistConvNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
