"""Mixture-of-experts language model (Switch-style), expert-parallel.

Acceptance workload for the EP extension (the reference has no MoE or
expert parallelism — SURVEY.md §2.4): a causal transformer LM whose MLP
blocks are Switch MoE layers with experts sharded one-per-device over a
mesh axis. Runs inside ``shard_map``: the EP axis doubles as the data
axis (each device holds its own token batch); attention/embedding params
are replicated (their gradients arrive pre-averaged through the pmean'd
loss), expert weights are per-device (each device trains only its own
expert — no cross-device averaging of expert gradients).

Functional-style (plain param pytrees, pure apply) because the expert
leading axis is a shard_map in_spec, which flax module trees don't
express naturally.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.ops.pallas.flash_attention import flash_attention
from horovod_tpu.parallel import ep as ep_mod
from horovod_tpu.parallel._util import stack_stage_params


def init_moe_lm(rng: np.random.RandomState, *, vocab_size: int,
                d_model: int, num_layers: int, num_heads: int, d_ff: int,
                n_experts: int, max_seq: int) -> dict:
    """Parameter pytree. ``experts`` subtrees carry a leading
    ``n_experts`` axis — shard it over the EP mesh axis with
    ``P(axis)``; everything in ``shared`` replicates (``P()``)."""

    def dense(n_in, n_out, scale=None):
        scale = scale or 1.0 / math.sqrt(n_in)
        return jnp.asarray(rng.randn(n_in, n_out).astype(np.float32)
                           * scale)

    shared = {
        "token_embed": jnp.asarray(
            rng.randn(vocab_size, d_model).astype(np.float32) * 0.02),
        "pos_embed": jnp.asarray(
            rng.randn(max_seq, d_model).astype(np.float32) * 0.02),
        "layers": [],
    }
    experts = {"layers": []}
    for _ in range(num_layers):
        shared["layers"].append({
            "ln1": {"scale": jnp.ones((d_model,)),
                    "bias": jnp.zeros((d_model,))},
            "ln2": {"scale": jnp.ones((d_model,)),
                    "bias": jnp.zeros((d_model,))},
            "wq": dense(d_model, d_model),
            "wk": dense(d_model, d_model),
            "wv": dense(d_model, d_model),
            "wo": dense(d_model, d_model),
            "gate": dense(d_model, n_experts, scale=0.02),
        })
        experts["layers"].append(stack_stage_params([
            {"wi": dense(d_model, d_ff), "wo": dense(d_ff, d_model)}
            for _ in range(n_experts)]))
    shared["final_ln"] = {"scale": jnp.ones((d_model,)),
                          "bias": jnp.zeros((d_model,))}
    return {"shared": shared, "experts": experts}


def _layer_norm(p, x):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-6) * p["scale"] + p["bias"]


def _attention(lp, x, num_heads):
    b, s, d = x.shape
    hd = d // num_heads

    def heads(w):
        return (x @ w).reshape(b, s, num_heads, hd).transpose(0, 2, 1, 3)

    o = flash_attention(heads(lp["wq"]), heads(lp["wk"]), heads(lp["wv"]),
                        causal=True)
    return o.transpose(0, 2, 1, 3).reshape(b, s, d) @ lp["wo"]


def _expert_fn(p, h):
    return jax.nn.gelu(h @ p["wi"]) @ p["wo"]


def apply_moe_lm(params: dict, tokens, axis_name: str, capacity: int,
                 *, num_heads: int) -> Tuple[jax.Array, jax.Array]:
    """Forward pass inside ``shard_map``; ``tokens`` is this device's
    (batch, seq) shard, ``num_heads`` the static head count used at init.
    Returns (logits, mean auxiliary load-balance loss)."""
    shared = params["shared"]
    b, s = tokens.shape
    x = shared["token_embed"][tokens] + shared["pos_embed"][None, :s, :]

    aux_total = 0.0
    for lp, xp in zip(shared["layers"], params["experts"]["layers"]):
        x = x + _attention(lp, _layer_norm(lp["ln1"], x), num_heads)
        h = _layer_norm(lp["ln2"], x)
        flat = h.reshape(b * s, -1)
        y, probs = ep_mod.switch_moe(
            flat, flat @ lp["gate"], _expert_fn, xp, axis_name, capacity)
        aux_total = aux_total + ep_mod.load_balance_loss(
            probs, axis_name=axis_name)
        x = x + y.reshape(b, s, -1)

    x = _layer_norm(shared["final_ln"], x)
    logits = x @ shared["token_embed"].T
    n_layers = len(shared["layers"])
    return logits, aux_total / n_layers


def moe_lm_loss(params, tokens, axis_name: str, capacity: int, *,
                num_heads: int, aux_weight: float = 0.01):
    """Next-token loss + auxiliary balance loss, averaged over the EP/data
    axis (inside shard_map)."""
    import optax

    logits, aux = apply_moe_lm(params, tokens, axis_name, capacity,
                               num_heads=num_heads)
    lm = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], tokens[:, 1:]).mean()
    return lax.pmean(lm, axis_name) + aux_weight * aux
