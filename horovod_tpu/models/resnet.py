"""ResNet family (flax), TPU-first.

The reference's acceptance workloads are ResNet-50/101 + Inception/VGG CNNs
driven through its synthetic benchmark harness (reference:
examples/pytorch_synthetic_benchmark.py:37-100,
examples/pytorch_imagenet_resnet50.py, docs/benchmarks.rst:13-43). This is a
fresh TPU-native implementation, not a port of any torch model code:

* NHWC layout (TPU-native; XLA convs tile NHWC onto the MXU directly).
* bfloat16 compute / float32 parameters and batch statistics — the MXU's
  native mixed-precision recipe.
* Static shapes everywhere; no Python control flow in the forward pass.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic 3x3+3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)

        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)

        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1-3x3-1x1 bottleneck block (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last norm scale so blocks start as identity
        y = self.norm(scale_init=nn.initializers.zeros)(y)

        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)

        return self.act(residual + y)


class SpaceToDepthConvInit(nn.Module):
    """The 7x7/2 input conv, reparametrized exactly for the MXU: 2x2
    space-to-depth the image to (112,112,12) and fold the 7x7 stride-2
    kernel into a 4x4 stride-1 kernel over 12 channels with asymmetric
    [(2,1),(2,1)] padding — identical output, 4x the contraction depth
    per MXU pass (the classic TPU MLPerf ResNet transform; measured
    1.43x on this layer, tools/conv0_s2d.py). The parameter KEEPS the
    canonical (7,7,3,filters) shape — checkpoints interchange freely
    with the direct path — and the fold is a tiny reshape per step."""

    filters: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        w7 = self.param("kernel", nn.initializers.he_normal(),
                        (7, 7, 3, self.filters), jnp.float32)
        # fold: pad to (8,8), then w4[th,tw, 3*(2uh+uw)+c] =
        # w7[2th+uh-1, 2tw+uw-1, c] (zeros where out of range)
        w8 = jnp.pad(w7, ((1, 0), (1, 0), (0, 0), (0, 0)))
        w4 = w8.reshape(4, 2, 4, 2, 3, self.filters) \
            .transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 12, self.filters)
        n, h, w, c = x.shape
        y = x.reshape(n, h // 2, 2, w // 2, 2, c) \
            .transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
        return jax.lax.conv_general_dilated(
            y.astype(self.dtype), w4.astype(self.dtype), (1, 1),
            [(2, 1), (2, 1)], dimension_numbers=("NHWC", "HWIO", "NHWC"))


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    # exact MXU-friendly reparametrization of the input conv (above);
    # disable to get the textbook direct 7x7/2 convolution
    space_to_depth: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       kernel_init=nn.initializers.he_normal())
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)

        x = x.astype(self.dtype)
        if self.space_to_depth and x.shape[1] % 2 == 0 \
                and x.shape[2] % 2 == 0 and x.shape[3] == 3:
            x = SpaceToDepthConvInit(self.num_filters, self.dtype,
                                     name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm, act=self.act,
                )(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
