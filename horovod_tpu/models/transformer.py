"""Transformer model family (flax), TPU-first: BERT-style encoders and
GPT-style causal decoders.

The reference's BERT acceptance workload is a data-parallel fine-tune whose
distinguishing traffic is large embedding-table gradients on the allgather/
sparse path (BASELINE.md config #5; reference sparse handling:
horovod/tensorflow/__init__.py:64-75 IndexedSlices → allgather). This module
is a fresh TPU-native implementation, not a port of any reference model
code (the reference ships no transformer code at all):

* Attention runs through the Pallas flash kernel (ops/pallas/
  flash_attention.py) — the (seq, seq) score matrix never hits HBM.
* bfloat16 compute / float32 parameters; matmuls sized for the MXU
  (head_dim 64-128, hidden multiples of 128).
* Static shapes; per-layer ``jax.checkpoint`` (remat) optional for long
  sequences.
* Sequence parallelism drops in by swapping the attention function for
  ``ring_attention``/``ulysses_attention`` (parallel/) under ``shard_map``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu.ops.pallas.flash_attention import NEG_INF, flash_attention

Dtype = Any


def cached_attention(q, k, v, q_positions):
    """Masked attention against an absolute-position KV cache.

    ``q``: (batch, heads, new, head_dim) — the new tokens' queries;
    ``k``/``v``: (batch, heads, cache_len, head_dim) — the FULL per-slot
    cache, freshly-written rows and stale/zero rows alike;
    ``q_positions``: (batch, new) int32 absolute position of each query.

    The mask ``key_pos <= q_pos`` is what makes the cache safe to reuse
    without per-slot length bookkeeping: a key row is attendable only
    once some query's absolute position has reached it, and by then it
    was written either by this request's prefill or by an earlier decode
    step of this request — stale rows from a previous slot occupant sit
    at positions the current request has not reached, padded prefill
    rows are overwritten by decode before a query passes them.

    Plain XLA einsum + f32 softmax (the shapes are decode-sized: one or
    a few queries against ``max_seq`` keys — no flash-kernel tiling to
    win, and it must run everywhere, CPU tests included).
    """
    head_dim = q.shape[-1]
    scale = 1.0 / float(np.sqrt(head_dim))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    key_ids = jnp.arange(k.shape[2], dtype=jnp.int32)
    mask = key_ids[None, None, None, :] <= q_positions[:, None, :, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


class SelfAttention(nn.Module):
    """Multi-head self-attention on the flash kernel.

    ``attention_fn`` takes ``(q, k, v, causal=...)`` over
    ``(batch, heads, seq, head_dim)`` and defaults to the single-device
    Pallas kernel; sequence-parallel callers inject a ring/Ulysses closure.

    ``decode=True`` switches to the serving path: a ``cache`` variable
    collection holds per-row key/value tensors of length
    ``max_cache_len``, new tokens are scattered in at their absolute
    ``positions`` and attention runs masked against the whole cache
    (:func:`cached_attention`). Parameters are identical to the training
    module — only runtime behavior and the (non-param) cache change.

    ``paged=True`` (with ``decode=True``) swaps the per-row cache for a
    POOLED one: ``(num_pages, page_tokens, heads, head_dim)`` per layer,
    indexed through a per-row int32 ``page_table`` mapping logical block
    ``pos // page_tokens`` to a physical page (serve/paging.py owns the
    allocator). Writes scatter at ``cache.at[page, offset]`` with traced
    indices; reads gather ``cache[page_table]`` and flatten back to a
    per-row view whose flattened key index IS the absolute position, so
    the same ``key_pos <= q_pos`` mask applies unchanged. Table entries
    past a request's last block point at the reserved scratch page 0 —
    scatter clamps overflowing (padded-garbage) positions onto it and
    the mask keeps it unattendable. Both shapes and the program are
    fixed; growing a request only changes table VALUES.
    """

    num_heads: int
    causal: bool = False
    dtype: Dtype = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    fused_qkv: bool = False
    decode: bool = False
    max_cache_len: int = 0
    paged: bool = False
    num_pages: int = 0
    page_tokens: int = 0

    @nn.compact
    def __call__(self, x, positions=None, page_table=None):
        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(
                f"num_heads ({self.num_heads}) must divide d_model "
                f"({d_model})")
        head_dim = d_model // self.num_heads
        dense = partial(nn.DenseGeneral, dtype=self.dtype,
                        param_dtype=jnp.float32)

        qkv_shape = (self.num_heads, head_dim)
        if self.fused_qkv:
            # one (d_model, 3*d_model) matmul instead of three separate
            # (d_model, d_model) ones: reads the activations from HBM
            # once and gives XLA a single taller MXU tile. Changes the
            # checkpoint layout (param "qkv" replaces query/key/value),
            # so it is opt-in.
            qkv = dense(features=(3,) + qkv_shape, name="qkv")(x)
            q, k, v = (qkv[..., i, :, :] for i in range(3))
        else:
            q = dense(features=qkv_shape, name="query")(x)
            k = dense(features=qkv_shape, name="key")(x)
            v = dense(features=qkv_shape, name="value")(x)

        if self.decode and self.paged:
            if positions is None:
                raise ValueError("decode=True requires per-row positions")
            if page_table is None:
                raise ValueError("paged=True requires a page_table")
            if self.num_pages <= 0 or self.page_tokens <= 0:
                raise ValueError(
                    "paged=True requires num_pages and page_tokens > 0")
            batch, new_tokens = x.shape[0], x.shape[1]
            T = self.page_tokens
            cache_shape = (self.num_pages, T, self.num_heads, head_dim)
            cached_key = self.variable("cache", "cached_key", jnp.zeros,
                                       cache_shape, self.dtype)
            cached_value = self.variable("cache", "cached_value", jnp.zeros,
                                         cache_shape, self.dtype)
            table = jnp.asarray(page_table, jnp.int32)
            width = table.shape[1]
            pos = jnp.asarray(positions, jnp.int32)
            abs_pos = pos[:, None] + jnp.arange(new_tokens, dtype=jnp.int32)
            # logical block per new token; positions past the mapped
            # table clamp onto the trailing scratch entry (padded
            # prefill garbage lands there, masked + never gathered as a
            # reachable key position)
            blk = jnp.minimum(abs_pos // T, width - 1)
            page = jnp.take_along_axis(table, blk, axis=1)
            off = abs_pos % T
            cached_key.value = cached_key.value.at[page, off].set(
                k.astype(self.dtype))
            cached_value.value = cached_value.value.at[page, off].set(
                v.astype(self.dtype))
            # gather the row's mapped pages and flatten: key index i is
            # absolute position i for every mapped block, so the dense
            # path's mask semantics carry over verbatim
            k_all = cached_key.value[table].reshape(
                batch, width * T, self.num_heads, head_dim)
            v_all = cached_value.value[table].reshape(
                batch, width * T, self.num_heads, head_dim)
            o = cached_attention(
                q.transpose(0, 2, 1, 3), k_all.transpose(0, 2, 1, 3),
                v_all.transpose(0, 2, 1, 3), abs_pos)
            o = o.transpose(0, 2, 1, 3)
            return dense(features=d_model, axis=(-2, -1), name="out")(o)

        if self.decode:
            if positions is None:
                raise ValueError("decode=True requires per-row positions")
            if self.max_cache_len <= 0:
                raise ValueError("decode=True requires max_cache_len > 0")
            batch, new_tokens = x.shape[0], x.shape[1]
            cache_shape = (batch, self.max_cache_len, self.num_heads,
                           head_dim)
            cached_key = self.variable("cache", "cached_key", jnp.zeros,
                                       cache_shape, self.dtype)
            cached_value = self.variable("cache", "cached_value", jnp.zeros,
                                         cache_shape, self.dtype)
            pos = jnp.asarray(positions, jnp.int32)

            def scatter(cache, new, start):
                return jax.lax.dynamic_update_slice(cache, new, (start, 0, 0))

            cached_key.value = jax.vmap(scatter)(
                cached_key.value, k.astype(self.dtype), pos)
            cached_value.value = jax.vmap(scatter)(
                cached_value.value, v.astype(self.dtype), pos)
            q_pos = pos[:, None] + jnp.arange(new_tokens, dtype=jnp.int32)
            o = cached_attention(
                q.transpose(0, 2, 1, 3),
                cached_key.value.transpose(0, 2, 1, 3),
                cached_value.value.transpose(0, 2, 1, 3), q_pos)
            o = o.transpose(0, 2, 1, 3)
            return dense(features=d_model, axis=(-2, -1), name="out")(o)

        # (batch, seq, heads, head_dim) -> (batch, heads, seq, head_dim)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))

        if self.attention_fn is not None:
            attn = self.attention_fn
        elif self.is_initializing():
            # init trace only shapes the params; the Pallas kernel can't
            # lower off-TPU (and interpret mode is python-speed), so the
            # once-only init uses the plain XLA attention — enabling
            # host-side init (training.init_on_host) on remote chips
            from horovod_tpu.ops.pallas.flash_attention import (
                attention_reference)

            attn = (lambda q, k, v, causal: attention_reference(
                q, k, v, causal=causal))
        else:
            attn = (lambda q, k, v, causal: flash_attention(
                q, k, v, causal=causal))
        o = attn(q, k, v, causal=self.causal)
        o = o.transpose(0, 2, 1, 3)  # back to (batch, seq, heads, head_dim)
        return dense(features=d_model, axis=(-2, -1), name="out")(o)


class Mlp(nn.Module):
    d_ff: int
    dtype: Dtype = jnp.bfloat16
    act: Callable = nn.gelu

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        h = nn.Dense(self.d_ff, dtype=self.dtype,
                     param_dtype=jnp.float32, name="wi")(x)
        h = self.act(h)
        return nn.Dense(d_model, dtype=self.dtype,
                        param_dtype=jnp.float32, name="wo")(h)


class TransformerLayer(nn.Module):
    """Pre-LayerNorm block: x + Attn(LN(x)); x + MLP(LN(x))."""

    num_heads: int
    d_ff: int
    causal: bool = False
    dtype: Dtype = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    fused_qkv: bool = False
    decode: bool = False
    max_cache_len: int = 0
    paged: bool = False
    num_pages: int = 0
    page_tokens: int = 0

    @nn.compact
    def __call__(self, x, positions=None, page_table=None):
        ln = partial(nn.LayerNorm, dtype=self.dtype, param_dtype=jnp.float32)
        x = x + SelfAttention(
            num_heads=self.num_heads, causal=self.causal, dtype=self.dtype,
            attention_fn=self.attention_fn, fused_qkv=self.fused_qkv,
            decode=self.decode, max_cache_len=self.max_cache_len,
            paged=self.paged, num_pages=self.num_pages,
            page_tokens=self.page_tokens,
            name="attention")(ln()(x), positions=positions,
                              page_table=page_table)
        x = x + Mlp(d_ff=self.d_ff, dtype=self.dtype, name="mlp")(ln()(x))
        return x


class Transformer(nn.Module):
    """Shared trunk: embeddings → N layers → final LayerNorm → logits.

    ``causal=True`` makes a GPT-style decoder; ``causal=False`` a BERT-style
    bidirectional encoder. The output projection ties the token-embedding
    matrix (standard for both families). Vocab logits are returned in
    float32 for a numerically stable softmax-cross-entropy.
    """

    vocab_size: int
    d_model: int = 768
    num_layers: int = 12
    num_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 512
    causal: bool = False
    dtype: Dtype = jnp.bfloat16
    remat: bool = False
    attention_fn: Optional[Callable] = None
    fused_qkv: bool = False
    decode: bool = False
    paged: bool = False
    num_pages: int = 0
    page_tokens: int = 0

    @nn.compact
    def __call__(self, token_ids, train: bool = True, pos_offset=0,
                 output: str = "logits", positions=None, page_table=None):
        """``pos_offset`` is the global position of the first token — under
        sequence parallelism each device passes its shard's offset (e.g.
        ``lax.axis_index(axis) * seq_local``) so position embeddings stay
        global; it may be a traced scalar. ``max_seq`` must cover the
        GLOBAL sequence (``pos_offset + seq``); with a traced offset this
        cannot be checked at trace time, so size ``max_seq`` accordingly.

        ``output="hidden"`` returns the final-norm hidden states
        (batch, seq, d_model) WITHOUT the tied vocab projection — the
        MLM training path projects only the masked positions
        (:func:`masked_lm_loss_gathered`), so the (batch, seq, vocab)
        float32 logits tensor (0.5 GB at BERT-Large bench shapes) never
        exists. Measured on the BERT-Large bench shape: the full-logits
        head costs ~2.9 ms of a 79.2 ms step — the gathered path is
        +3.8% tokens/s end to end (docs/perf_experiments.md round 4)."""
        if token_ids.ndim != 2:
            raise ValueError("expected (batch, seq) int token ids")
        seq = token_ids.shape[1]
        if seq > self.max_seq:
            raise ValueError(
                f"sequence length {seq} exceeds max_seq={self.max_seq}")
        embed = nn.Embed(self.vocab_size, self.d_model,
                         dtype=self.dtype, param_dtype=jnp.float32,
                         embedding_init=nn.initializers.normal(0.02),
                         name="token_embed")
        pos_embed = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.max_seq, self.d_model), jnp.float32)

        if self.decode:
            # serving decode: each batch row sits at its own absolute
            # position (continuous batching mixes requests of different
            # lengths in one step). Gather per-row position embeddings
            # and thread ``positions`` to every layer's KV cache.
            if positions is None:
                raise ValueError("decode=True requires per-row positions")
            pos_idx = (jnp.asarray(positions, jnp.int32)[:, None]
                       + jnp.arange(seq, dtype=jnp.int32)[None, :])
            pos_idx = jnp.minimum(pos_idx, self.max_seq - 1)
            pos_rows = jnp.take(pos_embed, pos_idx, axis=0)
            x = embed(token_ids) + pos_rows.astype(self.dtype)
            for i in range(self.num_layers):
                x = TransformerLayer(
                    num_heads=self.num_heads, d_ff=self.d_ff,
                    causal=self.causal, dtype=self.dtype,
                    attention_fn=self.attention_fn,
                    fused_qkv=self.fused_qkv, decode=True,
                    max_cache_len=self.max_seq, paged=self.paged,
                    num_pages=self.num_pages,
                    page_tokens=self.page_tokens,
                    name=f"layer_{i}")(x, positions=positions,
                                       page_table=page_table)
            x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                             name="final_norm")(x)
            if output == "hidden":
                return x
            return embed.attend(x).astype(jnp.float32)

        if isinstance(pos_offset, int):
            # static offset: check bounds eagerly — dynamic_slice would
            # silently clamp and reuse wrong position embeddings.
            if pos_offset + seq > self.max_seq:
                raise ValueError(
                    f"pos_offset {pos_offset} + seq {seq} exceeds "
                    f"max_seq={self.max_seq}; under sequence parallelism "
                    f"max_seq must cover the GLOBAL sequence length")
            pos = jax.lax.dynamic_slice_in_dim(pos_embed, pos_offset, seq,
                                               axis=0) if pos_offset else \
                pos_embed[:seq, :]
        else:
            pos = jax.lax.dynamic_slice_in_dim(
                pos_embed, jnp.asarray(pos_offset, jnp.int32), seq, axis=0)
        x = embed(token_ids) + pos[None, :, :].astype(self.dtype)

        layer = TransformerLayer
        if self.remat:
            layer = nn.remat(layer)
        for i in range(self.num_layers):
            x = layer(num_heads=self.num_heads, d_ff=self.d_ff,
                      causal=self.causal, dtype=self.dtype,
                      attention_fn=self.attention_fn,
                      fused_qkv=self.fused_qkv,
                      name=f"layer_{i}")(x)

        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="final_norm")(x)
        if output == "hidden":
            return x
        logits = embed.attend(x)  # tied output projection
        return logits.astype(jnp.float32)


# BERT family (bidirectional encoders; BERT-Large is BASELINE config #5's
# shape: 24 layers, hidden 1024, 16 heads).
BertBase = partial(Transformer, d_model=768, num_layers=12, num_heads=12,
                   d_ff=3072, causal=False)
BertLarge = partial(Transformer, d_model=1024, num_layers=24, num_heads=16,
                    d_ff=4096, causal=False)

# GPT family (causal decoders).
GPT2Small = partial(Transformer, d_model=768, num_layers=12, num_heads=12,
                    d_ff=3072, max_seq=1024, causal=True)
GPT2Medium = partial(Transformer, d_model=1024, num_layers=24, num_heads=16,
                     d_ff=4096, max_seq=1024, causal=True)


def masked_lm_loss(logits, labels, mask):
    """BERT MLM objective: mean cross-entropy over masked positions only."""
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    mask = mask.astype(loss.dtype)
    return (loss * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def masked_lm_loss_gathered(hidden, embed_matrix, positions, labels,
                            weights=None):
    """BERT MLM objective over a FIXED set of masked positions, vocab
    projection applied AFTER gathering — the standard BERT data layout
    (``max_predictions_per_seq``: positions/labels/weights per row).

    ``hidden``: (batch, seq, d) from ``model(..., output="hidden")``;
    ``embed_matrix``: the tied (vocab, d) token embedding
    (``params["params"]["token_embed"]["embedding"]``);
    ``positions``: (batch, M) int32; ``labels``: (batch, M) int32;
    ``weights``: (batch, M) 0/1 mask for rows with fewer than M real
    predictions (None = all real).

    Projecting only the M≈0.15*seq masked positions instead of all seq
    keeps the (batch, seq, vocab) f32 logits tensor from ever existing:
    at BERT-Large bench shapes that is 0.5 GB of HBM written + re-read
    in softmax fwd AND bwd — measured ~2.9 ms of the 79.2 ms step,
    +3.8% tokens/s end to end (docs/perf_experiments.md round 4). FLOPs
    of the projection drop the same way; MFU accounting must use the
    gathered count."""
    gathered = jnp.take_along_axis(hidden, positions[..., None], axis=1)
    logits = (gathered @ embed_matrix.astype(gathered.dtype).T
              ).astype(jnp.float32)
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    if weights is None:
        return loss.mean()
    w = weights.astype(loss.dtype)
    return (loss * w).sum() / jnp.maximum(w.sum(), 1.0)


def sample_masked_positions(rng: np.random.Generator, batch: int,
                            seq: int, num_predictions: int):
    """Fixed-count masked-position sampling (BERT's
    ``max_predictions_per_seq`` layout): per row, ``num_predictions``
    distinct positions, sorted. Returns an int32 (batch, M) array of
    positions (labels are the input tokens at those positions; gather
    them with ``np.take_along_axis``)."""
    pos = np.stack([rng.choice(seq, size=num_predictions, replace=False)
                    for _ in range(batch)])
    return np.sort(pos, axis=1).astype(np.int32)


def causal_lm_loss(logits, token_ids):
    """Next-token prediction: shift-by-one cross-entropy."""
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], token_ids[:, 1:])
    return loss.mean()


def causal_lm_loss_chunked(hidden, embed_matrix, token_ids,
                           chunk: int = 128):
    """Next-token cross-entropy computed seq-chunk at a time, vocab
    projection applied INSIDE the chunk loop — the (batch, seq, vocab)
    float32 logits tensor never exists (3.3 GB at GPT-2 bench shapes;
    unlike MLM, causal LM needs every position's logits, but never all
    at once).

    MEASURED (docs/perf_experiments.md round 4): 5.8-8.1% SLOWER than
    the full-logits path on the GPT-2 bench — the chunk scan trades one
    large efficient (B·S, d)x(d, vocab) matmul for several smaller
    ones, and XLA streams the big tensor better than the hand loop.
    Kept for memory-constrained configurations (long seq x large vocab
    where the logits tensor itself OOMs), NOT as a throughput move.

    ``hidden``: (batch, seq, d) from ``model(..., output="hidden")``;
    ``embed_matrix``: the tied (vocab, d) token embedding;
    ``token_ids``: (batch, seq) int labels. Exactly equals
    ``causal_lm_loss(model.apply(...), token_ids)`` up to f32 summation
    order (tested). ``chunk`` must divide seq."""
    b, s, d = hidden.shape
    if s % chunk:
        raise ValueError(f"chunk ({chunk}) must divide seq ({s})")
    emb = embed_matrix.astype(hidden.dtype)
    # predictions at positions [0, s-1) predict tokens [1, s); weight the
    # final position 0 so the scan body is uniform across chunks
    labels = jnp.concatenate(
        [token_ids[:, 1:], jnp.zeros((b, 1), token_ids.dtype)], axis=1)
    valid = jnp.concatenate(
        [jnp.ones((b, s - 1), jnp.float32), jnp.zeros((b, 1), jnp.float32)],
        axis=1)

    h_c = hidden.reshape(b, s // chunk, chunk, d).transpose(1, 0, 2, 3)
    lab_c = labels.reshape(b, s // chunk, chunk).transpose(1, 0, 2)
    w_c = valid.reshape(b, s // chunk, chunk).transpose(1, 0, 2)

    # remat the body: without it, scan's backward stores each chunk's
    # softmax residuals — stacked, that is the full (batch, seq, vocab)
    # tensor again and the memory benefit evaporates under value_and_grad
    @jax.checkpoint
    def body(acc, xs):
        h, lab, w = xs
        logits = (h @ emb.T).astype(jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, lab)
        return acc + jnp.sum(loss * w), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (h_c, lab_c, w_c))
    return total / (b * (s - 1))


def random_tokens(rng: np.random.Generator, batch: int, seq: int,
                  vocab_size: int) -> np.ndarray:
    """Synthetic token batch for benchmarks (uniform vocab draw)."""
    return rng.integers(0, vocab_size, size=(batch, seq), dtype=np.int32)
