"""VGG family (flax), TPU-first.

VGG-16 is one of the reference's three headline scaling-benchmark models
(reference: docs/benchmarks.rst:13-14 — 68% efficiency at 512 GPUs; its
huge dense layers stress gradient-exchange bandwidth, which is exactly why
the reference reports it). Fresh implementation: NHWC, bf16 compute /
f32 params, static shapes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class VGG(nn.Module):
    """Classic VGG: conv stages + 2x4096 dense head.

    ``stage_sizes`` gives convs per stage; channels double per stage from
    64 up to 512. ``batch_norm`` selects the BN variant (vgg*_bn).
    """

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    batch_norm: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), dtype=self.dtype,
                       param_dtype=jnp.float32,
                       kernel_init=nn.initializers.he_normal())
        x = x.astype(self.dtype)
        channels = 64
        for stage, n_convs in enumerate(self.stage_sizes):
            for i in range(n_convs):
                x = conv(features=channels, name=f"conv{stage}_{i}")(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9, epsilon=1e-5,
                                     dtype=self.dtype,
                                     param_dtype=jnp.float32,
                                     name=f"bn{stage}_{i}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            channels = min(channels * 2, 512)

        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype,
                             param_dtype=jnp.float32, name="fc1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype,
                             param_dtype=jnp.float32, name="fc2")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="classifier")(x)
        return x.astype(jnp.float32)


VGG11 = partial(VGG, stage_sizes=[1, 1, 2, 2, 2])
VGG13 = partial(VGG, stage_sizes=[2, 2, 2, 2, 2])
VGG16 = partial(VGG, stage_sizes=[2, 2, 3, 3, 3])
VGG19 = partial(VGG, stage_sizes=[2, 2, 4, 4, 4])
