"""horovod_tpu.mxnet — MXNet-shaped binding surface (duck-typed).

Rebuild of the reference's MXNet API surface (reference:
horovod/mxnet/__init__.py:40-125, horovod/mxnet/mpi_ops.py:53-232):
``DistributedOptimizer`` folds the world-size average into
``rescale_grad`` and allreduces gradients with per-index names and
priority hints; ``broadcast_parameters`` syncs a parameter dict from the
root. The reference pushes async ops into the MXNet engine with
write-var dependencies and a ``priority`` ordering hint — here the ops
ride the same data plane as every other binding (XLA collectives / the
dynamic enqueue runtime), and ``priority`` orders tensors within a
runtime cycle.

DELIBERATE LIMIT (PARITY.md "Deliberate limits"): MXNet is EOL
(archived upstream) and absent from the TPU stack, so this binding is
duck-typed, not an engine integration — ops accept any
numpy-convertible mutable array, and ``DistributedOptimizer`` wraps any
object with MXNet's optimizer protocol (``rescale_grad``,
``update(index, weight, grad, state)``). The reference's Gluon
``DistributedTrainer`` (horovod/mxnet/__init__.py:85-107) is NOT
implemented: a subclass of a class that can never be imported here
would be dead code no test or user could ever construct; the name
raises ImportError with a pointer to the covered surfaces instead.
"""

from __future__ import annotations

import numpy as np

from horovod_tpu.core.basics import (  # noqa: F401 — re-exported lifecycle
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    mesh,
    is_homogeneous,
    mpi_built,
    gloo_built,
    nccl_built,
    ddl_built,
    mlsl_built,
    xla_built,
    mpi_enabled,
    mpi_threads_supported,
)
from horovod_tpu.core import basics
from horovod_tpu.ops import collectives as _coll


def _run_async(kind: str, tensor, *, average: bool = True,
               root_rank: int = 0, name=None, priority: int = 0):
    """Dispatch one collective on the shared data plane, returning a handle
    for :func:`_coll.synchronize`. In a multi-process world the op enters
    the enqueue runtime (negotiation + fusion + priority ordering) WITHOUT
    blocking — callers that enqueue several tensors before synchronizing
    get them negotiated and fused in the same cycle, the engine-async
    behavior of the reference's MXNet ops. Single-controller worlds use
    the eager XLA path where the replicated/stacked semantics already
    hold (dispatch is still async — the result is a future-backed array).
    """
    st = basics._ensure_init()
    x = np.asarray(tensor)
    if _coll._multiprocess_world(st) and _coll._runtime_capable(st):
        if kind == "allreduce":
            return _coll.allreduce_async(
                x, average=average,
                name=name or _coll._auto_name("mx.allreduce"),
                priority=priority)
        if kind == "allgather":
            return _coll.allgather_async(
                x, name=name or _coll._auto_name("mx.allgather"),
                priority=priority)
        return _coll.broadcast_async(
            x, root_rank, name=name or _coll._auto_name("mx.broadcast"),
            priority=priority)
    if kind == "allreduce":
        return _coll.Handle(_coll.allreduce(x, average=average))
    if kind == "allgather":
        return _coll.Handle(_coll.allgather(x))
    return _coll.Handle(_coll.broadcast(x, root_rank))


def _run(kind: str, tensor, *, average: bool = True, root_rank: int = 0,
         name=None, priority: int = 0):
    return _coll.synchronize(_run_async(
        kind, tensor, average=average, root_rank=root_rank, name=name,
        priority=priority))


def _check_mutable(tensor) -> None:
    """Fail fast on misuse BEFORE the collective runs — an in-place op on
    an immutable input would otherwise waste a full negotiation + dispatch
    on every rank just to raise on write-back."""
    if not (isinstance(tensor, np.ndarray) and tensor.flags.writeable):
        raise TypeError(
            "in-place collectives need a mutable numpy array, got "
            f"{type(tensor)}")


def _write_back(tensor, result) -> None:
    # output dtype == input dtype, as in the reference (the device compute
    # may run narrower, e.g. f64 -> f32 with jax's default x64-off)
    tensor[...] = np.asarray(result).astype(tensor.dtype).reshape(
        tensor.shape)


def _like(tensor, result):
    return np.asarray(result).astype(np.asarray(tensor).dtype)


def allreduce(tensor, average=True, name=None, priority=0):
    """Average/sum ``tensor`` over all workers; input unmodified
    (reference: horovod/mxnet/mpi_ops.py:53-93)."""
    return _like(tensor, _run("allreduce", tensor, average=average,
                              name=name, priority=priority))


def allreduce_(tensor, average=True, name=None, priority=0):
    """In-place allreduce (reference: horovod/mxnet/mpi_ops.py:95-127)."""
    _check_mutable(tensor)
    _write_back(tensor, _run("allreduce", tensor, average=average,
                             name=name, priority=priority))
    return tensor


def allgather(tensor, name=None, priority=0):
    """Concatenate each worker's tensor along dim 0 (reference:
    horovod/mxnet/mpi_ops.py:129-166)."""
    return _like(tensor, _run("allgather", tensor, name=name,
                              priority=priority))


def broadcast(tensor, root_rank, name=None, priority=0):
    """Out-of-place broadcast from ``root_rank`` (reference:
    horovod/mxnet/mpi_ops.py:168-206)."""
    return _like(tensor, _run("broadcast", tensor, root_rank=root_rank,
                              name=name, priority=priority))


def broadcast_(tensor, root_rank, name=None, priority=0):
    """In-place broadcast (reference: horovod/mxnet/mpi_ops.py:208-232)."""
    _check_mutable(tensor)
    _write_back(tensor, _run("broadcast", tensor, root_rank=root_rank,
                             name=name, priority=priority))
    return tensor


class DistributedOptimizer:
    """Optimizer wrapper: allreduce gradients inside ``update`` with the
    average folded into ``rescale_grad`` (reference:
    horovod/mxnet/__init__.py:40-77 — "normalizing rescale_grad by size
    is equivalent to performing average in allreduce").

    Wraps any object with MXNet's optimizer protocol: a mutable
    ``rescale_grad`` attribute and ``update(index, weight, grad, state)``.
    """

    def __init__(self, optimizer):
        if isinstance(optimizer, DistributedOptimizer):
            raise ValueError("optimizer is already a DistributedOptimizer")
        self._optimizer = optimizer
        self._optimizer.rescale_grad /= size()

    def __getattr__(self, item):
        if item == "_optimizer":  # not yet in __dict__ (e.g. unpickling)
            raise AttributeError(item)
        # delegates everything the wrapper doesn't override —
        # create_state*, set_learning_rate, set_lr_mult, set_wd_mult, ...
        return getattr(self._optimizer, item)

    def _do_allreduce(self, index, grad):
        if isinstance(index, (tuple, list)):
            # Enqueue every gradient BEFORE synchronizing any, so in
            # multi-process mode they all land in the same runtime cycle —
            # negotiated together, priority-ordered, and fused (the
            # reference gets this from MXNet's async engine push).
            for g in grad:
                _check_mutable(g)
            handles = [
                _run_async("allreduce", grad[i], average=False,
                           name=str(index[i]), priority=-i)
                for i in range(len(index))]
            for g, h in zip(grad, handles):
                _write_back(g, _coll.synchronize(h))
        else:
            allreduce_(grad, average=False, name=str(index))

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)


class DistributedTrainer:
    """NOT implemented — deliberate limit, not a gap (see module
    docstring and PARITY.md). The reference's Gluon trainer (reference:
    horovod/mxnet/__init__.py:85-107) subclasses ``mx.gluon.Trainer``,
    which cannot exist without real MXNet; its two behaviors (fold
    world size into ``_scale``, exchange grads by sorted-name order with
    priority hints) are covered by :class:`DistributedOptimizer` and
    the other bindings' trainers."""

    def __init__(self, *args, **kwargs):
        raise ImportError(
            "DistributedTrainer requires mxnet (EOL, not part of the TPU "
            "stack — see PARITY.md 'Deliberate limits'); use "
            "DistributedOptimizer (any MXNet-protocol optimizer) or the "
            "jax/torch/tf surfaces instead")


def broadcast_parameters(params, root_rank=0):
    """Broadcast a parameter dict (name → array) in place from
    ``root_rank`` (reference: horovod/mxnet/__init__.py:118-125)."""
    if not hasattr(params, "items"):
        raise ValueError(f"invalid params of type: {type(params)}")
    for name, t in sorted(params.items()):
        if t is None:
            continue
        broadcast_(t, root_rank=root_rank, name=name)
