"""horovod_tpu.mxnet — MXNet-shaped binding for the TPU-native framework.

Rebuild of the reference's MXNet API (reference: horovod/mxnet/__init__.py
:40-125, horovod/mxnet/mpi_ops.py:53-232): ``DistributedOptimizer`` folds
the world-size average into ``rescale_grad`` and allreduces gradients with
per-index names and priority hints; ``DistributedTrainer`` does the same for
Gluon; ``broadcast_parameters`` syncs a parameter dict from the root. The
reference pushes async ops into the MXNet engine with write-var
dependencies and a ``priority`` ordering hint — here the ops ride the same
data plane as every other binding (XLA collectives / the dynamic enqueue
runtime), and ``priority`` orders tensors within a runtime cycle.

MXNet itself is EOL and not part of the TPU stack, so the binding is
duck-typed: ops accept ``mx.nd.NDArray`` when MXNet is importable and any
numpy-convertible mutable array otherwise, and ``DistributedOptimizer``
wraps any object with MXNet's optimizer protocol (``rescale_grad``,
``update(index, weight, grad, state)``). ``DistributedTrainer`` requires
real Gluon and raises ``ImportError`` without it.
"""

from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np

from horovod_tpu.core.basics import (  # noqa: F401 — re-exported lifecycle
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    mesh,
    is_homogeneous,
    mpi_built,
    gloo_built,
    nccl_built,
    ddl_built,
    mlsl_built,
    xla_built,
    mpi_enabled,
    mpi_threads_supported,
)
from horovod_tpu.core import basics
from horovod_tpu.ops import collectives as _coll

try:  # pragma: no cover — mxnet absent from the TPU image
    import mxnet as _mx
except ImportError:
    _mx = None


def _is_mx(tensor) -> bool:
    return _mx is not None and isinstance(tensor, _mx.nd.NDArray)


def _to_device(tensor):
    if _is_mx(tensor):  # pragma: no cover — mxnet absent
        return jnp.asarray(tensor.asnumpy())
    return jnp.asarray(np.asarray(tensor))


def _run_async(kind: str, tensor, *, average: bool = True,
               root_rank: int = 0, name=None, priority: int = 0):
    """Dispatch one collective on the shared data plane, returning a handle
    for :func:`_coll.synchronize`. In a multi-process world the op enters
    the enqueue runtime (negotiation + fusion + priority ordering) WITHOUT
    blocking — callers that enqueue several tensors before synchronizing
    get them negotiated and fused in the same cycle, the engine-async
    behavior of the reference's MXNet ops. Single-controller worlds use
    the eager XLA path where the replicated/stacked semantics already
    hold (dispatch is still async — the result is a future-backed array).
    """
    st = basics._ensure_init()
    x = _to_device(tensor)
    if _coll._multiprocess_world(st) and _coll._runtime_capable(st):
        if kind == "allreduce":
            return _coll.allreduce_async(
                x, average=average,
                name=name or _coll._auto_name("mx.allreduce"),
                priority=priority)
        if kind == "allgather":
            return _coll.allgather_async(
                x, name=name or _coll._auto_name("mx.allgather"),
                priority=priority)
        return _coll.broadcast_async(
            x, root_rank, name=name or _coll._auto_name("mx.broadcast"),
            priority=priority)
    if kind == "allreduce":
        return _coll.Handle(_coll.allreduce(x, average=average))
    if kind == "allgather":
        return _coll.Handle(_coll.allgather(x))
    return _coll.Handle(_coll.broadcast(x, root_rank))


def _run(kind: str, tensor, *, average: bool = True, root_rank: int = 0,
         name=None, priority: int = 0):
    return _coll.synchronize(_run_async(
        kind, tensor, average=average, root_rank=root_rank, name=name,
        priority=priority))


def _check_mutable(tensor) -> None:
    """Fail fast on misuse BEFORE the collective runs — an in-place op on
    an immutable input would otherwise waste a full negotiation + dispatch
    on every rank just to raise on write-back."""
    if _is_mx(tensor):  # pragma: no cover — mxnet absent
        return
    if not (isinstance(tensor, np.ndarray) and tensor.flags.writeable):
        raise TypeError(
            "in-place collectives need a mutable array (numpy or "
            f"mx.nd.NDArray), got {type(tensor)}")


def _write_back(tensor, result) -> None:
    if _is_mx(tensor):  # pragma: no cover — mxnet absent
        tensor[:] = _mx.nd.array(np.asarray(result), dtype=tensor.dtype)
        return
    # output dtype == input dtype, as in the reference (the device compute
    # may run narrower, e.g. f64 -> f32 with jax's default x64-off)
    tensor[...] = np.asarray(result).astype(tensor.dtype).reshape(
        tensor.shape)


def _like(tensor, result):
    out = np.asarray(result)
    if _is_mx(tensor):  # pragma: no cover — mxnet absent
        return _mx.nd.array(out, dtype=tensor.dtype)
    return out.astype(np.asarray(tensor).dtype)


def allreduce(tensor, average=True, name=None, priority=0):
    """Average/sum ``tensor`` over all workers; input unmodified
    (reference: horovod/mxnet/mpi_ops.py:53-93)."""
    return _like(tensor, _run("allreduce", tensor, average=average,
                              name=name, priority=priority))


def allreduce_(tensor, average=True, name=None, priority=0):
    """In-place allreduce (reference: horovod/mxnet/mpi_ops.py:95-127)."""
    _check_mutable(tensor)
    _write_back(tensor, _run("allreduce", tensor, average=average,
                             name=name, priority=priority))
    return tensor


def allgather(tensor, name=None, priority=0):
    """Concatenate each worker's tensor along dim 0 (reference:
    horovod/mxnet/mpi_ops.py:129-166)."""
    return _like(tensor, _run("allgather", tensor, name=name,
                              priority=priority))


def broadcast(tensor, root_rank, name=None, priority=0):
    """Out-of-place broadcast from ``root_rank`` (reference:
    horovod/mxnet/mpi_ops.py:168-206)."""
    return _like(tensor, _run("broadcast", tensor, root_rank=root_rank,
                              name=name, priority=priority))


def broadcast_(tensor, root_rank, name=None, priority=0):
    """In-place broadcast (reference: horovod/mxnet/mpi_ops.py:208-232)."""
    _check_mutable(tensor)
    _write_back(tensor, _run("broadcast", tensor, root_rank=root_rank,
                             name=name, priority=priority))
    return tensor


class DistributedOptimizer:
    """Optimizer wrapper: allreduce gradients inside ``update`` with the
    average folded into ``rescale_grad`` (reference:
    horovod/mxnet/__init__.py:40-77 — "normalizing rescale_grad by size
    is equivalent to performing average in allreduce").

    Wraps any object with MXNet's optimizer protocol: a mutable
    ``rescale_grad`` attribute and ``update(index, weight, grad, state)``.
    """

    def __init__(self, optimizer):
        if isinstance(optimizer, DistributedOptimizer):
            raise ValueError("optimizer is already a DistributedOptimizer")
        self._optimizer = optimizer
        self._optimizer.rescale_grad /= size()

    def __getattr__(self, item):
        if item == "_optimizer":  # not yet in __dict__ (e.g. unpickling)
            raise AttributeError(item)
        # delegates everything the wrapper doesn't override —
        # create_state*, set_learning_rate, set_lr_mult, set_wd_mult, ...
        return getattr(self._optimizer, item)

    def _do_allreduce(self, index, grad):
        if isinstance(index, (tuple, list)):
            # Enqueue every gradient BEFORE synchronizing any, so in
            # multi-process mode they all land in the same runtime cycle —
            # negotiated together, priority-ordered, and fused (the
            # reference gets this from MXNet's async engine push).
            for g in grad:
                _check_mutable(g)
            handles = [
                _run_async("allreduce", grad[i], average=False,
                           name=str(index[i]), priority=-i)
                for i in range(len(index))]
            for g, h in zip(grad, handles):
                _write_back(g, _coll.synchronize(h))
        else:
            allreduce_(grad, average=False, name=str(index))

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)


if _mx is not None:  # pragma: no cover — mxnet absent from the TPU image

    class DistributedTrainer(_mx.gluon.Trainer):
        """Gluon trainer doing gradient exchange through the framework's
        allreduce instead of kvstore push/pull (reference:
        horovod/mxnet/__init__.py:85-107)."""

        def __init__(self, params, optimizer, optimizer_params=None):
            if isinstance(optimizer, DistributedOptimizer):
                optimizer = optimizer._optimizer
                warnings.warn(
                    "DistributedTrainer does not take DistributedOptimizer "
                    "as its optimizer. We have unwrapped it for you.")
            super().__init__(params, optimizer,
                             optimizer_params=optimizer_params, kvstore=None)
            self._scale /= size()

        def _allreduce_grads(self):
            for i, param in enumerate(
                    sorted(self._params, key=lambda p: p.name)):
                if param.grad_req != "null":
                    allreduce_(param.list_grad()[0], average=False,
                               name=str(i), priority=-i)

else:

    class DistributedTrainer:  # type: ignore[no-redef]
        """Placeholder: Gluon's Trainer needs real MXNet (reference:
        horovod/mxnet/__init__.py:85-107). The optimizer-protocol surface
        is covered by :class:`DistributedOptimizer`."""

        def __init__(self, *args, **kwargs):
            raise ImportError(
                "DistributedTrainer requires mxnet, which is not "
                "installed; use DistributedOptimizer (any MXNet-protocol "
                "optimizer) or the jax/torch surfaces instead")


def broadcast_parameters(params, root_rank=0):
    """Broadcast a parameter dict (name → array) in place from
    ``root_rank`` (reference: horovod/mxnet/__init__.py:118-125; the
    reference also hooks Gluon ``Parameter._init_impl`` — with real MXNet,
    pass ``Block.collect_params()`` and each parameter's data is synced).
    """
    if _mx is not None and hasattr(params, "items") and all(
            hasattr(p, "list_data") for p in
            params.values()):  # pragma: no cover — ParameterDict w/ mxnet
        tensors = {name: p.data() for name, p in params.items()}
        for name, t in sorted(tensors.items()):
            broadcast_(t, root_rank=root_rank, name=name)
        return
    if not hasattr(params, "items"):
        raise ValueError(f"invalid params of type: {type(params)}")
    for name, t in sorted(params.items()):
        if t is None:
            continue
        broadcast_(t, root_rank=root_rank, name=name)
