"""Collective operations: the TPU data plane.

TPU-native replacement for the reference's op layer (reference:
horovod/common/ops/{mpi,nccl,gloo}_operations.cc and the Python op wrappers
horovod/torch/mpi_ops.py, horovod/tensorflow/mpi_ops.py). Where the
reference dispatches to NCCL/MPI/Gloo rings, every collective here is an XLA
collective compiled over the global ``(cross, local)`` device mesh so the
traffic rides ICI (and DCN across slices), fused and scheduled by XLA.

Two call modes, one API:

* **In-jit (hot path)** — called on traced values under ``shard_map``/
  ``pjit``: emits ``lax.psum``/``all_gather``/``psum_scatter``/``all_to_all``
  over the mesh axis names. This is where training-step gradient reduction
  happens, fully fused into the step program.

* **Eager** — called on concrete arrays: dispatches a cached, jit-compiled
  collective program over the mesh. Per-worker data uses the *stacked*
  encoding: an array of shape ``(size, *shape)`` sharded along axis 0, one
  slice per device (see ``stack_per_worker``). A replicated input means
  "every worker holds this same tensor", matching single-controller SPMD
  semantics.

Async semantics come from XLA's async dispatch: eager ops return immediately
with a future-backed ``jax.Array``; ``*_async`` returns a ``Handle`` and
``poll``/``synchronize`` mirror the reference's handle API (reference:
horovod/torch/mpi_ops.py:61-124, torch/handle_manager.cc).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.utils import compat
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import comms, flight_recorder, tracing
from horovod_tpu.compression import Compression
from horovod_tpu.core import basics, mesh as mesh_mod, state as state_mod

# Reduction ops (reference: common/message.h RequestType + torch mpi_ops v2
# op constants; v0.18 supports sum/average, we add min/max/product as
# first-class TPU extensions).
Average = 0
Sum = 1
Min = 2
Max = 3
Product = 4

_OP_NAMES = {Average: "average", Sum: "sum", Min: "min", Max: "max", Product: "product"}
OPS_BY_NAME = {v: k for k, v in _OP_NAMES.items()}


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _global_axes(axis_name):
    if axis_name is None:
        return mesh_mod.GLOBAL_AXES
    return axis_name


class OrderedLaneError(RuntimeError):
    """A global-mesh collective program was about to be dispatched from a
    caller thread while named async collectives were still in flight on
    the background runtime lane.

    In a multi-process (SPMD) world every rank must issue collective
    programs in the SAME order; the enqueue runtime's background thread is
    the single ordered issuer for dynamically-timed ops (reference
    architecture note: operations.cc:281-300). Interleaving a caller-thread
    global program with in-flight named ops can order programs differently
    per rank — a hang or garbage, which the reference's analogous misuse
    paths turn into errors (tensor_queue.cc:26-29). Synchronize the
    outstanding handles first."""


def _lane_check() -> None:
    """Raise instead of hanging on the documented cross-rank
    program-order hazard (docs/troubleshooting.md: one ordered collective
    lane). Only the multi-process SPMD mode is at risk; the runtime's own
    background thread IS the lane and is exempt."""
    if jax.process_count() <= 1:
        return
    st = state_mod.global_state()
    rt = getattr(st, "runtime", None)
    if rt is None:
        return
    if threading.current_thread() is getattr(rt, "_thread", None):
        return
    n = rt.in_flight()
    if n:
        raise OrderedLaneError(
            f"{n} named async collective(s) are still in flight on the "
            "background runtime lane; dispatching a global-mesh collective "
            "program from the caller thread now can interleave collective "
            "programs differently across ranks (hang/garbage). Call "
            "hvd.synchronize() on the outstanding handles (or "
            "optimizer.step() in the torch binding) first — see "
            "docs/troubleshooting.md, 'one ordered collective lane'.")


def assert_collective_lane_clear() -> None:
    """Public guard for user-owned global programs: call before
    dispatching your own jitted global-mesh step (e.g. a pjit train step)
    in multi-process mode; raises :class:`OrderedLaneError` if named async
    collectives are still in flight instead of risking the documented
    cross-rank interleaving hang."""
    _lane_check()


def _to_plane(tensor):
    """Bring an input onto the data plane WITHOUT narrowing 64-bit numpy
    payloads: ``jnp.asarray`` under default x32 silently casts
    int64/uint64/float64 down (2**40 becomes garbage, 1e300 becomes inf)
    — exactly the corruption the reference's per-dtype op matrix guards
    against (reference: test/test_torch.py dtype sweeps). 64-bit numpy
    arrays stay numpy end-to-end: the host ring reduces them exactly
    (``_widen_for_ring`` passes 64-bit through), and the
    single-controller replicated math (``x * size`` etc.) is exact in
    numpy. Everything else becomes a jax array as before."""
    if isinstance(tensor, jax.Array):
        return tensor
    a = np.asarray(tensor)
    if a.dtype.itemsize == 8 and a.dtype.kind in "iuf":
        return a
    return jnp.asarray(a)


def _replicated_rs_a2a(kind: str, x, world: int, op):
    """Single-controller emulation of reducescatter/alltoall for the
    framework bindings (torch/tf): every worker holds ``x`` (the
    replicated world model the bindings' other ops use), and the binding
    returns worker 0's result — computed exactly in numpy (no device
    round trip, so 64-bit payloads stay exact). Narrow ints widen for
    the arithmetic and cast back, the same wrap-on-overflow semantics as
    the host ring kernels (runtime/executor.py _widen_for_ring)."""
    from horovod_tpu.runtime.executor import _widen_for_ring

    if x.shape[0] % world:
        # bindings check statically where they can; dynamic tf.function
        # shapes bypass that, and flooring here would silently truncate
        raise ValueError(
            f"{kind} dim 0 ({x.shape[0]}) must divide evenly by "
            f"size ({world})")
    shard = x.shape[0] // world
    if kind == "reducescatter":
        head = x[:shard]
        if op == Sum:
            return (_widen_for_ring(head, copy=True) * world).astype(
                head.dtype, copy=False)
        if op == Product:
            return (_widen_for_ring(head, copy=True) ** world).astype(
                head.dtype, copy=False)
        # average/min/max of `world` identical copies is the copy
        return np.array(head, copy=True)
    # alltoall: worker 0 receives chunk 0 from each of `world` identical
    # workers -> tile of the first chunk
    return np.concatenate([x[:shard]] * world, axis=0)


def _resolve_op(average: Optional[bool], op: Optional[int]) -> int:
    if op is not None and average is not None:
        raise ValueError("specify either average or op, not both")
    if op is None:
        # reference default: average=True (torch/mpi_ops.py allreduce)
        return Average if (average is None or average) else Sum
    if op not in _OP_NAMES:
        raise ValueError(f"unknown op {op}")
    return op


# ---------------------------------------------------------------------------
# Stacked / replicated encodings for eager mode
# ---------------------------------------------------------------------------

def stack_per_worker(values) -> jax.Array:
    """Place one tensor per worker: returns a global array of shape
    ``(size, *shape)`` with axis 0 sharded one-slice-per-device.

    This is the single-controller encoding of the reference's
    "each rank holds its own tensor" input model.
    """
    st = basics._ensure_init()
    if isinstance(values, (list, tuple)):
        values = jnp.stack([jnp.asarray(v) for v in values])
    else:
        values = jnp.asarray(values)
    if values.shape[0] != st.size:
        raise ValueError(
            f"stacked input must have leading dim == size ({st.size}), "
            f"got shape {values.shape}"
        )
    return jax.device_put(values, mesh_mod.worker_sharding(st.mesh))


def _is_worker_stacked(x) -> bool:
    """True if ``x`` is a jax array whose axis 0 is sharded across workers
    (the ``stack_per_worker`` layout).

    Detection is purely by sharding spec — including on a 1-device mesh,
    where ``stack_per_worker`` still attaches the worker PartitionSpec, so a
    user array that merely happens to have leading dim == size is never
    silently squeezed.
    """
    st = state_mod.global_state()
    if not isinstance(x, jax.Array) or x.ndim < 1 or x.shape[0] != st.size:
        return False
    sharding = x.sharding
    spec = getattr(sharding, "spec", None)
    if spec is None or len(spec) == 0:
        return False
    first = spec[0]
    if first is None:
        return False
    axes = first if isinstance(first, tuple) else (first,)
    return set(axes) & set(mesh_mod.GLOBAL_AXES) != set()


# ---------------------------------------------------------------------------
# Cached compiled eager programs
# ---------------------------------------------------------------------------

_jit_cache: dict[tuple, Any] = {}
_jit_cache_lock = threading.Lock()


def _cached(key, builder):
    # Every eager stacked-dispatch site fetches its compiled program here
    # at call time, so this is the one chokepoint for the ordered-lane
    # misuse check (raise instead of the documented cross-rank hang).
    _lane_check()
    with _jit_cache_lock:
        fn = _jit_cache.get(key)
        if fn is None:
            fn = builder()
            _jit_cache[key] = fn
        return fn


def clear_compiled_cache() -> None:
    """Drop cached compiled collective programs (called on shutdown so a
    re-init with a different mesh starts clean)."""
    with _jit_cache_lock:
        _jit_cache.clear()


def _replicated(mesh):
    return mesh_mod.replicated_sharding(mesh)


_noname_counters: dict = {}


def _auto_name(kind: str) -> str:
    """Call-order names for unnamed eager ops in multi-process mode —
    ranks match tensors by identical call sequence, exactly the
    reference's unnamed-op convention (reference: torch/mpi_ops.py
    'allreduce.noname.<handle>' naming)."""
    n = _noname_counters.get(kind, 0) + 1
    _noname_counters[kind] = n
    return f"{kind}.noname.{n}"


def _socket_world(st) -> bool:
    """True when this process is one rank of a multi-process world whose
    data plane is the enqueue runtime (the world is larger than the local
    mesh and jax.distributed isn't forming a global mesh) — a plain local
    array must NOT be treated as replicated there."""
    return st.size > st.mesh.size and jax.process_count() == 1


def _multiprocess_world(st) -> bool:
    """True in ANY multi-process world — socket mode or multi-controller
    jax.distributed. A plain local array is per-process data there and an
    eager collective on it must really communicate."""
    return st.size > st.mesh.size or jax.process_count() > 1


def _runtime_capable(st) -> bool:
    """True when the enqueue runtime has (or will build) a multi-process
    controller to exchange per-process data — the launcher env contract is
    present, or socket mode is active. Eager per-process collectives are
    then routed through the runtime: its background thread is the single
    issuer of dynamically-timed collective programs, so dispatch order is
    the coordinator-agreed order on every rank; issuing directly from the
    caller thread would interleave differently per rank against in-flight
    runtime programs — a distributed program mismatch (the exact hazard
    the reference's single-background-thread architecture prevents,
    reference: operations.cc:281-300).

    Without the launcher contract (externally-initialized jax.distributed)
    the runtime would have no controller — routing would re-enter this
    path from the executor and hang — so callers fall back to a direct
    global-mesh exchange on the caller thread instead."""
    import os

    return _socket_world(st) or (jax.process_count() > 1
                                 and "HOROVOD_RANK" in os.environ)


def _process_local_stacked(x, st) -> jax.Array:
    """Lift one process-local value into the worker-stacked global layout:
    each of this process's devices contributes the process's value, so
    per-worker (= per-device) semantics stay consistent with the
    single-controller replicated model. Multi-controller only — the
    direct-exchange fallback for worlds without a launcher-provided
    controller (see _runtime_capable)."""
    local = np.broadcast_to(
        np.asarray(x)[None], (st.local_size,) + np.shape(x)).copy()
    return jax.make_array_from_process_local_data(
        mesh_mod.worker_sharding(st.mesh), local)


def _is_globally_replicated(x, st) -> bool:
    """True when ``x`` is a jax.Array already replicated across the WHOLE
    mesh — the only case where "every worker holds this value" is a fact
    rather than an assumption in a multi-controller world."""
    return (isinstance(x, jax.Array) and x.sharding.is_fully_replicated
            and len(x.sharding.device_set) == st.size)


def _reduce_stacked_fn(mesh, op: int):
    """Compiled: stacked (W, *S) -> reduced (*S), replicated everywhere.

    The axis-0 reduction over a worker-sharded array compiles to an XLA
    all-reduce over ICI, exactly the role of ``MPI_Allreduce``/
    ``ncclAllReduce`` in the reference (reference: ops/mpi_operations.cc:48,
    ops/nccl_operations.cc:86-90).
    """

    def build():
        def f(x):
            if op == Average:
                return jnp.mean(x, axis=0)
            if op == Sum:
                return jnp.sum(x, axis=0)
            if op == Min:
                return jnp.min(x, axis=0)
            if op == Max:
                return jnp.max(x, axis=0)
            if op == Product:
                return jnp.prod(x, axis=0)
            raise ValueError(f"unknown op {op}")

        return jax.jit(f, out_shardings=_replicated(mesh))

    return _cached(("reduce_stacked", mesh, op), build)


def two_level_reduce_block(v, local: int, world: int, average: bool):
    """Shared RS→AR→AG body for two-level allreduce, called inside a
    shard_map block with a flat per-device vector ``v``: reduce-scatter
    over ``local`` (ICI), allreduce over ``cross`` (DCN — 1/local of the
    bytes), allgather over ``local`` (reference:
    NCCLHierarchicalAllreduce, ops/nccl_operations.cc:150-346). Used by
    both the eager stacked path and the executor's fused program."""
    n = v.shape[0]
    pad = (-n) % local
    if pad:
        v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
    s = lax.psum_scatter(v, mesh_mod.LOCAL_AXIS, scatter_dimension=0,
                         tiled=True)           # ICI: (n/local,)
    s = lax.psum(s, mesh_mod.CROSS_AXIS)       # DCN: 1/local bytes
    g = lax.all_gather(s, mesh_mod.LOCAL_AXIS, axis=0,
                       tiled=True)             # ICI: (n,)
    if average:
        g = g / world
    return g[:n]


def _hierarchical_reduce_stacked_fn(mesh, op: int):
    """Two-level allreduce over a stacked (W, *S) array (knob common.h:75).
    Only SUM/AVERAGE decompose this way (the reference's hierarchical path
    is likewise sum-only); other ops use the flat program."""

    def build():
        cross, local = mesh.devices.shape
        world = cross * local

        def inner(x):
            # per-device block (1, *S) of the stacked (W, *S) input
            return two_level_reduce_block(
                x[0].reshape(-1), local, world, average=(op == Average))

        def f(x):
            out = jax.shard_map(
                inner, mesh=mesh,
                in_specs=P(mesh_mod.GLOBAL_AXES),
                out_specs=P(), check_vma=False)(x)
            return out.reshape(x.shape[1:])

        return jax.jit(f, out_shardings=_replicated(mesh))

    return _cached(("hier_reduce_stacked", mesh, op), build)


def _hierarchical_gather_stacked_fn(mesh):
    """Two-level allgather: gather over ``local`` then over ``cross``
    (reference: MPIHierarchicalAllgather's node-then-cross structure,
    ops/mpi_operations.cc:168-314; knob common.h:76)."""

    def build():
        def inner(x):
            # block (1, s0, *S) -> full (W*s0, *S) on every device
            g = lax.all_gather(x[0], mesh_mod.LOCAL_AXIS, axis=0, tiled=True)
            g = lax.all_gather(g, mesh_mod.CROSS_AXIS, axis=0, tiled=True)
            return g

        def f(x):
            return jax.shard_map(
                inner, mesh=mesh,
                in_specs=P(mesh_mod.GLOBAL_AXES),
                out_specs=P(), check_vma=False)(x)

        return jax.jit(f, out_shardings=_replicated(mesh))

    return _cached(("hier_gather_stacked", mesh), build)


def _hierarchical_enabled(st, op: Optional[int] = None) -> bool:
    """Hierarchical path applies when configured and the mesh actually has
    two levels (reference gates on hierarchical params + homogeneity,
    nccl_operations.cc:348-355)."""
    cross, local = st.mesh.devices.shape
    if cross <= 1 or local <= 1:
        return False
    return op is None or op in (Sum, Average)


def _bcast_stacked_fn(mesh, root: int):
    def build():
        return jax.jit(
            lambda x: lax.index_in_dim(x, root, axis=0, keepdims=False),
            out_shardings=_replicated(mesh),
        )

    return _cached(("bcast_stacked", mesh, root), build)


def _gather_stacked_fn(mesh):
    def build():
        def f(x):
            # (W, s0, *S) -> (W*s0, *S): Horovod allgather concatenates
            # along the first dimension (reference: ops/mpi_operations.cc:83).
            return jnp.reshape(x, (x.shape[0] * x.shape[1],) + x.shape[2:])

        return jax.jit(f, out_shardings=_replicated(mesh))

    return _cached(("gather_stacked", mesh), build)


def _alltoall_stacked_fn(mesh, world: int):
    def build():
        def f(x):
            # (W, m, *S), m = world*k: worker i's j-th chunk goes to worker j.
            w, m = x.shape[0], x.shape[1]
            k = m // world
            y = jnp.reshape(x, (w, world, k) + x.shape[2:])
            y = jnp.swapaxes(y, 0, 1)
            return jnp.reshape(y, (w, m) + x.shape[2:])

        return jax.jit(f, out_shardings=mesh_mod.worker_sharding(mesh))

    return _cached(("alltoall_stacked", mesh, world), build)


def _reducescatter_stacked_fn(mesh, op: int, world: int):
    def build():
        def f(x):
            # (W, m, *S) -> reduce over W, scatter m into W shards:
            # output stacked (W, m/W, *S), worker i owning shard i.
            if op in (Average, Sum):
                r = jnp.sum(x, axis=0)
                if op == Average:
                    r = r / x.shape[0]
            elif op == Min:
                r = jnp.min(x, axis=0)
            elif op == Max:
                r = jnp.max(x, axis=0)
            elif op == Product:
                r = jnp.prod(x, axis=0)
            else:
                raise ValueError(f"unknown op {op}")
            return jnp.reshape(r, (world, r.shape[0] // world) + r.shape[1:])

        return jax.jit(f, out_shardings=mesh_mod.worker_sharding(mesh))

    return _cached(("rs_stacked", mesh, op, world), build)


def _integrity_check_stacked(x, name: str) -> None:
    """Eager worker-stacked payload digest: per-row non-finite counts
    name the contributing worker (row == rank) BEFORE the reduction
    collapses attribution. Tiny jnp ops cached by shape in jax's own
    executable cache; gated to every HOROVOD_INTEGRITY_INTERVAL calls
    per lane, no-op when HOROVOD_INTEGRITY is off."""
    from horovod_tpu.integrity import digest as integ_digest

    if np.dtype(x.dtype).kind not in ("f", "V"):  # V: ml_dtypes bf16
        return
    if not integ_digest.cadence_due(f"eager.{name}"):
        return
    counts = np.asarray(jnp.sum(
        ~jnp.isfinite(jnp.reshape(x, (x.shape[0], -1))), axis=1,
        dtype=jnp.int32))
    bad = np.nonzero(counts)[0]
    integ_digest.verify_local(
        int(counts.sum()), bucket="eager", tensor=name,
        suspect_rank=int(bad[0]) if bad.size else None)


# ---------------------------------------------------------------------------
# Public collectives
# ---------------------------------------------------------------------------

def allreduce(
    tensor,
    average: Optional[bool] = None,
    name: Optional[str] = None,
    op: Optional[int] = None,
    compression=Compression.none,
    axis_name=None,
):
    """Reduce a tensor across all workers; every worker gets the result.

    * In-jit (tracer input): emits ``lax.psum``/``pmean`` over the mesh axes
      — use under ``shard_map`` with the global mesh.
    * Eager: stacked ``(size, *shape)`` input reduces axis 0; a replicated
      input is treated as identical on every worker.

    reference: horovod/torch/mpi_ops.py:126-180 (API), ops chain
    horovod/common/ops/*_operations.cc (execution).
    """
    red_op = _resolve_op(average, op)
    tensor_c, ctx = compression.compress(tensor)

    if _is_tracer(tensor_c):
        axes = _global_axes(axis_name)
        if red_op == Average:
            out = lax.pmean(tensor_c, axes)
        elif red_op == Sum:
            out = lax.psum(tensor_c, axes)
        elif red_op == Min:
            out = lax.pmin(tensor_c, axes)
        elif red_op == Max:
            out = lax.pmax(tensor_c, axes)
        elif red_op == Product:
            if jnp.issubdtype(tensor_c.dtype, jnp.integer):
                # exact integer product: gather then multiply — the fp32
                # log-sum-exp round trip is off by whole units once the
                # product exceeds 2^24 (MPI_PROD is exact). The gathered
                # result is device-varying to shard_map's replication
                # checker, so re-broadcast it with a masked psum (device
                # 0's exact value) to make replication static.
                axes_t = tuple(axes) if isinstance(axes, (tuple, list)) \
                    else (axes,)
                gathered = lax.all_gather(tensor_c, axes_t)
                prod = jnp.prod(gathered, axis=0)
                flat_index = lax.axis_index(axes_t)
                out = lax.psum(
                    jnp.where(flat_index == 0, prod, jnp.zeros_like(prod)),
                    axes_t)
            else:
                # Sign/zero-correct log-sum-exp product: exp(psum(log|x|))
                # NaN-poisons on negatives and mishandles zeros, so track
                # sign parity and zero presence through separate psums
                # (all outputs statically replicated, unlike gather+prod).
                xf = tensor_c
                magnitude = jnp.exp(lax.psum(
                    jnp.log(jnp.where(xf == 0, 1.0, jnp.abs(xf))), axes))
                neg_parity = lax.psum((xf < 0).astype(jnp.int32), axes) % 2
                any_zero = lax.psum((xf == 0).astype(jnp.int32), axes) > 0
                signed = jnp.where(neg_parity == 1, -magnitude, magnitude)
                out = jnp.where(any_zero, jnp.zeros_like(signed), signed)
        else:
            raise ValueError(f"unknown op {red_op}")
        return compression.decompress(out, ctx)

    st = basics._ensure_init()
    x = _to_plane(tensor_c)
    if _is_worker_stacked(x):
        _integrity_check_stacked(x, name or "allreduce")
        if (st.config.hierarchical_allreduce
                and _hierarchical_enabled(st, red_op)):
            out = _op_event(
                "allreduce", st, x,
                lambda: _hierarchical_reduce_stacked_fn(st.mesh, red_op)(x),
                name=name)
        else:
            out = _op_event(
                "allreduce", st, x,
                lambda: _reduce_stacked_fn(st.mesh, red_op)(x),
                name=name)
    elif _multiprocess_world(st) and not _is_globally_replicated(x, st):
        # Multi-process world with a plain local array: the data lives
        # per-rank, so "replicated" math would silently return a
        # local-only result — route through the named enqueue runtime
        # (auto call-order name, like the reference's unnamed torch ops),
        # whose background thread is the single ordered issuer of
        # collective programs (see _runtime_capable).
        if _runtime_capable(st):
            return synchronize(allreduce_async(
                tensor, average=average, op=op, compression=compression,
                name=name or _auto_name("allreduce")))
        # no controller (externally-initialized jax.distributed):
        # direct global-mesh exchange on the caller thread
        stacked = _process_local_stacked(x, st)
        if (st.config.hierarchical_allreduce
                and _hierarchical_enabled(st, red_op)):
            out = _hierarchical_reduce_stacked_fn(st.mesh, red_op)(stacked)
        else:
            out = _reduce_stacked_fn(st.mesh, red_op)(stacked)
    else:
        # Replicated: every worker holds the same value.
        if red_op in (Average, Min, Max):
            # never alias the caller's buffer: for 64-bit numpy inputs
            # _to_plane is the identity, and returning the input object
            # would let later in-place mutation corrupt the "result"
            out = np.array(x, copy=True) \
                if not isinstance(x, jax.Array) else x
        elif red_op == Sum:
            out = x * st.size
        elif red_op == Product:
            out = x ** st.size
        else:
            raise ValueError(f"unknown op {red_op}")
    return compression.decompress(out, ctx)


def grouped_allreduce(
    tensors: Sequence,
    average: Optional[bool] = None,
    name: Optional[str] = None,
    op: Optional[int] = None,
    compression=Compression.none,
    axis_name=None,
):
    """Allreduce a list of tensors as one logical operation (the analogue
    of the reference's explicitly grouped fusion).

    In-jit, XLA fuses the psums. Eager worker-stacked inputs of the same
    dtype genuinely share one dispatch: they are flattened, concatenated
    and reduced as one program, then split back. Everything else (plain
    arrays, mixed cases) falls through to individual allreduce — in the
    multi-process socket world those ride the runtime, whose tensor
    fusion batches them anyway."""
    tensors = list(tensors)
    if not tensors:
        return []
    if _is_tracer(tensors[0]):
        return [allreduce(t, average=average, op=op, compression=compression,
                          axis_name=axis_name) for t in tensors]

    st = basics._ensure_init()
    arrays = [_to_plane(t) for t in tensors]
    out: list = [None] * len(arrays)
    groups: dict = {}
    plain: list = []
    for i, a in enumerate(arrays):
        if _is_worker_stacked(a) and a.ndim >= 1:
            groups.setdefault(str(a.dtype), []).append(i)
        else:
            plain.append(i)
    if plain and _multiprocess_world(st) and _runtime_capable(st):
        # multi-process: enqueue every per-process plain tensor first so
        # they are all in flight in the same cycle — the runtime's tensor
        # fusion then batches them, matching the reference's grouped
        # guarantee. Globally replicated tensors skip the round trip (and
        # keep min/max/product working), same as single allreduce.
        handles = []
        for i in plain:
            if _is_globally_replicated(arrays[i], st):
                out[i] = allreduce(arrays[i], average=average, op=op,
                                   compression=compression,
                                   axis_name=axis_name)
            else:
                handles.append((i, allreduce_async(
                    tensors[i], average=average, op=op,
                    compression=compression,
                    name=_auto_name("grouped_allreduce"))))
        for i, h in handles:
            out[i] = synchronize(h)
    else:
        for i in plain:
            out[i] = allreduce(tensors[i], average=average, op=op,
                               compression=compression, axis_name=axis_name)
    for idxs in groups.values():
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = allreduce(arrays[i], average=average, op=op,
                               compression=compression, axis_name=axis_name)
            continue
        world = arrays[idxs[0]].shape[0]
        flat = [arrays[i].reshape(world, -1) for i in idxs]
        fused = allreduce(jnp.concatenate(flat, axis=1), average=average,
                          op=op, compression=compression,
                          axis_name=axis_name)
        offset = 0
        for i, f in zip(idxs, flat):
            n = f.shape[1]
            out[i] = fused[offset:offset + n].reshape(arrays[i].shape[1:])
            offset += n
    return out


def _op_event(op: str, st, x, fn, name: Optional[str] = None):
    """Bracket an eager single-controller collective dispatch with
    flight-recorder ``op_dispatch``/``op_complete`` events (shard index +
    bytes) and a ``collective:<name>`` tracing span, mirroring the
    executor's events on the multi-process path — postmortems attribute a
    stalled sharded step to the right phase, and eager collectives land on
    the same Perfetto lane as the enqueue runtime's (tracing.py)."""
    nbytes = int(np.prod(np.shape(x), dtype=np.int64)
                 * np.dtype(x.dtype).itemsize)
    flight_recorder.emit("op_dispatch", op=op, shard=int(st.rank),
                         bytes=nbytes)
    t0 = time.monotonic()
    t0_epoch = time.time()
    out = fn()
    total = time.monotonic() - t0
    flight_recorder.emit("op_complete", op=op, shard=int(st.rank),
                         bytes=nbytes, seconds=round(total, 6))
    # comms plane: eager single-controller collectives ride the fused
    # XLA "device" lane (docs/comms.md lane taxonomy)
    comms.record(op, "device", nbytes, total, world=int(st.size))
    if tracing.enabled():
        tracing.record("collective:" + str(name or op), t0_epoch, total,
                       op=op, bytes=nbytes)
    return out


def allgather(tensor, name: Optional[str] = None, axis_name=None):
    """Concatenate each worker's tensor along axis 0; all workers get the
    concatenation.

    Eager stacked input ``(size, s0, *S)`` yields ``(size*s0, *S)``. Ragged
    first dimensions (the reference supports per-rank sizes via negotiated
    recvcounts, reference: ops/collective_operations.cc:87-127) are passed
    as a Python list of per-worker arrays.
    """
    if _is_tracer(tensor):
        return lax.all_gather(tensor, _global_axes(axis_name), axis=0, tiled=True)

    st = basics._ensure_init()
    if isinstance(tensor, (list, tuple)):
        if len(tensor) != st.size:
            raise ValueError(
                f"ragged allgather needs one tensor per worker ({st.size}), "
                f"got {len(tensor)}"
            )
        shapes = {tuple(np.shape(t)[1:]) for t in tensor}
        if len(shapes) > 1:
            # reference: coordinator shape validation raises on mismatched
            # non-first dimensions (controller.cc:320-522).
            raise ValueError(
                f"allgather tensors must match in all but the first "
                f"dimension, got trailing shapes {sorted(shapes)}"
            )
        parts = [_to_plane(t) for t in tensor]
        if any(not isinstance(p, jax.Array) for p in parts):
            # 64-bit payload: concat exactly on host (see _to_plane)
            return np.concatenate([np.asarray(p) for p in parts], axis=0)
        out = jnp.concatenate(parts, axis=0)
        return jax.device_put(out, _replicated(st.mesh))

    x = _to_plane(tensor)
    if _is_worker_stacked(x):
        if x.ndim < 2:
            raise ValueError(
                "allgather concatenates along dim 0, so per-worker tensors "
                "must have rank >= 1 (stacked input rank >= 2); got shape "
                f"{x.shape}"
            )
        if (st.config.hierarchical_allgather
                and _hierarchical_enabled(st)):
            return _op_event(
                "allgather", st, x,
                lambda: _hierarchical_gather_stacked_fn(st.mesh)(x))
        return _op_event("allgather", st, x,
                         lambda: _gather_stacked_fn(st.mesh)(x))
    if x.ndim < 1:
        raise ValueError("allgather requires tensors of rank >= 1")
    if _multiprocess_world(st) and not _is_globally_replicated(x, st):
        # Multi-process world: each rank holds its own tensor — ride the
        # enqueue runtime rather than faking the concat locally (and so
        # the background thread keeps collective-program order agreed).
        if _runtime_capable(st):
            return synchronize(allgather_async(
                tensor, name=name or _auto_name("allgather")))
        stacked = _process_local_stacked(x, st)
        if (st.config.hierarchical_allgather and _hierarchical_enabled(st)):
            return _hierarchical_gather_stacked_fn(st.mesh)(stacked)
        return _gather_stacked_fn(st.mesh)(stacked)
    # Replicated: every worker contributes the same tensor.
    if not isinstance(x, jax.Array):  # 64-bit numpy payload (_to_plane)
        return np.concatenate([x] * st.size, axis=0)
    return jnp.concatenate([x] * st.size, axis=0)


def broadcast(tensor, root_rank: int, name: Optional[str] = None, axis_name=None):
    """Every worker receives worker ``root_rank``'s tensor.

    reference: horovod/torch/mpi_ops.py broadcast / ops/mpi_operations.cc:326.
    """
    if _is_tracer(tensor):
        # Masked psum: only the root contributes, and the psum output is
        # statically replicated over the mesh axes — one collective, no
        # gather+index. (The reference's MPI_Bcast analogue.)
        axes = _global_axes(axis_name)
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        flat_index = lax.axis_index(tuple(axes))
        masked = jnp.where(flat_index == root_rank, tensor,
                           jnp.zeros_like(tensor))
        # psum promotes bool to int32 — restore the input dtype so
        # jit/eager agree
        return lax.psum(masked, tuple(axes)).astype(tensor.dtype)

    st = basics._ensure_init()
    if not 0 <= root_rank < st.size:
        raise ValueError(f"root_rank {root_rank} out of range [0, {st.size})")
    x = _to_plane(tensor)
    if _is_worker_stacked(x):
        return _bcast_stacked_fn(st.mesh, root_rank)(x)
    if _multiprocess_world(st) and not _is_globally_replicated(x, st):
        # Multi-process world: the root's value must actually travel (the
        # reference's MPI_Bcast role in checkpoint restore,
        # torch/__init__.py:255-403) — through the runtime so the
        # background thread keeps collective-program order agreed.
        if _runtime_capable(st):
            return synchronize(broadcast_async(
                tensor, root_rank, name=name or _auto_name("broadcast")))
        return _bcast_stacked_fn(st.mesh, root_rank)(
            _process_local_stacked(x, st))
    # Single-controller: values are already globally consistent; force the
    # replicated layout over the mesh so downstream steps see it.
    if not isinstance(x, jax.Array):  # 64-bit numpy payload (_to_plane)
        return np.array(x, copy=True)
    return jax.device_put(x, _replicated(st.mesh))


def reducescatter(tensor, average: Optional[bool] = None, op: Optional[int] = None,
                  axis_name=None):
    """Reduce across workers and scatter the result: worker i gets shard i
    of the reduced tensor (TPU extension; the building block of the
    hierarchical allreduce, reference: ops/nccl_operations.cc:150-346)."""
    red_op = _resolve_op(average, op)
    if _is_tracer(tensor):
        axes = _global_axes(axis_name)
        if red_op in (Average, Sum):
            out = lax.psum_scatter(tensor, axes, scatter_dimension=0,
                                   tiled=True)
            if red_op == Average:
                # divide by the size of the axes actually reduced, not
                # the global world size (they differ for axis_name='local')
                out = out / compat.axis_size(axes)
            return out
        # XLA's reduce-scatter primitive is sum-only; min/max/product
        # decompose into all_to_all + local reduce — same bytes on the
        # wire as a reduce-scatter (each device sends shard j to owner j)
        world = compat.axis_size(axes)
        if tensor.shape[0] % world != 0:
            raise ValueError(
                f"reducescatter dim 0 ({tensor.shape[0]}) must divide "
                f"evenly by the axis size ({world})")
        xr = tensor.reshape((world, tensor.shape[0] // world)
                            + tensor.shape[1:])
        got = lax.all_to_all(xr, axes, split_axis=0, concat_axis=0)
        reducer = {Min: jnp.min, Max: jnp.max, Product: jnp.prod}[red_op]
        return reducer(got, axis=0)

    st = basics._ensure_init()
    x = _to_plane(tensor)
    if not _is_worker_stacked(x):
        if _multiprocess_world(st) and _runtime_capable(st):
            # per-process data: route through the runtime lane like
            # allreduce (each rank contributes its local tensor, receives
            # its shard of the reduction)
            from horovod_tpu.runtime.runtime import get_runtime

            return synchronize(get_runtime().enqueue_reducescatter(
                _auto_name("reducescatter"), x,
                reduce_op=_OP_NAMES[red_op]))
        raise ValueError("eager reducescatter requires stacked per-worker input")
    if x.ndim < 2:
        raise ValueError(
            "reducescatter scatters along dim 0 of per-worker tensors, so "
            f"stacked input must have rank >= 2; got shape {x.shape}"
        )
    if x.shape[1] % st.size != 0:
        raise ValueError(
            f"reducescatter dim 1 ({x.shape[1]}) must divide evenly by "
            f"size ({st.size})"
        )
    return _op_event(
        "reducescatter", st, x,
        lambda: _reducescatter_stacked_fn(st.mesh, red_op, st.size)(x))


def alltoall(tensor, name: Optional[str] = None, axis_name=None):
    """Each worker splits its tensor into ``size`` chunks along axis 0 and
    sends chunk j to worker j (TPU extension; enables Ulysses-style sequence
    parallelism)."""
    if _is_tracer(tensor):
        return lax.all_to_all(
            tensor, _global_axes(axis_name), split_axis=0, concat_axis=0,
            tiled=True,
        )

    st = basics._ensure_init()
    x = _to_plane(tensor)
    if not _is_worker_stacked(x):
        if _multiprocess_world(st) and _runtime_capable(st):
            from horovod_tpu.runtime.runtime import get_runtime

            return synchronize(get_runtime().enqueue_alltoall(
                name or _auto_name("alltoall"), x))
        raise ValueError("eager alltoall requires stacked per-worker input")
    if x.ndim < 2:
        raise ValueError(
            "alltoall splits along dim 0 of per-worker tensors, so stacked "
            f"input must have rank >= 2; got shape {x.shape}"
        )
    if x.shape[1] % st.size != 0:
        raise ValueError(
            f"alltoall dim 1 ({x.shape[1]}) must divide evenly by size "
            f"({st.size})"
        )
    return _alltoall_stacked_fn(st.mesh, st.size)(x)


# ---------------------------------------------------------------------------
# Async handles
# ---------------------------------------------------------------------------

class Handle:
    """Future for an async collective.

    XLA dispatch is already asynchronous — the returned ``jax.Array`` is a
    future whose buffer materializes when the collective completes on
    device. This class carries the reference's handle API on top
    (reference: horovod/torch/handle_manager.cc, mpi_ops.py:93-124). Unlike
    the reference there is no global handle table to leak: the handle owns
    its result and is garbage-collected with it.
    """

    __slots__ = ("_result",)

    def __init__(self, result):
        self._result = result

    def poll(self) -> bool:
        # Exceptions surface here, not swallowed: an error inside
        # is_ready() (e.g. a failed async computation) must reach the
        # caller that polled, not masquerade as "complete" and then raise
        # from an unrelated wait() later. Duck-typed on is_ready so
        # non-array leaves (python scalars in a result tree) pass through.
        leaves = jax.tree_util.tree_leaves(self._result)
        return all(
            leaf.is_ready() for leaf in leaves if hasattr(leaf, "is_ready")
        )

    def wait(self):
        return jax.block_until_ready(self._result)


def allreduce_async(tensor, average=None, name=None, op=None,
                    compression=Compression.none, priority=0):
    """Async allreduce. With a ``name``, the tensor enters the dynamic
    enqueue runtime — per-tensor negotiation, response cache and tensor
    fusion, the reference's core execution model (reference:
    operations.cc:736-768 EnqueueTensorAllreduce). Unnamed tensors dispatch
    immediately (XLA's async dispatch already overlaps). ``priority``
    orders runtime tensors within a cycle, highest first (reference:
    horovod/mxnet/mpi_ops.py:52)."""
    if name is not None:
        red_op = _resolve_op(average, op)
        from horovod_tpu.runtime.runtime import get_runtime

        x, ctx = compression.compress(
            _to_plane(tensor))
        handle = get_runtime().enqueue_allreduce(
            name, x, reduce_op=_OP_NAMES[red_op], priority=priority)
        handle._decompress = (compression, ctx)  # applied in synchronize()
        return handle
    return Handle(allreduce(tensor, average=average, op=op,
                            compression=compression))


def grouped_allreduce_async(tensors, names, average=None, op=None,
                            reduce_op=None, priority=0,
                            group_callback=None):
    """Async grouped allreduce through the runtime: the whole group is
    enqueued atomically (``Runtime.enqueue_allreduce_group``) so one
    negotiation cycle sees it and the fusion planner packs it into as few
    dispatches as ``HOROVOD_FUSION_THRESHOLD`` allows. This is the wire
    primitive behind bucket-wise gradient release
    (:class:`horovod_tpu.parallel.buckets.GradReleasePlan`): each bucket
    becomes one grouped enqueue, released while backward is still
    running. Returns one handle per tensor, in order; ``group_callback``
    fires on the cycle thread per completion (see the runtime method)."""
    tensors = list(tensors)
    names = list(names)
    if len(tensors) != len(names):
        raise ValueError("tensors and names must pair up")
    if not tensors:
        return []
    if reduce_op is None:
        red_op = _resolve_op(average, op)
        reduce_op = _OP_NAMES[red_op]
    elif average is not None or op is not None:
        raise ValueError("specify reduce_op or average/op, not both")
    from horovod_tpu.runtime.runtime import get_runtime

    return get_runtime().enqueue_allreduce_group(
        names, [_to_plane(t) for t in tensors], reduce_op=reduce_op,
        priority=priority, group_callback=group_callback)


def allgather_async(tensor, name=None, priority=0):
    if name is not None:
        from horovod_tpu.runtime.runtime import get_runtime

        return get_runtime().enqueue_allgather(
            name, _to_plane(tensor), priority=priority)
    return Handle(allgather(tensor))


def broadcast_async(tensor, root_rank, name=None, priority=0):
    if name is not None:
        from horovod_tpu.runtime.runtime import get_runtime

        return get_runtime().enqueue_broadcast(
            name, _to_plane(tensor), root_rank, priority=priority)
    return Handle(broadcast(tensor, root_rank))


def poll(handle: Handle) -> bool:
    """True if the collective backing ``handle`` has completed
    (reference: horovod/torch/mpi_ops.py:93-105)."""
    return handle.poll()


def synchronize(handle):
    """Block until the collective completes and return its result
    (reference: horovod/torch/mpi_ops.py:107-124). Accepts both immediate
    handles and runtime handles."""
    out = handle.wait()
    decompress = getattr(handle, "_decompress", None)
    if decompress is not None and out is not None:
        compression, ctx = decompress
        out = compression.decompress(out, ctx)
    return out
