"""Pallas TPU kernels for the framework's hot ops."""

from horovod_tpu.ops.pallas.flash_attention import (
    attention_reference,
    flash_attention,
    flash_attention_partial,
    merge_partials,
)

__all__ = [
    "flash_attention",
    "flash_attention_partial",
    "merge_partials",
    "attention_reference",
]
