"""Pallas TPU kernels for the framework's hot ops."""

from horovod_tpu.ops.pallas.flash_attention import (
    attention_reference,
    flash_attention,
    flash_attention_partial,
    merge_partials,
)
from horovod_tpu.ops.pallas.fused_adamw import FusedAdamW, fused_adamw
from horovod_tpu.ops.pallas.fused_optimizer import flat_adamw_shard
from horovod_tpu.ops.pallas.conv_bn_act import (
    FusedBatchNormAct,
    bn_stats,
    scale_bias_act,
)

__all__ = [
    "flash_attention",
    "flash_attention_partial",
    "merge_partials",
    "attention_reference",
    "fused_adamw",
    "FusedAdamW",
    "flat_adamw_shard",
    "FusedBatchNormAct",
    "bn_stats",
    "scale_bias_act",
]
