"""Fused batch-norm + activation epilogue for the conv models.

Productization of the ``tools/pallas_conv_bn.py`` prototype: the
Inception/ResNet decompositions (tools/*_decompose.py) show the conv
stacks spend a measurable slice of every ConvBN in the *elementwise
tail* — normalize, scale/shift, ReLU — which XLA emits as its own
HBM-bound loop over the conv output. The prototype measured the win of
folding that tail into one pass; this module ships the production
half that composes with autodiff and checkpoints:

* :func:`bn_stats` — one-pass per-channel mean/variance in f32 (sum and
  sum-of-squares in the same sweep, the prototype's epilogue contract).
* :func:`scale_bias_act` — ``relu(x * s + b)`` as a Pallas kernel with
  a ``custom_vjp`` (jnp backward), so the folded BN apply is one
  VMEM-resident pass instead of XLA's normalize → scale → clamp chain.
* :class:`FusedBatchNormAct` — drop-in for ``nn.BatchNorm`` + ``relu``
  with identical variable names/shapes ("scale"/"bias" params,
  "mean"/"var" batch stats, same momentum update), so checkpoints
  interchange with the unfused ConvBN.

Kernel gating is honest about TPU lane tiling: the channel axis must
pack lanes exactly — ``C % 128 == 0``, or ``128 % C == 0`` (lane rows
tile ``128/C`` whole channel groups — covers the stem/reduction convs'
C ∈ {32, 64}). Everything else, tracers, and non-TPU backends take the
jnp path, which is also the custom_vjp backward everywhere.
``HOROVOD_FUSED_BN_ACT`` forces the kernel on/off (default: auto — on
for a TPU default backend); ``HOROVOD_PALLAS_INTERPRET`` runs the
kernel in interpret mode for tests (same switch as the other kernels).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from horovod_tpu.ops.pallas.fused_adamw import _use_interpret
from horovod_tpu.utils import env as env_mod

# Same launch-worthiness floor as the other kernels.
_MIN_PALLAS = 16 * 1024
_BLOCK_ROWS = 512


def _use_kernel() -> bool:
    default = jax.devices()[0].platform == "tpu"
    return env_mod._get_bool("HOROVOD_FUSED_BN_ACT", default)


def bn_stats(x):
    """Per-channel (last axis) batch mean and variance in one f32 pass.

    ``var = E[x^2] - E[x]^2`` — the same estimator ``nn.BatchNorm``
    uses, so the fused module is numerically interchangeable with it."""
    xf = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(xf, axis=axes)
    var = jnp.mean(xf * xf, axis=axes) - mean * mean
    return mean, var


def _sba_kernel(x_ref, s_ref, b_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    y = x * s_ref[...] + b_ref[...]
    o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)


def _sba_jnp(x, s, b):
    y = x.astype(jnp.float32) * s + b
    return jnp.maximum(y, 0.0).astype(x.dtype)


def _sba_pallas(x, s, b):
    """relu(x*s + b) with per-channel f32 ``s``/``b``; returns None when
    the shape doesn't pack TPU lanes (caller falls back to jnp)."""
    c = x.shape[-1]
    n = x.size
    if n < _MIN_PALLAS:
        return None
    if c % 128 == 0:
        lanes = 128
        reps = 1
    elif c <= 128 and 128 % c == 0:
        # tile 128/c whole channel groups per lane row
        lanes = 128
        reps = 128 // c
    else:
        return None
    if n % lanes:
        return None
    rows = n // lanes
    block_rows = min(rows, _BLOCK_ROWS)
    while rows % block_rows:
        block_rows -= 1
    if block_rows < 8:
        return None
    if c % 128 == 0:
        # lane rows walk the channel axis in 128-wide slabs: row r covers
        # channels [(r % (c//128))*128, ...) — broadcast s/b to the same
        # (rows, 128) layout
        s2 = s.reshape(1, c // 128, 128)
        s2 = jnp.broadcast_to(s2, (rows // (c // 128), c // 128, 128)) \
            .reshape(rows, 128)
        b2 = b.reshape(1, c // 128, 128)
        b2 = jnp.broadcast_to(b2, (rows // (c // 128), c // 128, 128)) \
            .reshape(rows, 128)
    else:
        tiled_s = jnp.tile(s, reps)
        tiled_b = jnp.tile(b, reps)
        s2 = jnp.broadcast_to(tiled_s, (rows, 128))
        b2 = jnp.broadcast_to(tiled_b, (rows, 128))
    spec = pl.BlockSpec((block_rows, 128), lambda i: (i, 0))
    out = pl.pallas_call(
        _sba_kernel,
        grid=(rows // block_rows,),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, 128), x.dtype),
        interpret=_use_interpret(),
    )(x.reshape(rows, 128), s2, b2)
    return out.reshape(x.shape)


@jax.custom_vjp
def scale_bias_act(x, s, b):
    """``relu(x * s + b)`` with per-channel f32 scale/bias.

    The forward runs as one Pallas pass when the shape packs TPU lanes
    (see module docstring); the backward is the standard masked chain in
    jnp — XLA fuses it into the surrounding conv backward anyway."""
    if x.ndim >= 1 and _use_kernel():
        # shape gating is static, so this composes with jit/scan traces
        out = _sba_pallas(x, s, b)
        if out is not None:
            return out
    return _sba_jnp(x, s, b)


def _sba_fwd(x, s, b):
    return scale_bias_act(x, s, b), (x, s, b)


def _sba_bwd(res, g):
    x, s, b = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    mask = (xf * s + b) > 0.0
    gm = jnp.where(mask, gf, 0.0)
    axes = tuple(range(x.ndim - 1))
    dx = (gm * s).astype(x.dtype)
    ds = jnp.sum(gm * xf, axis=axes)
    db = jnp.sum(gm, axis=axes)
    return dx, ds, db


scale_bias_act.defvjp(_sba_fwd, _sba_bwd)


try:  # flax is present in this environment, but keep the ops importable
    import flax.linen as nn
except Exception:  # pragma: no cover - flax-less import of the op layer
    nn = None


if nn is not None:

    class FusedBatchNormAct(nn.Module):
        """``nn.BatchNorm(momentum, epsilon)`` + ``relu`` as one fused
        epilogue, with identical variable names and update rules."""

        momentum: float = 0.9
        epsilon: float = 1e-3
        dtype: Any = jnp.bfloat16

        @nn.compact
        def __call__(self, x, use_running_average: bool = False):
            c = x.shape[-1]
            scale = self.param("scale", nn.initializers.ones, (c,),
                               jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (c,),
                              jnp.float32)
            ra_mean = self.variable("batch_stats", "mean",
                                    lambda: jnp.zeros((c,), jnp.float32))
            ra_var = self.variable("batch_stats", "var",
                                   lambda: jnp.ones((c,), jnp.float32))
            if use_running_average:
                mean, var = ra_mean.value, ra_var.value
            else:
                mean, var = bn_stats(x)
                if not self.is_initializing():
                    m = self.momentum
                    ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
                    ra_var.value = m * ra_var.value + (1.0 - m) * var
            s = scale * jax.lax.rsqrt(var + self.epsilon)
            b = bias - mean * s
            return scale_bias_act(x, s, b)
