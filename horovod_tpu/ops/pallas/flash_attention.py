"""Fused blockwise (flash) attention as a Pallas TPU kernel.

The hot op of the transformer model family. Online-softmax attention that
never materialises the ``(seq, seq)`` score matrix: per query block, key/value
blocks stream through VMEM while a running (max, sum, accumulator) triple is
maintained — the MXU does the two matmuls, the VPU the rescaling. A custom
VJP provides the matching blockwise backward kernels (dq; dk/dv), so memory
stays O(seq · head_dim) end to end.

This kernel is also the *local* building block of ring attention
(horovod_tpu/parallel/ring.py): it accepts dynamic ``q_offset``/``k_offset``
global position scalars and returns the per-row log-sum-exp, so partial
results computed against one shard of keys/values can be merged exactly
across ppermute steps (see ``merge_partials``).

The reference framework has no attention kernels at all (it is a pure
data-parallel gradient-averaging layer — SURVEY.md §5.7); this module is part
of the TPU-first long-context extension, not a port.

On non-TPU backends (CPU tests) the kernels run in Pallas interpret mode;
set ``HOROVOD_PALLAS_INTERPRET=0/1`` to force either way.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

NEG_INF = float("-inf")


def _use_interpret() -> bool:
    env = os.environ.get("HOROVOD_PALLAS_INTERPRET")
    if env is not None:
        return env not in ("0", "false", "False")
    return jax.devices()[0].platform != "tpu"


def _vma(*arrays) -> frozenset:
    """Union of the inputs' varying-mesh-axes, so pallas_call outputs carry
    the right vma under ``shard_map(check_vma=True)``."""
    out = frozenset()
    for a in arrays:
        out |= getattr(jax.typeof(a), "vma", frozenset())
    return out


def _pick_block(seq: int, requested: int) -> int:
    """Largest block ≤ requested that divides seq (power-of-two friendly)."""
    b = min(requested, seq)
    while seq % b:
        b -= 1
    return b


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                *, sm_scale, causal, block_q, block_k, kv_seq):
    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32) * sm_scale  # (bq, d)
    nk = kv_seq // block_k

    q_start = q_off_ref[0] + qi * block_q
    q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)

    if causal:
        # Only k blocks whose first global id can be <= the last q id.
        last_q = q_start + block_q - 1
        nk_dyn = jnp.clip(
            (last_q - k_off_ref[0]) // block_k + 1, 0, nk)
    else:
        nk_dyn = nk

    def body(j, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            k_ids = (k_off_ref[0] + j * block_k
                     + jax.lax.broadcasted_iota(
                         jnp.int32, (block_q, block_k), 1))
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        # Rows with every key masked so far have m_new == -inf; subtracting
        # -inf would give NaN, so shift by a safe 0 instead — every exp()
        # argument is then -inf and the row correctly accumulates nothing.
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        alpha = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m, l, acc = jax.lax.fori_loop(0, nk_dyn, body, (m0, l0, acc0))

    # Fully-masked rows (l == 0): output 0, lse -inf so a later merge
    # treats this partial as absent.
    empty = l == 0.0
    l_safe = jnp.where(empty, 1.0, l)
    m_fin = jnp.where(empty, 0.0, m)
    o_ref[0, 0, :, :] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse = jnp.where(empty, NEG_INF, m_fin + jnp.log(l_safe))
    # Row vectors are stored broadcast across LANES lanes to satisfy TPU
    # tiling (same layout as the stock TPU flash kernel's l/m buffers).
    lse_ref[0, 0, :, :] = jax.lax.broadcast_in_dim(
        lse, (block_q, LANES), (0,))


# Per-row scalars (lse, delta) are stored as (B, H, S, LANES) with the value
# broadcast across lanes, satisfying the TPU (8, 128) tiling constraint.
LANES = 128


def _make_specs(block_q, block_k, dim, q_seq, kv_seq):
    """Common BlockSpecs: q-like blocks, full-sequence k/v, row vectors."""
    q_spec = pl.BlockSpec((1, 1, block_q, dim), lambda b, h, i: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, kv_seq, dim), lambda b, h, i: (b, h, 0, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, LANES),
                            lambda b, h, i: (b, h, i, 0))
    return q_spec, kv_spec, row_spec


# The scalar offsets ride as int32 arrays of shape (1,); gridded kernels see
# the whole array in scalar memory, indexed as ref[0].
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

_OFF_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)


def _flash_fwd(q, k, v, q_offset, k_offset, *, sm_scale, causal,
               block_q, block_k, interpret):
    batch, heads, q_seq, dim = q.shape
    kv_seq = k.shape[2]
    block_q = _pick_block(q_seq, block_q)
    block_k = _pick_block(kv_seq, block_k)
    grid = (batch, heads, q_seq // block_q)
    q_spec, kv_spec, row_spec = _make_specs(block_q, block_k, dim,
                                            q_seq, kv_seq)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_seq=kv_seq)

    vma = _vma(q, k, v, q_offset, k_offset)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_OFF_SPEC, _OFF_SPEC, q_spec, kv_spec, kv_spec],
        out_specs=[q_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype, vma=vma),
            jax.ShapeDtypeStruct((batch, heads, q_seq, LANES), jnp.float32,
                                 vma=vma),
        ],
        interpret=interpret,
    )(q_offset, k_offset, q, k, v)
    return o, lse  # lse lane-broadcast: (B, H, S, LANES)


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dq_ref,
                   *, sm_scale, causal, block_q, block_k, kv_seq):
    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32)
    do = do_ref[0, 0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]
    delta = delta_ref[0, 0, :, 0]
    nk = kv_seq // block_k

    q_start = q_off_ref[0] + qi * block_q
    q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    # Fully-masked rows have lse = -inf and all s = -inf; shifting by 0
    # instead of -inf keeps exp(s - lse) at 0 rather than NaN.
    lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)

    if causal:
        last_q = q_start + block_q - 1
        nk_dyn = jnp.clip((last_q - k_off_ref[0]) // block_k + 1, 0, nk)
    else:
        nk_dyn = nk

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            k_ids = (k_off_ref[0] + j * block_k
                     + jax.lax.broadcasted_iota(
                         jnp.int32, (block_q, block_k), 1))
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        p = jnp.exp(s - lse_safe[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, nk_dyn, body, jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32))
    dq_ref[0, 0, :, :] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref,
                    *, sm_scale, causal, block_q, block_k, q_seq):
    ki = pl.program_id(2)
    k = k_ref[0, 0, :, :].astype(jnp.float32)
    v = v_ref[0, 0, :, :].astype(jnp.float32)
    nq = q_seq // block_q

    k_start = k_off_ref[0] + ki * block_k
    k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    if causal:
        # First q block whose last global id can be >= the first k id.
        j0 = jnp.clip((k_start - q_off_ref[0]) // block_q, 0, nq)
    else:
        j0 = 0

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(j * block_q, block_q), 0]
        delta = delta_ref[0, 0, pl.ds(j * block_q, block_q), 0]
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            q_ids = (q_off_ref[0] + j * block_q
                     + jax.lax.broadcasted_iota(
                         jnp.int32, (block_q, block_k), 0))
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        p = jnp.exp(s - lse_safe[:, None])
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dim = k_ref.shape[-1]
    dk0 = jnp.zeros((block_k, dim), jnp.float32)
    dv0 = jnp.zeros((block_k, dim), jnp.float32)
    dk, dv = jax.lax.fori_loop(j0, nq, body, (dk0, dv0))
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, q_offset, k_offset, *, sm_scale, causal,
               block_q, block_k, interpret):
    batch, heads, q_seq, dim = q.shape
    kv_seq = k.shape[2]
    block_q = _pick_block(q_seq, block_q)
    block_k = _pick_block(kv_seq, block_k)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))

    q_spec, kv_spec, row_spec = _make_specs(block_q, block_k, dim,
                                            q_seq, kv_seq)

    vma = _vma(q, k, v, do, q_offset, k_offset)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, kv_seq=kv_seq),
        grid=(batch, heads, q_seq // block_q),
        in_specs=[_OFF_SPEC, _OFF_SPEC, q_spec, kv_spec, kv_spec, q_spec,
                  row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype, vma=vma),
        interpret=interpret,
    )(q_offset, k_offset, q, k, v, do, lse, delta)

    # dk/dv: grid over k blocks; q-side tensors stream via pl.ds.
    k_block_spec = pl.BlockSpec((1, 1, block_k, dim),
                                lambda b, h, i: (b, h, i, 0))
    q_full_spec = pl.BlockSpec((1, 1, q_seq, dim), lambda b, h, i: (b, h, 0, 0))
    row_full_spec = pl.BlockSpec((1, 1, q_seq, LANES),
                                 lambda b, h, i: (b, h, 0, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, q_seq=q_seq),
        grid=(batch, heads, kv_seq // block_k),
        in_specs=[_OFF_SPEC, _OFF_SPEC, q_full_spec, k_block_spec,
                  k_block_spec, q_full_spec, row_full_spec, row_full_spec],
        out_specs=[k_block_spec, k_block_spec],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype, vma=vma),
            jax.ShapeDtypeStruct(v.shape, v.dtype, vma=vma),
        ],
        interpret=interpret,
    )(q_offset, k_offset, q, k, v, do, lse, delta)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API: differentiable flash attention (+ residuals for ring merging)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash(q, k, v, q_offset, k_offset, sm_scale, causal, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, q_offset, k_offset, sm_scale=sm_scale,
                      causal=causal, block_q=block_q, block_k=block_k,
                      interpret=_use_interpret())
    return o


def _flash_vjp_fwd(q, k, v, q_offset, k_offset, sm_scale, causal,
                   block_q, block_k):
    o, lse = _flash_fwd(q, k, v, q_offset, k_offset, sm_scale=sm_scale,
                        causal=causal, block_q=block_q, block_k=block_k,
                        interpret=_use_interpret())
    return o, (q, k, v, o, lse, q_offset, k_offset)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse, q_offset, k_offset = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, q_offset, k_offset,
                            sm_scale=sm_scale, causal=causal,
                            block_q=block_q, block_k=block_k,
                            interpret=_use_interpret())
    zero = jnp.zeros((1,), jnp.int32)
    return dq, dk, dv, zero, zero


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _as_offset(x) -> jax.Array:
    return jnp.asarray(x, jnp.int32).reshape((1,))


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Fused attention over ``(batch, heads, seq, head_dim)`` inputs.

    ``q_offset``/``k_offset`` are the global sequence positions of the first
    query/key row — used by ring attention, where each device holds one
    sequence shard and the causal mask depends on global, not local, indices.
    They may be traced scalars (e.g. derived from ``lax.axis_index``).
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError("flash_attention expects (batch, heads, seq, dim)")
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    return _flash(q, k, v, _as_offset(q_offset), _as_offset(k_offset),
                  float(sm_scale), bool(causal), int(block_q), int(block_k))


def flash_attention_partial(
    q, k, v, *, causal=False, sm_scale=None, q_offset=0, k_offset=0,
    block_q: int = 128, block_k: int = 128,
):
    """Forward-only partial attention returning ``(out, lse)``.

    ``out`` is normalised over the *local* keys only; ``lse`` is the per-row
    log-sum-exp normaliser, so partials over disjoint key shards can be
    combined exactly with :func:`merge_partials`. Used by the ring-attention
    forward (the ring backward re-derives gradients through its own loop).
    """
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    o, lse = _flash_fwd(q, k, v, _as_offset(q_offset), _as_offset(k_offset),
                        sm_scale=float(sm_scale), causal=bool(causal),
                        block_q=int(block_q), block_k=int(block_k),
                        interpret=_use_interpret())
    return o, lse[..., 0]


def merge_partials(o_a, lse_a, o_b, lse_b):
    """Exactly combine two attention partials over disjoint key sets.

    Each partial is (normalised output, log-sum-exp). Rows absent from one
    side carry ``lse = -inf`` and contribute nothing.
    """
    lse = jnp.logaddexp(lse_a, lse_b)
    # exp(-inf - -inf) would be NaN; an absent row has weight exactly 0.
    w_a = jnp.where(lse_a == NEG_INF, 0.0, jnp.exp(lse_a - lse))
    w_b = jnp.where(lse_b == NEG_INF, 0.0, jnp.exp(lse_b - lse))
    o = (o_a.astype(jnp.float32) * w_a[..., None]
         + o_b.astype(jnp.float32) * w_b[..., None])
    return o.astype(o_a.dtype), lse


def attention_reference(q, k, v, *, causal=False, sm_scale=None,
                        q_offset=0, k_offset=0):
    """Naive O(seq²) attention — ground truth for kernel tests."""
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        q_ids = q_offset + jnp.arange(q.shape[2])[:, None]
        k_ids = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_ids >= k_ids, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)  # fully-masked rows
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
