"""Fused blockwise (flash) attention as a Pallas TPU kernel.

The hot op of the transformer model family. Online-softmax attention that
never materialises the ``(seq, seq)`` score matrix: the grid walks
(batch, head, q-block, k-block) with the k-block axis innermost, so exactly
one ``(block, head_dim)`` tile of each of q/k/v is resident in VMEM at a
time while a running (max, sum, accumulator) triple lives in VMEM scratch —
the MXU does the two matmuls, the VPU the rescaling. A custom VJP provides
the matching blockwise backward kernels (dq; dk/dv), so both compute and
VMEM stay O(block² + block·head_dim) per grid step end to end, independent
of sequence length.

This kernel is also the *local* building block of ring attention
(horovod_tpu/parallel/ring.py): it accepts dynamic ``q_offset``/``k_offset``
global position scalars and returns the per-row log-sum-exp, so partial
results computed against one shard of keys/values can be merged exactly
across ppermute steps (see ``merge_partials``).

The reference framework has no attention kernels at all (it is a pure
data-parallel gradient-averaging layer — SURVEY.md §5.7); this module is part
of the TPU-first long-context extension, not a port.

On non-TPU backends (CPU tests) the kernels run in Pallas interpret mode;
set ``HOROVOD_PALLAS_INTERPRET=0/1`` to force either way.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from horovod_tpu.utils import compat
from horovod_tpu.utils import env as env_mod

NEG_INF = float("-inf")

# Per-row scalars (lse, delta) are stored as (B, H, S, LANES) with the value
# broadcast across lanes, satisfying the TPU (8, 128) tiling constraint.
LANES = 128

# Softmax runs in base 2 inside the kernels (exp2 is cheaper than exp on the
# VPU): scores are pre-scaled by log2(e), the log-sum-exp converts back on
# the way out.
LOG2E = float(np.log2(np.e))


def _use_interpret() -> bool:
    default = jax.devices()[0].platform != "tpu"
    return env_mod._get_bool("HOROVOD_PALLAS_INTERPRET", default)


def _mxu_bf16(*refs) -> bool:
    """``FLASH_MXU_BF16=1``: feed the MXU dots bf16 operands (f32
    accumulation) instead of up-casting everything to f32 first — the
    standard TPU flash-kernel layout (softmax max/exp2/normalise stays f32
    on the VPU; dot operands, including the probability/ds intermediates,
    round to bf16). Measured on the BERT-Large bench shape (B8 H16 S512
    D64): NO speedup — 24-layer fwd 7.79→8.01 ms, fwd+bwd 13.12→13.24 ms
    (docs/perf_experiments.md round 4) — the kernel's cost at this shape is
    VPU/softmax-bound, not MXU-rate-bound, so the default stays the f32
    path (better p/ds precision for free). Kept as a measured-excluded
    counter-move and for A/B on future shapes where the MXU term dominates
    (longer head_dim, causal long-seq).

    NOTE (r4 advisor): the env var is read at KERNEL TRACE time — step
    functions already compiled under jax.jit keep the path they were
    traced with (jit caches don't key on env). Toggle it before the
    first call, or restart the process, for a clean A/B; the bench
    scripts do this via fresh processes."""
    return (env_mod._get_bool("FLASH_MXU_BF16", False)
            and all(r.dtype == jnp.bfloat16 for r in refs))


def _vma(*arrays) -> frozenset:
    """Union of the inputs' varying-mesh-axes, so pallas_call outputs carry
    the right vma under ``shard_map(check_vma=True)``."""
    out = frozenset()
    for a in arrays:
        out |= getattr(jax.typeof(a), "vma", frozenset())
    return out


def _pick_block(seq: int, requested: int) -> int:
    """Largest block ≤ requested that divides seq (power-of-two friendly)."""
    b = min(requested, seq)
    while seq % b:
        b -= 1
    return b


def _compiler_params(grid_len: int):
    # All grid axes are embarrassingly parallel except the innermost, which
    # carries the online-softmax accumulator in scratch.
    sem = ("parallel",) * (grid_len - 1) + ("arbitrary",)
    return pltpu.CompilerParams(dimension_semantics=sem)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    q_start = q_off_ref[0] + qi * block_q
    k_start = k_off_ref[0] + kj * block_k
    last_q = q_start + block_q - 1

    @pl.when(kj == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def update(masked):
        # Scores and the running max are tracked in base 2 (pre-scaled by
        # LOG2E) so the inner loop uses exp2, which is cheaper on the VPU.
        bf16 = _mxu_bf16(q_ref, k_ref, v_ref)
        if bf16:
            # bf16 operands straight from HBM; scale moves after the dot
            # (algebraically identical — the accumulator is f32 either way)
            q = q_ref[0, 0, :, :]
            k = k_ref[0, 0, :, :]
            v = v_ref[0, 0, :, :]
        else:
            q = q_ref[0, 0, :, :].astype(jnp.float32) * (sm_scale * LOG2E)
            k = k_ref[0, 0, :, :].astype(jnp.float32)  # (bk, d)
            v = v_ref[0, 0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (bq, bk)
        if bf16:
            s = s * (sm_scale * LOG2E)
        if masked:
            q_ids = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        # Rows with every key masked so far have m_new == -inf; subtracting
        # -inf would give NaN, so shift by a safe 0 instead — every exp()
        # argument is then -inf and the row correctly accumulates nothing.
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        alpha = jnp.exp2(m_prev - m_safe)
        p = jnp.exp2(s - m_safe[:, None])
        if bf16:
            # the SAME bf16-rounded p feeds both the PV numerator and the
            # l denominator (summed f32), so the softmax normalisation is
            # exactly consistent (r4 advisor finding)
            p = p.astype(jnp.bfloat16)
            l_new = l_prev * alpha + jnp.sum(p.astype(jnp.float32),
                                             axis=-1)
        else:
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jax.lax.broadcast_in_dim(m_new, m_ref.shape, (0,))
        l_ref[...] = jax.lax.broadcast_in_dim(l_new, l_ref.shape, (0,))

    if causal:
        # Skip k blocks entirely in this q block's future; mask only blocks
        # straddling the diagonal — interior blocks skip the iota/where.
        # Offsets are dynamic scalars, so this is predicated rather than
        # pruned from the (static) grid.
        interior = k_start + block_k - 1 <= q_start
        pl.when(interior)(lambda: update(False))
        pl.when(jnp.logical_and(k_start <= last_q,
                                jnp.logical_not(interior)))(
            lambda: update(True))
    else:
        update(False)

    @pl.when(kj == nk - 1)
    def _finalize():
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        # Fully-masked rows (l == 0): output 0, lse -inf so a later merge
        # treats this partial as absent.
        empty = l == 0.0
        l_safe = jnp.where(empty, 1.0, l)
        m_fin = jnp.where(empty, 0.0, m)
        o_ref[0, 0, :, :] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse = jnp.where(empty, NEG_INF,
                        m_fin * (1.0 / LOG2E) + jnp.log(l_safe))
        # Row vectors are stored broadcast across LANES lanes to satisfy TPU
        # tiling (same layout as the stock TPU flash kernel's l/m buffers).
        lse_ref[0, 0, :, :] = jax.lax.broadcast_in_dim(
            lse, (block_q, LANES), (0,))


def _fwd_single_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, o_ref,
                       lse_ref, *, sm_scale, causal, block_q, block_k):
    """Single-k-block forward: the whole key sequence is resident, so the
    softmax is direct — no m/l/acc scratch, no revolving online-softmax
    arithmetic, no @pl.when machinery. Measured r5 (B8 H16 S512 D64,
    tools/flash_vpu_probe.py): 0.130 ms/call vs 0.321 ms for the general
    online-softmax kernel at the same shape — 2.5x — with the general
    kernel already 2.8x faster than the stock pallas flash kernel and
    1.3x faster than unfused XLA attention. The win is the removed
    scratch traffic and per-block bookkeeping, NOT the MXU (a 2-head
    128-deep-contraction packing variant measured the same 0.12 ms)."""
    qi = pl.program_id(2)
    q_start = q_off_ref[0] + qi * block_q
    k_start = k_off_ref[0]
    last_q = q_start + block_q - 1

    def compute(bk):
        # bk: static k extent — the causal wedge passes block_k//2 so
        # q blocks whose rows never see the upper half of the keys skip
        # half the dots and half the softmax arithmetic
        bf16 = _mxu_bf16(q_ref, k_ref, v_ref)
        if bf16:
            q = q_ref[0, 0, :, :]
            k = k_ref[0, 0, :bk, :]
            v = v_ref[0, 0, :bk, :]
        else:
            q = q_ref[0, 0, :, :].astype(jnp.float32) * (sm_scale * LOG2E)
            k = k_ref[0, 0, :bk, :].astype(jnp.float32)
            v = v_ref[0, 0, :bk, :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if bf16:
            s = s * (sm_scale * LOG2E)
        if causal:
            q_ids = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        m = jnp.max(s, axis=-1)
        # fully-masked rows: m = -inf; shift by 0 so p is 0, not NaN
        m_safe = jnp.where(m == NEG_INF, 0.0, m)
        p = jnp.exp2(s - m_safe[:, None])
        if bf16:
            # same bf16-rounded p for numerator and denominator (r4
            # advisor)
            p = p.astype(jnp.bfloat16)
            l = jnp.sum(p.astype(jnp.float32), axis=-1)
        else:
            l = jnp.sum(p, axis=-1)
        empty = l == 0.0
        l_safe = jnp.where(empty, 1.0, l)
        o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        o_ref[0, 0, :, :] = (o / l_safe[:, None]).astype(o_ref.dtype)
        lse = jnp.where(empty, NEG_INF,
                        m_safe * (1.0 / LOG2E) + jnp.log(l_safe))
        lse_ref[0, 0, :, :] = jax.lax.broadcast_in_dim(
            lse, (block_q, LANES), (0,))

    if causal:
        # kv shards entirely in this q block's future are no-ops — the
        # ring-attention contract (parallel/ring.py: causal ring does
        # ~half the FLOPs because future shards self-skip). Offsets are
        # dynamic scalars, so predicate rather than prune the grid.
        relevant = k_start <= last_q
        half = block_k // 2
        if half and block_k % 2 == 0 and half % 128 == 0:
            # causal wedge: rows that never reach the keys' upper half
            # run the half-extent body — for in-model causal attention
            # (offsets 0) the first half of the q blocks take this
            # branch, cutting ~25% of the attention MACs and softmax
            # arithmetic overall
            needs_hi = last_q >= k_start + half

            @pl.when(needs_hi)
            def _():
                compute(block_k)

            @pl.when(jnp.logical_and(relevant,
                                     jnp.logical_not(needs_hi)))
            def _():
                compute(half)
        else:
            @pl.when(relevant)
            def _():
                compute(block_k)

        @pl.when(jnp.logical_not(relevant))
        def _():
            o_ref[0, 0, :, :] = jnp.zeros_like(o_ref[0, 0, :, :])
            lse_ref[0, 0, :, :] = jnp.full_like(lse_ref[0, 0, :, :],
                                                NEG_INF)
    else:
        compute(block_k)


def _single_specs(block_q, block_k, dim, ride):
    """BlockSpecs for the single-block (b, h, i) grids: ``ride`` names
    the operand the grid axis walks ("q" or "k"); the opposite side is
    pinned to block 0 (its whole extent is resident). Returns
    (q_spec, k_spec, q_row_spec) — the row spec follows the q side
    (lse/delta are per-q-row, lane-broadcast)."""
    walk = lambda b, h, i: (b, h, i, 0)
    pin = lambda b, h, i: (b, h, 0, 0)
    q_ix, k_ix = (walk, pin) if ride == "q" else (pin, walk)
    return (pl.BlockSpec((1, 1, block_q, dim), q_ix),
            pl.BlockSpec((1, 1, block_k, dim), k_ix),
            pl.BlockSpec((1, 1, block_q, LANES), q_ix))


def _make_specs(block_q, block_k, dim):
    """BlockSpecs for a (b, h, q-block, k-block) grid: q-side tiles index by
    the q-block id, k-side tiles by the k-block id — one block of each input
    is in VMEM per grid step regardless of sequence length."""
    q_spec = pl.BlockSpec((1, 1, block_q, dim), lambda b, h, i, j: (b, h, i, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, dim), lambda b, h, i, j: (b, h, j, 0))
    qrow_spec = pl.BlockSpec((1, 1, block_q, LANES),
                             lambda b, h, i, j: (b, h, i, 0))
    return q_spec, k_spec, qrow_spec


# The scalar offsets ride as int32 arrays of shape (1,); gridded kernels see
# the whole array in scalar memory, indexed as ref[0].
_OFF_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)


def _flash_fwd(q, k, v, q_offset, k_offset, *, sm_scale, causal,
               block_q, block_k, interpret):
    batch, heads, q_seq, dim = q.shape
    kv_seq = k.shape[2]
    block_q = _pick_block(q_seq, block_q)
    block_k = _pick_block(kv_seq, block_k)
    grid = (batch, heads, q_seq // block_q, kv_seq // block_k)
    q_spec, k_spec, qrow_spec = _make_specs(block_q, block_k, dim)
    vma = _vma(q, k, v, q_offset, k_offset)

    if kv_seq == block_k:
        # whole key sequence in one block: direct softmax, no scratch
        # (see _fwd_single_kernel — measured 2.5x at the bench shapes)
        sq_spec, sk_spec, srow_spec = _single_specs(
            block_q, block_k, dim, ride="q")
        o, lse = pl.pallas_call(
            functools.partial(
                _fwd_single_kernel, sm_scale=sm_scale, causal=causal,
                block_q=block_q, block_k=block_k),
            grid=grid[:3],
            in_specs=[_OFF_SPEC, _OFF_SPEC, sq_spec, sk_spec, sk_spec],
            out_specs=[sq_spec, srow_spec],
            out_shape=[
                compat.sds(q.shape, q.dtype, vma=vma),
                compat.sds((batch, heads, q_seq, LANES),
                                     jnp.float32, vma=vma),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",) * 3),
            interpret=interpret,
        )(q_offset, k_offset, q, k, v)
        return o, lse

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k)

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_OFF_SPEC, _OFF_SPEC, q_spec, k_spec, k_spec],
        out_specs=[q_spec, qrow_spec],
        out_shape=[
            compat.sds(q.shape, q.dtype, vma=vma),
            compat.sds((batch, heads, q_seq, LANES), jnp.float32,
                                 vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, dim), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        compiler_params=_compiler_params(len(grid)),
        interpret=interpret,
    )(q_offset, k_offset, q, k, v)
    return o, lse  # lse lane-broadcast: (B, H, S, LANES)


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dq_ref, dq_acc_ref,
                   *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    q_start = q_off_ref[0] + qi * block_q
    k_start = k_off_ref[0] + kj * block_k
    last_q = q_start + block_q - 1

    @pl.when(kj == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    def update(masked):
        bf16 = _mxu_bf16(q_ref, k_ref, v_ref, do_ref)
        cast = (lambda r: r[0, 0, :, :]) if bf16 else \
            (lambda r: r[0, 0, :, :].astype(jnp.float32))
        q = cast(q_ref)
        do = cast(do_ref)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        # Fully-masked rows have lse = -inf and all s = -inf; shifting by 0
        # instead of -inf keeps exp(s - lse) at 0 rather than NaN.
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse) * LOG2E
        k = cast(k_ref)
        v = cast(v_ref)
        s = (sm_scale * LOG2E) * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if masked:
            q_ids = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        p = jnp.exp2(s - lse_safe[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_acc_ref[...] = dq_acc_ref[...] + jax.lax.dot_general(
            ds.astype(jnp.bfloat16) if bf16 else ds, k,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        interior = k_start + block_k - 1 <= q_start
        pl.when(interior)(lambda: update(False))
        pl.when(jnp.logical_and(k_start <= last_q,
                                jnp.logical_not(interior)))(
            lambda: update(True))
    else:
        update(False)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, dk_acc_ref,
                    dv_acc_ref, *, sm_scale, causal, block_q, block_k):
    ki = pl.program_id(2)
    qj = pl.program_id(3)
    nq = pl.num_programs(3)

    k_start = k_off_ref[0] + ki * block_k
    q_start = q_off_ref[0] + qj * block_q
    last_q = q_start + block_q - 1

    @pl.when(qj == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    def update(masked):
        bf16 = _mxu_bf16(q_ref, k_ref, v_ref, do_ref)
        cast = (lambda r: r[0, 0, :, :]) if bf16 else \
            (lambda r: r[0, 0, :, :].astype(jnp.float32))
        k = cast(k_ref)
        v = cast(v_ref)
        q = cast(q_ref)
        do = cast(do_ref)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse) * LOG2E
        s = (sm_scale * LOG2E) * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (bq, bk)
        if masked:
            q_ids = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        p = jnp.exp2(s - lse_safe[:, None])
        pcast = p.astype(jnp.bfloat16) if bf16 else p
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            pcast, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds.astype(jnp.bfloat16) if bf16 else ds, q,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # q blocks entirely before this k block contribute nothing; blocks
        # entirely past the diagonal need no mask.
        interior = k_start + block_k - 1 <= q_start
        pl.when(interior)(lambda: update(False))
        pl.when(jnp.logical_and(last_q >= k_start,
                                jnp.logical_not(interior)))(
            lambda: update(True))
    else:
        update(False)

    @pl.when(qj == nq - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd_dq_single_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref,
                          do_ref, lse_ref, delta_ref, dq_ref,
                          *, sm_scale, causal, block_q, block_k):
    """Single-k-block dq: the general kernel's accumulator scratch and
    per-k-block @pl.when machinery removed (same specialization as
    _fwd_single_kernel), with the causal wedge — q blocks whose rows
    never reach the keys' upper half run half-extent dots."""
    qi = pl.program_id(2)
    q_start = q_off_ref[0] + qi * block_q
    k_start = k_off_ref[0]
    last_q = q_start + block_q - 1

    def compute(bk):
        bf16 = _mxu_bf16(q_ref, k_ref, v_ref, do_ref)
        cast = (lambda r, n: r[0, 0, :n, :]) if bf16 else \
            (lambda r, n: r[0, 0, :n, :].astype(jnp.float32))
        q = cast(q_ref, block_q)
        do = cast(do_ref, block_q)
        k = cast(k_ref, bk)
        v = cast(v_ref, bk)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse) * LOG2E
        s = (sm_scale * LOG2E) * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            q_ids = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        p = jnp.exp2(s - lse_safe[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_ref[0, 0, :, :] = jax.lax.dot_general(
            ds.astype(jnp.bfloat16) if bf16 else ds, k,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)

    if causal:
        relevant = k_start <= last_q
        half = block_k // 2
        if half and block_k % 2 == 0 and half % 128 == 0:
            needs_hi = last_q >= k_start + half

            @pl.when(needs_hi)
            def _():
                compute(block_k)

            @pl.when(jnp.logical_and(relevant,
                                     jnp.logical_not(needs_hi)))
            def _():
                compute(half)
        else:
            @pl.when(relevant)
            def _():
                compute(block_k)

        @pl.when(jnp.logical_not(relevant))
        def _():
            dq_ref[0, 0, :, :] = jnp.zeros_like(dq_ref[0, 0, :, :])
    else:
        compute(block_k)


def _bwd_dkv_single_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref,
                           do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                           *, sm_scale, causal, block_q, block_k):
    """Single-q-block dk/dv: scratch-free like _bwd_dq_single_kernel.
    (No wedge here — the causal cut for dk/dv runs along k COLUMNS,
    which does not map to a uniform static extent slice of the q
    operand.)"""
    ki = pl.program_id(2)
    k_start = k_off_ref[0] + ki * block_k
    q_start = q_off_ref[0]
    last_q = q_start + block_q - 1

    def compute():
        bf16 = _mxu_bf16(q_ref, k_ref, v_ref, do_ref)
        cast = (lambda r: r[0, 0, :, :]) if bf16 else \
            (lambda r: r[0, 0, :, :].astype(jnp.float32))
        q = cast(q_ref)
        k = cast(k_ref)
        v = cast(v_ref)
        do = cast(do_ref)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse) * LOG2E
        s = (sm_scale * LOG2E) * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            q_ids = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        p = jnp.exp2(s - lse_safe[:, None])
        pcast = p.astype(jnp.bfloat16) if bf16 else p
        dv_ref[0, 0, :, :] = jax.lax.dot_general(
            pcast, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_ref[0, 0, :, :] = jax.lax.dot_general(
            ds.astype(jnp.bfloat16) if bf16 else ds, q,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)

    if causal:
        # a kv shard entirely in the future of every q row gets no
        # gradient (ring contract, mirror of the forward predication)
        relevant = k_start <= last_q

        @pl.when(relevant)
        def _():
            compute()

        @pl.when(jnp.logical_not(relevant))
        def _():
            dk_ref[0, 0, :, :] = jnp.zeros_like(dk_ref[0, 0, :, :])
            dv_ref[0, 0, :, :] = jnp.zeros_like(dv_ref[0, 0, :, :])
    else:
        compute()


def _bwd_single_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, do_ref,
                       lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
                       *, sm_scale, causal, block_q, block_k):
    """Single-block fused backward: dq, dk AND dv from ONE kernel — s and
    p computed once instead of once per output kernel.

    MEASURED AND EXCLUDED (r5, tools/flash_vpu_probe.py): fwd+bwd
    0.503 ms vs 0.408 for the two-kernel bwd at B8 H16 S512 D64, and
    2.453 vs 1.828 at the GPT-2 shape — the fused kernel's strictly
    sequential dot chain (dv needs p, ds needs dp, dq/dk need ds) with
    three 1-4 MB live intermediates pipelines WORSE across grid steps
    than two lean kernels that each recompute s. Kept behind
    FLASH_FUSED_BWD=1 (trace-time env, default off) as the measured
    counter-example."""
    q_start = q_off_ref[0]
    k_start = k_off_ref[0]
    bf16 = _mxu_bf16(q_ref, k_ref, v_ref, do_ref)  # same A/B semantics
    cast = (lambda r: r[0, 0, :, :]) if bf16 else \
        (lambda r: r[0, 0, :, :].astype(jnp.float32))
    q = cast(q_ref)
    k = cast(k_ref)
    v = cast(v_ref)
    do = cast(do_ref)
    lse = lse_ref[0, 0, :, 0]
    delta = delta_ref[0, 0, :, 0]
    lse_safe = jnp.where(lse == NEG_INF, 0.0, lse) * LOG2E

    s = (sm_scale * LOG2E) * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if causal:
        q_ids = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_ids = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_ids >= k_ids, s, NEG_INF)
    p = jnp.exp2(s - lse_safe[:, None])
    pcast = p.astype(jnp.bfloat16) if bf16 else p
    dv_ref[0, 0, :, :] = jax.lax.dot_general(
        pcast, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * sm_scale
    dscast = ds.astype(jnp.bfloat16) if bf16 else ds
    dq_ref[0, 0, :, :] = jax.lax.dot_general(
        dscast, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_ref[0, 0, :, :] = jax.lax.dot_general(
        dscast, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def compute_delta(o, do) -> jax.Array:
    """The backward's per-row correction term, lane-broadcast: delta_i =
    sum_d do[i,d]·o[i,d], shape (B, H, S, LANES). Depends only on the final
    output/cotangent, so callers running many partial backwards against the
    same (o, do) — e.g. the ring sweep — compute it once and pass it in."""
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    return jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))


def _flash_bwd(q, k, v, o, lse, do, q_offset, k_offset, *, sm_scale, causal,
               block_q, block_k, interpret, delta=None):
    batch, heads, q_seq, dim = q.shape
    kv_seq = k.shape[2]
    block_q = _pick_block(q_seq, block_q)
    block_k = _pick_block(kv_seq, block_k)
    if (causal and kv_seq == block_k and block_q == q_seq
            and q_seq >= 1024 and (q_seq // 2) % 128 == 0
            and not env_mod._get_bool("FLASH_FUSED_BWD", False)):
        # single-k-block causal: two q blocks let the dq wedge skip the
        # first block's upper-half dots (measured r5 at the GPT-2
        # shape: fwd+bwd 1.697 -> 1.555 ms, incl. the dkv kernel
        # falling back to the general path). Skipped under the
        # FLASH_FUSED_BWD A/B so that flag still reaches its fused
        # kernel at these shapes.
        block_q = q_seq // 2

    if delta is None:
        delta = compute_delta(o, do)

    q_spec, k_spec, qrow_spec = _make_specs(block_q, block_k, dim)

    vma = _vma(q, k, v, do, q_offset, k_offset)

    if (q_seq == block_q and kv_seq == block_k
            and env_mod._get_bool("FLASH_FUSED_BWD", False)):
        # whole (q, k) extent resident: one fused kernel computes s and
        # p once and writes dq, dk, dv together (see _bwd_single_kernel)
        bh_q_spec = pl.BlockSpec((1, 1, block_q, dim),
                                 lambda b, h: (b, h, 0, 0))
        bh_k_spec = pl.BlockSpec((1, 1, block_k, dim),
                                 lambda b, h: (b, h, 0, 0))
        bh_row_spec = pl.BlockSpec((1, 1, block_q, LANES),
                                   lambda b, h: (b, h, 0, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_single_kernel, sm_scale=sm_scale, causal=causal,
                block_q=block_q, block_k=block_k),
            grid=(batch, heads),
            in_specs=[_OFF_SPEC, _OFF_SPEC, bh_q_spec, bh_k_spec,
                      bh_k_spec, bh_q_spec, bh_row_spec, bh_row_spec],
            out_specs=[bh_q_spec, bh_k_spec, bh_k_spec],
            out_shape=[
                compat.sds(q.shape, q.dtype, vma=vma),
                compat.sds(k.shape, k.dtype, vma=vma),
                compat.sds(v.shape, v.dtype, vma=vma),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel")),
            interpret=interpret,
        )(q_offset, k_offset, q, k, v, do, lse, delta)
        return dq, dk, dv

    if kv_seq == block_k:
        # scratch-free single-k-block dq (with causal wedge), any nq
        sq_spec, sk_spec, srow_spec = _single_specs(
            block_q, block_k, dim, ride="q")
        dq = pl.pallas_call(
            functools.partial(
                _bwd_dq_single_kernel, sm_scale=sm_scale, causal=causal,
                block_q=block_q, block_k=block_k),
            grid=(batch, heads, q_seq // block_q),
            in_specs=[_OFF_SPEC, _OFF_SPEC, sq_spec, sk_spec, sk_spec,
                      sq_spec, srow_spec, srow_spec],
            out_specs=sq_spec,
            out_shape=compat.sds(q.shape, q.dtype, vma=vma),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",) * 3),
            interpret=interpret,
        )(q_offset, k_offset, q, k, v, do, lse, delta)
    else:
        dq = None

    if q_seq == block_q:
        # scratch-free single-q-block dk/dv, any nk
        gq_spec, gk_spec, grow_spec = _single_specs(
            block_q, block_k, dim, ride="k")
        dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_dkv_single_kernel, sm_scale=sm_scale,
                causal=causal, block_q=block_q, block_k=block_k),
            grid=(batch, heads, kv_seq // block_k),
            in_specs=[_OFF_SPEC, _OFF_SPEC, gq_spec, gk_spec, gk_spec,
                      gq_spec, grow_spec, grow_spec],
            out_specs=[gk_spec, gk_spec],
            out_shape=[
                compat.sds(k.shape, k.dtype, vma=vma),
                compat.sds(v.shape, v.dtype, vma=vma),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",) * 3),
            interpret=interpret,
        )(q_offset, k_offset, q, k, v, do, lse, delta)
    else:
        dk = dv = None

    if dq is None:
        # multi-k-block: the general accumulating dq kernel
        dq = pl.pallas_call(
            functools.partial(
                _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                block_q=block_q, block_k=block_k),
            grid=(batch, heads, q_seq // block_q, kv_seq // block_k),
            in_specs=[_OFF_SPEC, _OFF_SPEC, q_spec, k_spec, k_spec,
                      q_spec, qrow_spec, qrow_spec],
            out_specs=q_spec,
            out_shape=compat.sds(q.shape, q.dtype, vma=vma),
            scratch_shapes=[pltpu.VMEM((block_q, dim), jnp.float32)],
            compiler_params=_compiler_params(4),
            interpret=interpret,
        )(q_offset, k_offset, q, k, v, do, lse, delta)

    if dk is None:
        # multi-q-block: general dk/dv — grid over (b, h, k-block,
        # q-block), q-side tiles streaming along the innermost axis
        # while dk/dv accumulate in scratch.
        kq_k_spec = pl.BlockSpec((1, 1, block_k, dim),
                                 lambda b, h, i, j: (b, h, i, 0))
        kq_q_spec = pl.BlockSpec((1, 1, block_q, dim),
                                 lambda b, h, i, j: (b, h, j, 0))
        kq_qrow_spec = pl.BlockSpec((1, 1, block_q, LANES),
                                    lambda b, h, i, j: (b, h, j, 0))

        dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                block_q=block_q, block_k=block_k),
            grid=(batch, heads, kv_seq // block_k, q_seq // block_q),
            in_specs=[_OFF_SPEC, _OFF_SPEC, kq_q_spec, kq_k_spec,
                      kq_k_spec, kq_q_spec, kq_qrow_spec, kq_qrow_spec],
            out_specs=[kq_k_spec, kq_k_spec],
            out_shape=[
                compat.sds(k.shape, k.dtype, vma=vma),
                compat.sds(v.shape, v.dtype, vma=vma),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, dim), jnp.float32),
                pltpu.VMEM((block_k, dim), jnp.float32),
            ],
            compiler_params=_compiler_params(4),
            interpret=interpret,
        )(q_offset, k_offset, q, k, v, do, lse, delta)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API: differentiable flash attention (+ residuals for ring merging)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, q_offset, k_offset, sm_scale, causal, block_q, block_k,
           bwd_block_q, bwd_block_k):
    o, _ = _flash_fwd(q, k, v, q_offset, k_offset, sm_scale=sm_scale,
                      causal=causal, block_q=block_q, block_k=block_k,
                      interpret=_use_interpret())
    return o


def _flash_vjp_fwd(q, k, v, q_offset, k_offset, sm_scale, causal,
                   block_q, block_k, bwd_block_q, bwd_block_k):
    o, lse = _flash_fwd(q, k, v, q_offset, k_offset, sm_scale=sm_scale,
                        causal=causal, block_q=block_q, block_k=block_k,
                        interpret=_use_interpret())
    return o, (q, k, v, o, lse, q_offset, k_offset)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, bwd_block_q,
                   bwd_block_k, res, do):
    q, k, v, o, lse, q_offset, k_offset = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, q_offset, k_offset,
                            sm_scale=sm_scale, causal=causal,
                            block_q=bwd_block_q, block_k=bwd_block_k,
                            interpret=_use_interpret())
    zero = jnp.zeros((1,), jnp.int32)
    return dq, dk, dv, zero, zero


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _as_offset(x) -> jax.Array:
    return jnp.asarray(x, jnp.int32).reshape((1,))


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    block_q: int = 512,
    block_k: int = 1024,
    bwd_block_q: int = 1024,
    bwd_block_k: int = 1024,
) -> jax.Array:
    """Fused attention over ``(batch, heads, seq, head_dim)`` inputs.

    ``q_offset``/``k_offset`` are the global sequence positions of the first
    query/key row — used by ring attention, where each device holds one
    sequence shard and the causal mask depends on global, not local, indices.
    They may be traced scalars (e.g. derived from ``lax.axis_index``).

    Block-size defaults are tuned on v5e (head_dim 128): the forward prefers
    tall k blocks, the backward square 1024 blocks. Sequences shorter than a
    block fall back to the largest divisor automatically.
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError("flash_attention expects (batch, heads, seq, dim)")
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    return _flash(q, k, v, _as_offset(q_offset), _as_offset(k_offset),
                  float(sm_scale), bool(causal), int(block_q), int(block_k),
                  int(bwd_block_q), int(bwd_block_k))


def flash_attention_partial(
    q, k, v, *, causal=False, sm_scale=None, q_offset=0, k_offset=0,
    block_q: int = 512, block_k: int = 1024,
):
    """Forward-only partial attention returning ``(out, lse)``.

    ``out`` is normalised over the *local* keys only; ``lse`` is the per-row
    log-sum-exp normaliser, so partials over disjoint key shards can be
    combined exactly with :func:`merge_partials`. Used by the ring-attention
    forward (the ring backward re-derives gradients through its own loop).
    """
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    o, lse = _flash_fwd(q, k, v, _as_offset(q_offset), _as_offset(k_offset),
                        sm_scale=float(sm_scale), causal=bool(causal),
                        block_q=int(block_q), block_k=int(block_k),
                        interpret=_use_interpret())
    return o, lse[..., 0]


def merge_partials(o_a, lse_a, o_b, lse_b):
    """Exactly combine two attention partials over disjoint key sets.

    Each partial is (normalised output, log-sum-exp). Rows absent from one
    side carry ``lse = -inf`` and contribute nothing.
    """
    lse = jnp.logaddexp(lse_a, lse_b)
    # exp(-inf - -inf) would be NaN; an absent row has weight exactly 0.
    w_a = jnp.where(lse_a == NEG_INF, 0.0, jnp.exp(lse_a - lse))
    w_b = jnp.where(lse_b == NEG_INF, 0.0, jnp.exp(lse_b - lse))
    o = (o_a.astype(jnp.float32) * w_a[..., None]
         + o_b.astype(jnp.float32) * w_b[..., None])
    return o.astype(o_a.dtype), lse


def attention_reference(q, k, v, *, causal=False, sm_scale=None,
                        q_offset=0, k_offset=0):
    """Naive O(seq²) attention — ground truth for kernel tests."""
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        q_ids = q_offset + jnp.arange(q.shape[2])[:, None]
        k_ids = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_ids >= k_ids, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)  # fully-masked rows
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
