"""AdamW as a single fused Pallas pass per parameter.

The round-4 BERT-Large decomposition (tools/bert_decompose.py,
docs/perf_experiments.md) measured the optax adamw update at 16.2 ms of a
77.6 ms step — 21%, entirely HBM-bandwidth-bound: the minimum traffic is
read p, mu, nu, g and write p, mu, nu (28 bytes/param in f32), ~11.4 ms at
the chip's ~819 GB/s for 334M params. optax's composed transform chain
(scale_by_adam -> add_decayed_weights -> scale -> apply_updates) leaves
XLA several fusion seams; this module expresses the whole update as ONE
elementwise Pallas kernel per leaf, so every byte is touched exactly once.

MEASURED OUTCOME (docs/perf_experiments.md round 4): on the BERT-Large
bench this loses ~27% end-to-end vs the optax chain (38.8k vs 53.7k
tokens/s; 1 MB and 256 KB blocks alike) — ~400 sequential per-leaf
pallas_calls forfeit XLA's cross-leaf scheduling, which the isolated
16.2 ms optax pass (~70% of its HBM roofline) was already exploiting.
Kept as a correctness-tested counter-move exemplar and for future work
(multi-leaf batched grids); NOT the default anywhere. The winning
optimizer-amortization move is gradient accumulation (BENCH_ACCUM).

The API is step-level — ``opt.apply(params, state, grads) -> (new_params,
new_state)`` — NOT an optax GradientTransformation: the optax contract
(update returns deltas, apply_updates adds them) would force two extra
full passes over the parameters, which is the very traffic being
eliminated. The state is optax's ScaleByAdamState (count, mu, nu), so
checkpoints interoperate with optax.adamw both ways.

Semantics follow optax.adamw: bias-corrected moments, decoupled weight
decay folded into the learning-rate step
(p -= lr * (m_hat / (sqrt(v_hat) + eps) + wd * p)).

The reference framework has no optimizer kernels (its DistributedOptimizer
wraps the host framework's optimizer — reference horovod/torch/optimizer.py);
this is part of the TPU-first performance layer, like the flash kernel.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from horovod_tpu.utils import env as env_mod

# Leaves smaller than this skip Pallas (a kernel launch isn't worth it for
# a LayerNorm scale; XLA fuses tiny elementwise chains fully on its own).
_MIN_PALLAS = 16 * 1024
# elements per grid step (tunable for A/B; 64k elements = 256 KB blocks,
# 7 live blocks x double buffering ~ 3.5 MB VMEM)
_BLOCK = env_mod._get_int("FUSED_ADAMW_BLOCK", 64 * 1024)


def _use_interpret() -> bool:
    default = jax.devices()[0].platform != "tpu"
    return env_mod._get_bool("HOROVOD_PALLAS_INTERPRET", default)


def _adamw_kernel(sc_ref, p_ref, m_ref, v_ref, g_ref, p_out, m_out, v_out,
                  *, eps):
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    # scalars in SMEM: b1, b2, 1/(1-b1^t), 1/(1-b2^t), lr, wd
    b1 = sc_ref[0]
    b2 = sc_ref[1]
    inv_bc1 = sc_ref[2]
    inv_bc2 = sc_ref[3]
    lr = sc_ref[4]
    wd = sc_ref[5]
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    p = p - lr * ((m * inv_bc1) / (jnp.sqrt(v * inv_bc2) + eps) + wd * p)
    p_out[...] = p.astype(p_out.dtype)
    m_out[...] = m.astype(m_out.dtype)
    v_out[...] = v.astype(v_out.dtype)


def _jnp_leaf(p, m, v, g, scalars, eps):
    b1, b2, inv_bc1, inv_bc2, lr, wd = (scalars[i] for i in range(6))
    gf = g.astype(jnp.float32)
    mf = b1 * m.astype(jnp.float32) + (1.0 - b1) * gf
    vf = b2 * v.astype(jnp.float32) + (1.0 - b2) * gf * gf
    pf = p.astype(jnp.float32)
    pf = pf - lr * ((mf * inv_bc1)
                    / (jnp.sqrt(vf * inv_bc2) + eps) + wd * pf)
    return pf.astype(p.dtype), mf.astype(m.dtype), vf.astype(v.dtype)


def _leaf_update(p, m, v, g, scalars, *, eps):
    """One fused read-modify-write pass over a single leaf."""
    n = int(np.prod(p.shape))
    if n < _MIN_PALLAS or n % 128:
        return _jnp_leaf(p, m, v, g, scalars, eps)

    rows = n // 128
    block_rows = min(rows, _BLOCK // 128)
    while rows % block_rows:
        block_rows -= 1
    if block_rows < 8:
        # no decent divisor (e.g. a prime row count): a grid of ~rows
        # 128-element kernel steps is correct but a severe perf cliff —
        # the XLA elementwise chain is the better program for such
        # leaves (r4 advisor finding)
        return _jnp_leaf(p, m, v, g, scalars, eps)
    flat = lambda a: a.reshape((rows, 128))
    spec = pl.BlockSpec((block_rows, 128), lambda i: (i, 0))
    p2, m2, v2 = pl.pallas_call(
        functools.partial(_adamw_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec, spec, spec, spec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows, 128), p.dtype),
                   jax.ShapeDtypeStruct((rows, 128), m.dtype),
                   jax.ShapeDtypeStruct((rows, 128), v.dtype)],
        interpret=_use_interpret(),
    )(scalars, flat(p), flat(m), flat(v), flat(g))
    return p2.reshape(p.shape), m2.reshape(m.shape), v2.reshape(v.shape)


class FusedAdamW(NamedTuple):
    """Step-level fused AdamW: ``apply(params, state, grads)``.

    ``init``/``apply`` instead of optax's update/apply_updates — returning
    deltas would re-read and re-write every parameter just to add them.
    """

    init: callable
    apply: callable


def fused_adamw(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8,
                weight_decay: float = 1e-4) -> FusedAdamW:
    """Fused-pass AdamW; state is optax ScaleByAdamState for checkpoint
    interop with ``optax.adamw`` (swap either way mid-training)."""

    def init(params):
        zeros = lambda p: jnp.zeros_like(p)
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(zeros, params),
            nu=jax.tree_util.tree_map(zeros, params))

    def apply(params, state, grads):
        count = optax.safe_int32_increment(state.count)
        t = count.astype(jnp.float32)
        scalars = jnp.stack([
            jnp.float32(b1), jnp.float32(b2),
            1.0 / (1.0 - jnp.float32(b1) ** t),
            1.0 / (1.0 - jnp.float32(b2) ** t),
            jnp.float32(learning_rate), jnp.float32(weight_decay)])

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_g = treedef.flatten_up_to(grads)
        new_p, new_m, new_v = [], [], []
        for p, m, v, g in zip(flat_p, flat_m, flat_v, flat_g):
            p2, m2, v2 = _leaf_update(p, m, v, g, scalars, eps=eps)
            new_p.append(p2)
            new_m.append(m2)
            new_v.append(v2)
        new_state = optax.ScaleByAdamState(
            count=count, mu=treedef.unflatten(new_m),
            nu=treedef.unflatten(new_v))
        return treedef.unflatten(new_p), new_state

    return FusedAdamW(init=init, apply=apply)
