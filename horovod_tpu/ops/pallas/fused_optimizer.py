"""Fused flat-buffer optimizer passes for ZeRO-1 shards.

Companion to :mod:`horovod_tpu.ops.pallas.fused_adamw`, reshaped for the
sharded data plane (:mod:`horovod_tpu.parallel.zero`): instead of one
kernel per parameter leaf, ONE kernel runs over the whole flat fp32
master/moment shard of a dtype group. That removes the per-leaf launch
overhead that sank the per-leaf fused AdamW (docs/perf_experiments.md
round 4 — ~400 sequential pallas_calls forfeit XLA's cross-leaf
scheduling): a BERT-Large f32 group is a single ~83M-element buffer, a
single grid. The minimum HBM traffic per element is read master, mu, nu
(f32) + grad and write all four again — and only 1/N of it happens on
each chip.

The kernel keeps fp32 master weights: ``mw`` carries the authoritative
parameters; the emitted ``p_out`` is the master cast to the parameter
dtype (bf16 master-weight training). Math matches optax.adamw
(bias-corrected moments, decoupled weight decay folded into the lr
step), so the jnp fallback and the kernel agree with the replicated
optax chain at fp32.

``HOROVOD_SHARDED_FUSED_KERNEL`` gates the Pallas path (default: on
when the backend is TPU, off elsewhere); the jnp fallback is always
available and is also used for shapes Pallas can't tile well (tiny
shards, non-multiple-of-128 lengths, stacked 2-D single-controller
layouts where the buffer is sharded across devices).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from horovod_tpu.ops.pallas.fused_adamw import _use_interpret
from horovod_tpu.utils import env as env_mod

# Same tiling policy as fused_adamw: skip Pallas below this (launch not
# worth it), and grid-step this many elements (256 KB f32 blocks).
_MIN_PALLAS = 16 * 1024
_BLOCK = env_mod._get_int("FUSED_OPTIMIZER_BLOCK", 64 * 1024)


def _use_kernel() -> bool:
    default = jax.devices()[0].platform == "tpu"
    return env_mod._get_bool(env_mod.HOROVOD_SHARDED_FUSED_KERNEL,
                             default)


def _flat_adamw_kernel(sc_ref, mw_ref, m_ref, v_ref, g_ref,
                       p_out, mw_out, m_out, v_out, *, eps):
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...]
    v = v_ref[...]
    w = mw_ref[...]
    # scalars in SMEM: b1, b2, 1/(1-b1^t), 1/(1-b2^t), lr, wd
    b1 = sc_ref[0]
    b2 = sc_ref[1]
    inv_bc1 = sc_ref[2]
    inv_bc2 = sc_ref[3]
    lr = sc_ref[4]
    wd = sc_ref[5]
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    w = w - lr * ((m * inv_bc1) / (jnp.sqrt(v * inv_bc2) + eps) + wd * w)
    p_out[...] = w.astype(p_out.dtype)
    mw_out[...] = w
    m_out[...] = m
    v_out[...] = v


def _jnp_flat(master, mu, nu, grad, scalars, eps, out_dtype):
    b1, b2, inv_bc1, inv_bc2, lr, wd = (scalars[i] for i in range(6))
    gf = grad.astype(jnp.float32)
    m2 = b1 * mu + (1.0 - b1) * gf
    v2 = b2 * nu + (1.0 - b2) * gf * gf
    w2 = master - lr * ((m2 * inv_bc1)
                        / (jnp.sqrt(v2 * inv_bc2) + eps) + wd * master)
    return w2.astype(out_dtype), w2, m2, v2


def flat_adamw_shard(master, mu, nu, grad, scalars, *, eps, out_dtype):
    """One fused AdamW pass over a flat fp32 master shard.

    ``master``/``mu``/``nu`` are f32 buffers, ``grad`` the reduced
    gradient shard (any float dtype), ``scalars`` the 6-vector
    [b1, b2, 1/(1-b1^t), 1/(1-b2^t), lr, wd]. Returns
    ``(params_shard[out_dtype], master', mu', nu')``.
    """
    out_dtype = jnp.dtype(out_dtype)
    if isinstance(master, jax.core.Tracer) or master.ndim != 1:
        # traced under shard_map (Pallas-per-device would need careful
        # vmem accounting inside the spmd body) or a stacked 2-D
        # single-controller buffer sharded across devices: the XLA
        # elementwise chain is the right program
        return _jnp_flat(master, mu, nu, grad, scalars, eps, out_dtype)
    n = int(master.shape[0])
    if not _use_kernel() or n < _MIN_PALLAS or n % 128:
        return _jnp_flat(master, mu, nu, grad, scalars, eps, out_dtype)
    rows = n // 128
    block_rows = min(rows, _BLOCK // 128)
    while rows % block_rows:
        block_rows -= 1
    if block_rows < 8:
        return _jnp_flat(master, mu, nu, grad, scalars, eps, out_dtype)
    flat = lambda a: a.reshape((rows, 128))
    spec = pl.BlockSpec((block_rows, 128), lambda i: (i, 0))
    p2, w2, m2, v2 = pl.pallas_call(
        functools.partial(_flat_adamw_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec, spec, spec, spec],
        out_specs=[spec, spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows, 128), out_dtype),
                   jax.ShapeDtypeStruct((rows, 128), jnp.float32),
                   jax.ShapeDtypeStruct((rows, 128), jnp.float32),
                   jax.ShapeDtypeStruct((rows, 128), jnp.float32)],
        interpret=_use_interpret(),
    )(scalars, flat(master), flat(mu), flat(nu), flat(grad))
    return (p2.reshape(n), w2.reshape(n), m2.reshape(n), v2.reshape(n))
