"""Parallelism strategies: data parallel (reference parity) plus the
TPU-first long-context extensions (ring + Ulysses sequence parallelism)."""

from horovod_tpu.parallel.dp import (
    DistributedGradientTape,
    DistributedOptimizer,
    allreduce_gradients,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from horovod_tpu.parallel.ring import ring_attention
from horovod_tpu.parallel.ulysses import ulysses_attention
from horovod_tpu.parallel.zero import (
    FlatAdamState,
    ShardedOptState,
    sharded_adamw,
    sharded_update,
)

__all__ = [
    "DistributedOptimizer",
    "DistributedGradientTape",
    "allreduce_gradients",
    "broadcast_parameters",
    "broadcast_optimizer_state",
    "broadcast_object",
    "ring_attention",
    "ulysses_attention",
    "sharded_update",
    "sharded_adamw",
    "ShardedOptState",
    "FlatAdamState",
]
