"""Shared helpers for the parallelism strategy modules."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def consume_stage_axis(tree):
    """Drop the length-1 leading axis shard_map leaves carry when a
    (n_stages, ...) stack is sharded with in_specs P(axis, ...) — used by
    the pipeline and expert-parallel dispatchers."""
    return jax.tree_util.tree_map(lambda a: jnp.squeeze(a, axis=0), tree)


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage param pytrees along a new leading axis
    (shard it over the pipeline/expert mesh axis with P('axis', ...))."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)
