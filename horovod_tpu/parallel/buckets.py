"""Bucket-wise gradient release: overlap allreduce with backward.

The post-hoc exchange (``jax.value_and_grad`` then one
``allreduce_gradients`` call) serializes the whole backward pass in
front of the first wire byte — exactly the pattern the reference's
background loop was built to kill (reference: Sergeev & Del Balso 2018
§3, the framework hooks that submit each gradient as its op completes)
and that PyTorch DDP formalized as gradient buckets (Li et al., VLDB
2020 §4.2). This module is the TPU-native version of both: the
parameter tree is partitioned into fusion buckets in
**reverse-topological order** (last layer first — the order gradients
become final during backward), and each bucket's allreduce is released
as soon as its last gradient lands, so early buckets reduce on the
cycle thread while later layers are still differentiating.

Three lanes, matching the collectives module:

* **eager / multiprocess** — ``plan.tag(params)`` wraps every dense
  leaf in a ``custom_vjp`` identity whose backward hook runs as Python
  with the *concrete* cotangent, in backward order. When a bucket's
  last gradient arrives the whole bucket is enqueued atomically
  (:meth:`Runtime.enqueue_allreduce_group`) and reduces under the
  PR-3 dispatch/drain pipeline while backward continues.
  ``plan.gather(grads)`` then waits the handles in release order and
  splices the reduced values back into the tree.
* **shard_map (bound mesh axes)** — the hook is traced: it emits the
  leaf's ``lax.pmean``/``psum`` at its backward position and chains a
  scalar token through ``lax.optimization_barrier`` at every bucket
  boundary, so XLA cannot sink the collectives to the end of the
  program — the staged-interleave analogue of the eager release.
* **plain jit (no bound axes)** — identity: gradients of a
  global-mean loss are already the global average and XLA schedules
  the collective from the shardings.

``backward_passes_per_step > 1`` composes on the eager lane: the plan
owns the accumulation (``every_k``), buckets accumulate locally for
micro-batches ``1..k-1`` and only the final pass releases the
accumulated mean to the wire (reference: torch/__init__.py:82-143
semantics, moved to bucket granularity). Do not combine a plan with
``optax.MultiSteps`` — two accumulators double-count.

Correctness contract (mirrors the PR-3 fusion rules):

* bit-parity with the unbucketed path for sum/avg — the wire programs
  are the same size-bucketed fused reducers with the same
  reduction-identity padding, and elementwise reduction is oblivious
  to how leaves are packed into buckets;
* zero steady-state compiles — bucket shapes repeat every step, so
  after the first step every program comes from the PR-3 size-bucket
  cache (pinned by the ``_PROGRAM_COMPILES`` canary in tests);
* integrity digests ride unchanged — the digest cadence counts fused
  dispatches, and a bucketed step simply contributes one dispatch per
  bucket;
* a ``WorkersDownError`` mid-backward fails every in-flight bucket
  token (PR-3 ``_PendingOp.fail`` releases the fusion-buffer leases);
  :meth:`GradReleasePlan.gather` drains the remaining handles and
  resets, so the next generation starts clean.

ZeRO-2 composition: ``GradReleasePlan(reduce_scatter=True)`` releases
each bucket as a **reduce-scatter** instead of an allreduce — only the
local 1/N shard comes back ((N-1)/N bus bytes per payload byte, half an
allreduce) and ``gather()`` returns a ``zero.ShardedGrads`` that
``sharded_adamw`` / ``sharded_update`` consume directly. Build the
optimizer with ``partition=plan.zero_partition(params)`` so the shard
layouts line up. See ``parallel/zero.py``.

Knobs: ``HOROVOD_GRAD_BUCKET_BYTES`` (target bucket payload, default
4 MiB, rounded up to the fusion quantum), ``HOROVOD_GRAD_BUCKET_WIRE``
(``auto``/``off`` — whether single-controller replicated gradients are
shipped worker-stacked through the runtime so the release is a real
dispatch, or short-circuited to local math), and
``HOROVOD_GRAD_BUCKET_RELEASE`` (default-on switch consumed by
``training.make_train_step``). See docs/performance.md "backward
overlap".
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu import comms
from horovod_tpu.analysis import witness
from horovod_tpu.utils import env as env_mod

DEFAULT_GRAD_BUCKET_BYTES = 4 * 1024 * 1024

_tls = threading.local()


def is_prereduced() -> bool:
    """True while the current thread is inside a :func:`prereduced`
    scope — gradients were already exchanged by a release plan and
    ``dp.allreduce_gradients`` must not reduce them again."""
    return getattr(_tls, "prereduced", False)


@contextmanager
def prereduced():
    """Mark gradients handed to ``DistributedOptimizer`` as already
    reduced (bucket-wise, during backward)."""
    prev = getattr(_tls, "prereduced", False)
    _tls.prereduced = True
    try:
        yield
    finally:
        _tls.prereduced = prev


def release_enabled() -> bool:
    """The ``HOROVOD_GRAD_BUCKET_RELEASE`` switch (default off: the
    unbucketed path stays the seed behavior unless opted in)."""
    return env_mod._get_bool("HOROVOD_GRAD_BUCKET_RELEASE", False)


# Autotuner override (runtime._autotune_sync): applies to release plans
# built AFTER the commit — an existing plan keeps its partition, since
# repartitioning mid-training would recompile every bucket program.
_autotuned_bucket_bytes = 0


def set_autotuned_bucket_bytes(nbytes: int) -> None:
    global _autotuned_bucket_bytes
    _autotuned_bucket_bytes = max(0, int(nbytes))


def bucket_bytes_from_env() -> int:
    """Target bucket payload: ``HOROVOD_GRAD_BUCKET_BYTES`` (or the
    autotuner's committed override, which wins while set) rounded up
    to a whole number of fusion quanta so bucket payloads land on the
    PR-3 size-bucket grid (zero steady-state compiles)."""
    raw = _autotuned_bucket_bytes or env_mod._get_int(
        "HOROVOD_GRAD_BUCKET_BYTES", DEFAULT_GRAD_BUCKET_BYTES)
    quantum = env_mod._get_int(env_mod.HOROVOD_FUSION_BUCKET_QUANTUM,
                               env_mod.DEFAULT_FUSION_BUCKET_QUANTUM_BYTES)
    quantum = max(1, quantum)
    raw = max(quantum, raw)
    return ((raw + quantum - 1) // quantum) * quantum


def _wire_mode() -> str:
    mode = (os.environ.get("HOROVOD_GRAD_BUCKET_WIRE", "auto")
            .strip().lower() or "auto")
    return mode if mode in ("auto", "off") else "auto"


def _leaf_nbytes(leaf) -> int:
    return int(np.prod(np.shape(leaf), dtype=np.int64)
               * np.dtype(leaf.dtype).itemsize)


class _Bucket:
    __slots__ = ("index", "leaves", "nbytes")

    def __init__(self, index: int, leaves: List[int], nbytes: int):
        self.index = index
        self.leaves = leaves  # leaf positions, reverse-topological order
        self.nbytes = nbytes


class GradReleasePlan:
    """Partition + release state for one model's gradient tree.

    Construct once per training setup and reuse across steps — the
    partition is computed lazily from the first tagged tree and the
    per-leaf hook closures are cached, so steady-state steps allocate
    nothing but the per-step bookkeeping dicts.
    """

    def __init__(self, *, bucket_bytes: Optional[int] = None,
                 every_k: int = 1, average: bool = True,
                 name: str = "grad", reduce_scatter: bool = False):
        if every_k < 1:
            raise ValueError("every_k must be >= 1")
        self.bucket_bytes = (bucket_bytes if bucket_bytes is not None
                             else bucket_bytes_from_env())
        self.every_k = every_k
        self.average = average
        self.name = name
        # ZeRO-2: release each bucket as a reduce-scatter and keep only
        # the local 1/N shard — gather() then returns a
        # zero.ShardedGrads for the sharded optimizer to consume
        # directly (half the gradient bus bytes of an allreduce)
        self.reduce_scatter = bool(reduce_scatter)
        # partition (filled by _ensure_partition on first tag)
        self._num_leaves: Optional[int] = None
        self._buckets: List[_Bucket] = []
        self._bucket_of: Dict[int, _Bucket] = {}
        self._tags: Dict[int, Any] = {}
        # per-backward-pass state (training thread only)
        self._grads: Dict[int, Any] = {}
        self._remaining: Dict[int, int] = {}   # bucket index -> leaves left
        self._accum: Dict[int, Any] = {}       # every_k partial sums
        self._pass_idx = 0
        self._step_id = 0
        # released wire state: (bucket, [(leaf, handle)]) in release order;
        # locally-reduced leaves land in _local instead of carrying handles
        self._released: List[tuple] = []
        self._local: Dict[int, Any] = {}
        # reduce-scatter mode: per-leaf shape/dtype metadata (for the
        # zero spec + zero-filling partial buckets), the bucket-aligned
        # ZeroSpec, its bucket->group map, and the per-group results
        self._leaf_meta: Dict[int, tuple] = {}
        self._zspec = None
        self._groups_of_bucket: Dict[int, List[int]] = {}
        self._rs_released: List[tuple] = []  # (bucket, [(gi, h)], t, B)
        self._shard_local: Dict[int, Any] = {}  # gi -> (W, shard)
        # traced-lane token for optimization_barrier chaining (valid only
        # within the enclosing trace; reset by tag())
        self._token = None
        # wire counters shared between the training thread (release) and
        # the runtime cycle thread (entry completion callbacks)
        self._wire_lock = witness.make_lock("GradReleasePlan._wire_lock")
        self._wire_released = 0   # guarded-by: _wire_lock
        self._wire_completed = 0  # guarded-by: _wire_lock
        self._wire_failed = 0     # guarded-by: _wire_lock

    # -- partition ----------------------------------------------------------
    def _ensure_partition(self, leaves) -> None:
        if self._num_leaves is not None:
            if len(leaves) != self._num_leaves:
                raise ValueError(
                    f"gradient tree changed shape: plan was built for "
                    f"{self._num_leaves} leaves, got {len(leaves)}")
            return
        self._num_leaves = len(leaves)
        dense = [i for i, leaf in enumerate(leaves)
                 if leaf is not None and hasattr(leaf, "dtype")]
        # reverse-topological: tree-flatten order follows model layer
        # order, so walking it backwards fronts the gradients that become
        # final first during backward
        order = list(reversed(dense))
        cur: List[int] = []
        cur_bytes = 0
        for i in order:
            cur.append(i)
            cur_bytes += _leaf_nbytes(leaves[i])
            if cur_bytes >= self.bucket_bytes:
                self._buckets.append(_Bucket(len(self._buckets), cur,
                                             cur_bytes))
                cur, cur_bytes = [], 0
        if cur:
            self._buckets.append(_Bucket(len(self._buckets), cur, cur_bytes))
        for b in self._buckets:
            for i in b.leaves:
                self._bucket_of[i] = b
                self._leaf_meta[i] = (tuple(np.shape(leaves[i])),
                                      np.dtype(leaves[i].dtype))

    def buckets(self) -> List[List[int]]:
        """The computed partition (leaf positions per bucket, release
        order) — empty before the first ``tag`` call."""
        return [list(b.leaves) for b in self._buckets]

    def zero_partition(self, params) -> List[List[int]]:
        """The bucket partition as a ``zero.build_spec`` partition —
        hand this to ``sharded_adamw(..., partition=...)`` /
        ``sharded_update(..., partition=...)`` so the optimizer's shard
        layout lines up 1:1 with the reduce-scatter release buckets."""
        leaves, _ = jax.tree_util.tree_flatten(params)
        self._ensure_partition(leaves)
        return self.buckets()

    def _ensure_zspec(self, st):
        """Bucket-aligned ZeroSpec (one dtype group per bucket cell),
        rebuilt when the world re-forms — bucket programs stay keyed on
        the spec, so a stable world means zero new compiles."""
        from horovod_tpu.ops import collectives
        from horovod_tpu.parallel import zero

        rank = (st.rank if collectives._multiprocess_world(st) else 0)
        spec = self._zspec
        if (spec is not None and spec.world == st.size
                and spec.rank == rank):
            return spec
        metas = [None] * (self._num_leaves or 0)
        for i, (shape, dtype) in self._leaf_meta.items():
            metas[i] = zero.LeafMeta(shape=shape, dtype=dtype)
        spec = zero.build_spec(metas, st.size, rank,
                               zero._quantum_bytes(st),
                               partition=self.buckets())
        self._groups_of_bucket = {}
        for gi, g in enumerate(spec.groups):
            b = self._bucket_of[g.indices[0]]
            self._groups_of_bucket.setdefault(b.index, []).append(gi)
        self._zspec = spec
        return spec

    # -- tagging ------------------------------------------------------------
    def _tag_for(self, i: int):
        tag = self._tags.get(i)
        if tag is not None:
            return tag

        @jax.custom_vjp
        def _tag(x):
            return x

        def _fwd(x):
            return x, None

        def _bwd(_res, g):
            return (self._on_grad(i, g),)

        _tag.defvjp(_fwd, _bwd)
        self._tags[i] = _tag
        return _tag

    def tag(self, params):
        """Wrap every dense leaf of ``params`` in its release hook.

        Call inside the loss closure, on the argument being
        differentiated — the hooks then see each leaf's cotangent the
        moment backward finishes it. Also resets the per-pass state, so
        one forward/backward == one pass."""
        leaves, treedef = jax.tree_util.tree_flatten(params)
        self._ensure_partition(leaves)
        self._begin_pass()
        out = [leaf if i not in self._bucket_of
               else self._tag_for(i)(leaf)
               for i, leaf in enumerate(leaves)]
        return jax.tree_util.tree_unflatten(treedef, out)

    def _begin_pass(self) -> None:
        self._grads.clear()
        self._remaining = {b.index: len(b.leaves) for b in self._buckets}
        self._token = None
        if self._pass_idx == 0:
            self._released = []
            self._local = {}

    # -- backward hooks -----------------------------------------------------
    def _on_grad(self, i: int, g):
        if isinstance(g, jax.core.Tracer):
            return self._on_grad_traced(i, g)
        bucket = self._bucket_of[i]
        self._grads[i] = g
        self._remaining[bucket.index] -= 1
        if self._remaining[bucket.index] == 0:
            self._bucket_ready(bucket)
        return g

    def _on_grad_traced(self, i: int, g):
        from horovod_tpu.parallel import dp as dp_mod

        axes = dp_mod._bound_axes(None)
        bucket = self._bucket_of[i]
        self._remaining[bucket.index] -= 1
        boundary = self._remaining[bucket.index] == 0
        if not axes:
            # plain jit global-batch DP: gradients are already the global
            # average (XLA inserts the collective from the shardings);
            # nothing to stage
            return g
        from jax import lax

        r = lax.pmean(g, axes) if self.average else lax.psum(g, axes)
        if boundary:
            # chain a token through the barrier at every bucket boundary:
            # the data dependency serializes the boundaries, so XLA keeps
            # each bucket's collectives at their backward position instead
            # of sinking them all to the end of the program
            if self._token is None:
                self._token = jnp.zeros((), jnp.float32)
            self._token, r = lax.optimization_barrier((self._token, r))
        return r

    def _bucket_ready(self, bucket: _Bucket) -> None:
        values = {i: self._grads.pop(i) for i in bucket.leaves}
        if self._pass_idx + 1 < self.every_k:
            # intermediate micro-batch: accumulate locally, nothing on the
            # wire (constraint: only the final micro-batch releases)
            for i, v in values.items():
                prev = self._accum.get(i)
                self._accum[i] = v if prev is None else prev + v
            return
        if self.every_k > 1:
            inv_k = 1.0 / self.every_k
            for i in list(values):
                prev = self._accum.pop(i, None)
                total = values[i] if prev is None else prev + values[i]
                values[i] = total * np.asarray(inv_k, dtype=total.dtype)
        self._release(bucket, values)

    # -- wire ---------------------------------------------------------------
    def _release(self, bucket: _Bucket, values: Dict[int, Any]) -> None:
        from horovod_tpu.core import basics
        from horovod_tpu.ops import collectives

        if self.reduce_scatter:
            return self._release_reduce_scatter(bucket, values)
        st = basics._ensure_init()
        reduce_op = "average" if self.average else "sum"
        wire_idx: List[int] = []
        tensors: List[Any] = []
        names: List[str] = []
        multiproc = (collectives._multiprocess_world(st)
                     and collectives._runtime_capable(st))
        for i in bucket.leaves:
            x = values[i]
            name = (f"grad_bucket.{self.name}.{self._step_id}"
                    f".b{bucket.index}.{i}")
            if multiproc:
                wire_idx.append(i)
                tensors.append(collectives._to_plane(x))
                names.append(name)
            elif collectives._is_worker_stacked(x):
                wire_idx.append(i)
                tensors.append(x)
                names.append(name)
            elif st.size > 1 and _wire_mode() != "off":
                # single-controller replicated gradient: ship it
                # worker-stacked through the runtime so the release is a
                # real pipelined dispatch (the "simulated multi-lane"
                # measurement mode). The splice still uses the locally
                # exact value (_local wins over the wire result in
                # gather): a sequential reduction over identical rows can
                # round 1 ULP, and bucketed must stay bit-identical to
                # the unbucketed local shortcut.
                stacked = collectives.stack_per_worker(
                    jnp.broadcast_to(jnp.asarray(x),
                                     (st.size,) + tuple(np.shape(x))))
                wire_idx.append(i)
                tensors.append(stacked)
                names.append(name)
                self._local[i] = x if self.average else x * st.size
            else:
                # 1-worker world (or wire=off): same local math as the
                # unbucketed replicated path
                self._local[i] = x if self.average else x * st.size
        if not wire_idx:
            return
        handles = collectives.grouped_allreduce_async(
            tensors, names=names, reduce_op=reduce_op,
            priority=len(self._buckets) - bucket.index,
            group_callback=self._on_wire_complete)
        with self._wire_lock:
            self._wire_released += len(handles)
        wire_bytes = sum(
            int(np.prod(np.shape(t), dtype=np.int64)
                * np.dtype(t.dtype).itemsize) for t in tensors)
        self._released.append((bucket.index,
                               list(zip(wire_idx, handles)),
                               time.monotonic(), wire_bytes))

    def _release_reduce_scatter(self, bucket: _Bucket,
                                values: Dict[int, Any]) -> None:
        """ZeRO-2 release: pack the bucket's dtype groups and
        reduce-scatter each one — only the local 1/N shard comes back.
        Multi-process rides the runtime's reduce-scatter lane under
        stable per-group names; single-controller replicated takes the
        same local short-circuit (and the same bits) as the stage-1
        eager path via a cached worker-sharded program."""
        from horovod_tpu.core import basics
        from horovod_tpu.ops import collectives
        from horovod_tpu.parallel import zero

        st = basics._ensure_init()
        spec = self._ensure_zspec(st)
        multiproc = (collectives._multiprocess_world(st)
                     and collectives._runtime_capable(st))
        if collectives._multiprocess_world(st) and not multiproc:
            raise NotImplementedError(
                "reduce-scatter gradient release in a multi-process "
                "world needs the enqueue runtime (tpurun / HOROVOD_RANK "
                "env contract)")
        pairs: List[tuple] = []
        wire_bytes = 0
        for gi in self._groups_of_bucket.get(bucket.index, []):
            g = spec.groups[gi]
            vals = {}
            for li, shape, _size in zip(g.indices, g.shapes, g.sizes):
                v = values.get(li)
                if v is None:
                    # partial bucket (a leaf produced no cotangent):
                    # zeros are the reduction identity
                    v = np.zeros(shape, np.dtype(g.dtype))
                vals[li] = v
            nbytes = g.padded * np.dtype(g.dtype).itemsize
            zero._RS_BYTES.inc(int(nbytes))
            # bucket_wire convention matches the allreduce release: the
            # multi-process lane counts per-rank tensor bytes; the
            # single-controller simulated wire counts the whole (W, n)
            # plane — so the stage-2 bus ratio vs the allreduce baseline
            # reads exactly 0.5 off the ledger in either mode
            wire_bytes += int(nbytes) * (1 if multiproc else st.size)
            if multiproc:
                op_name = collectives._OP_NAMES[
                    collectives.Average if self.average
                    else collectives.Sum]
                from horovod_tpu.runtime.runtime import get_runtime

                flat = zero._np_pack_group(vals, g)
                h = get_runtime().enqueue_reducescatter(
                    f"zero2.{self.name}.b{bucket.index}.g{gi}",
                    jnp.asarray(flat), reduce_op=op_name,
                    priority=len(self._buckets) - bucket.index)
                pairs.append((gi, h))
            else:
                stacked_flags = [
                    collectives._is_worker_stacked(
                        collectives._to_plane(vals[li]))
                    for li in g.indices]
                if any(stacked_flags) and not all(stacked_flags):
                    raise ValueError(
                        "reduce-scatter release needs a bucket's leaves "
                        "uniformly worker-stacked or uniformly "
                        "replicated, got a mix")
                self._shard_local[gi] = zero.scatter_bucket_group(
                    vals, spec, gi, st, average=self.average,
                    stacked=all(stacked_flags))
        if pairs:
            with self._wire_lock:
                self._wire_released += len(pairs)
        self._rs_released.append((bucket.index, pairs, time.monotonic(),
                                  wire_bytes))

    def _on_wire_complete(self, ok: bool) -> None:
        # runs on the runtime cycle thread as each entry completes/fails
        with self._wire_lock:
            self._wire_completed += 1
            if not ok:
                self._wire_failed += 1

    def wire_stats(self) -> dict:
        with self._wire_lock:
            return {"released": self._wire_released,
                    "completed": self._wire_completed,
                    "failed": self._wire_failed}

    # -- gather -------------------------------------------------------------
    def gather(self, grads):
        """Splice the reduced buckets back into the gradient tree.

        Eager: waits each released handle in release order (the first
        buckets are usually already drained — that wait is the overlap
        win) and returns the reduced tree. With ``every_k > 1`` the
        intermediate passes return ``None`` (nothing to apply yet).
        Traced: identity — the hooks already emitted the staged
        collectives in place. On a ``WorkersDownError`` (or any wire
        failure) every remaining handle is drained and the per-step
        state reset before the error propagates, so an elastic re-form
        can retry the step on the plan unchanged."""
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if any(isinstance(g, jax.core.Tracer) for g in leaves):
            return grads
        if self._pass_idx + 1 < self.every_k:
            self._pass_idx += 1
            return None
        self._flush()
        if self.reduce_scatter:
            return self._gather_shards()
        from horovod_tpu.ops import collectives

        out = list(leaves)
        failure = None
        for _bucket_idx, pairs, t_release, wire_bytes in self._released:
            bucket_ok = bool(pairs)
            for i, h in pairs:
                try:
                    out[i] = collectives.synchronize(h)
                except Exception as exc:  # drain the rest before raising
                    bucket_ok = False
                    if failure is None:
                        failure = exc
            if bucket_ok:
                # comms plane "bucket_wire" lane: one record per released
                # bucket, release→drain wall time over the bucket's wire
                # payload (docs/comms.md) — the end-to-end view next to
                # the carrying lane's per-dispatch records
                comms.record("allreduce", "bucket_wire", wire_bytes,
                             time.monotonic() - t_release)
        for i, v in self._local.items():
            out[i] = v
        self._reset_step()
        if failure is not None:
            raise failure
        return jax.tree_util.tree_unflatten(treedef, out)

    def _gather_shards(self):
        """Drain the per-bucket reduce-scatters in release order and
        assemble the :class:`zero.ShardedGrads` the sharded optimizer
        consumes directly — the full-gradient buffer is never
        reassembled. One ``bucket_wire`` comms record per bucket
        (op=reducescatter: the ledger's busbw math charges (N-1)/N bus
        bytes per payload byte — half an allreduce's 2(N-1)/N)."""
        from horovod_tpu.ops import collectives
        from horovod_tpu.parallel import zero

        spec = self._zspec
        if spec is None:  # no bucket ever released (empty tree)
            from horovod_tpu.core import basics

            spec = self._ensure_zspec(basics._ensure_init())
        shards: List[Any] = [None] * len(spec.groups)
        failure = None
        for _bucket_idx, pairs, t_release, wire_bytes in self._rs_released:
            bucket_ok = True
            for gi, h in pairs:
                try:
                    out = collectives.synchronize(h)
                    shards[gi] = jnp.asarray(out).astype(
                        np.dtype(spec.groups[gi].dtype))
                except Exception as exc:  # drain the rest first
                    bucket_ok = False
                    if failure is None:
                        failure = exc
            if bucket_ok and wire_bytes:
                comms.record("reducescatter", "bucket_wire", wire_bytes,
                             time.monotonic() - t_release,
                             world=spec.world)
        for gi, s in self._shard_local.items():
            shards[gi] = s
        from horovod_tpu.core import basics

        mp = collectives._multiprocess_world(basics._ensure_init())
        for gi, s in enumerate(shards):
            if s is None:
                # a whole bucket produced no cotangents and was never
                # released — its shard is the reduction identity
                g = spec.groups[gi]
                shape = ((g.shard_elems,) if mp
                         else (spec.world, g.shard_elems))
                shards[gi] = jnp.zeros(shape, np.dtype(g.dtype))
        self._reset_step()
        if failure is not None:
            raise failure
        zero._set_shard_bytes("grad_shards", shards, spec.world)
        return zero.ShardedGrads(spec, tuple(shards))

    def _flush(self) -> None:
        """Release any buckets whose countdown never hit zero (a leaf
        that produced no cotangent — e.g. an unused parameter). Partial
        buckets go to the wire with the gradients that did arrive."""
        for b in self._buckets:
            if self._remaining.get(b.index, 0) > 0 and any(
                    i in self._grads for i in b.leaves):
                values = {i: self._grads.pop(i) for i in b.leaves
                          if i in self._grads}
                if self._pass_idx + 1 >= self.every_k:
                    self._release(b, values)
                else:
                    for i, v in values.items():
                        prev = self._accum.get(i)
                        self._accum[i] = v if prev is None else prev + v

    def _reset_step(self) -> None:
        self._pass_idx = 0
        self._step_id += 1
        self._grads.clear()
        self._accum.clear()
        self._released = []
        self._local = {}
        self._rs_released = []
        self._shard_local = {}
        self._token = None

    def abort(self) -> None:
        """Drain every in-flight handle (ignoring errors) and reset —
        for callers that abandon a step without gathering (elastic
        re-form paths). An elastic reform also invalidates the
        bucket-aligned zero spec (the world changed), so it is dropped
        and lazily rebuilt on the next release."""
        for _bucket_idx, pairs, _t_release, _wire_bytes in (
                list(self._released) + list(self._rs_released)):
            for _i, h in pairs:
                try:
                    h.wait()
                except Exception:
                    pass
        self._zspec = None
        self._reset_step()
