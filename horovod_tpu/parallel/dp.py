"""Data-parallel training API: the ``DistributedOptimizer`` family.

TPU-native equivalent of the reference's framework wrappers (reference:
horovod/torch/__init__.py:47-203 ``_DistributedOptimizer``,
horovod/tensorflow/__init__.py:230-263 ``DistributedOptimizer``,
:323-376 ``DistributedGradientTape``). The idiomatic JAX optimizer is an
``optax.GradientTransformation``; ``DistributedOptimizer`` wraps one so that
gradients are averaged across all workers before the inner update:

* Under ``shard_map`` (per-device gradients, explicit SPMD): emits
  ``lax.pmean`` over the mesh axes — compiled into the step as an XLA
  all-reduce over ICI.
* Under plain ``jit``/``pjit`` with a global batch: gradients of a
  global-mean loss are *already* the global average; the wrapper detects
  that no mesh axis is bound and is a no-op, so the same user code runs
  in both styles.
* Eagerly (outside ``jit``): dispatches the cached compiled allreduce.

Gradient accumulation (``backward_passes_per_step``, reference:
horovod/torch/__init__.py:82-143) accumulates in optimizer state and
allreduces once every N steps.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from horovod_tpu.utils import compat

from horovod_tpu.compression import Compression
from horovod_tpu.core import basics, mesh as mesh_mod
from horovod_tpu.ops import collectives
from horovod_tpu.parallel import sparse as sparse_mod


def _bound_axes(axis_name=None) -> tuple:
    """Return the subset of the requested mesh axes bound in the current
    trace (empty outside ``shard_map``)."""
    axes = axis_name if axis_name is not None else mesh_mod.GLOBAL_AXES
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    bound = []
    for a in axes:
        try:
            compat.axis_size(a)
        except NameError:
            continue
        bound.append(a)
    return tuple(bound)


def _allreduce_leaf(g, average, compression, axis_name,
                    sparse_as_dense=False):
    if g is None:
        return None
    if sparse_mod.is_sparse(g):
        # Sparse/embedding gradient (reference:
        # horovod/tensorflow/__init__.py:64-75): exchanged via allgather of
        # (indices, values) unless sparse_as_dense densifies first
        # (reference: tensorflow/__init__.py:200-203).
        if sparse_as_dense:
            g = sparse_mod.densify_leaf(g)
        else:
            return sparse_mod.exchange_sparse_grad(
                g, average=average, compression=compression,
                axis_name=axis_name, bound_axes=_bound_axes(axis_name))
    if isinstance(g, jax.core.Tracer):
        axes = _bound_axes(axis_name)
        if not axes:
            # Plain pjit global-batch DP: gradients are already the global
            # average; XLA inserted the collective from the shardings.
            return g
        c, ctx = compression.compress(g)
        red = lax.pmean(c, axes) if average else lax.psum(c, axes)
        return compression.decompress(red, ctx)
    return collectives.allreduce(
        g, average=average, compression=compression, axis_name=axis_name
    )


def allreduce_gradients(grads, *, average: bool = True,
                        compression=Compression.none, axis_name=None,
                        sparse_as_dense: bool = False):
    """Average a pytree of gradients across all workers.

    Functional analogue of ``DistributedGradientTape.gradient`` post-
    processing (reference: horovod/tensorflow/__init__.py:323-376).
    ``SparseGrad`` leaves ride the allgather path (or are densified first
    when ``sparse_as_dense``); either way the result is dense.

    Eager dense leaves are exchanged through
    :func:`collectives.grouped_allreduce`, so a whole pytree is one
    fused submission per dtype group instead of one collective per leaf
    (reference: the fusion-buffer batching the per-leaf reference path
    gets from its background coordinator, horovod/common/operations.cc).
    Tracer leaves keep the in-jit ``lax.pmean``/``psum`` path unchanged.

    Inside a :func:`horovod_tpu.parallel.buckets.prereduced` scope the
    tree is returned untouched: a bucket-wise release plan already
    exchanged the gradients during backward, and reducing them a second
    time would divide (or multiply) by the world size twice.
    """
    from horovod_tpu.parallel import buckets as buckets_mod
    from horovod_tpu.parallel import zero as zero_mod

    if isinstance(grads, zero_mod.ShardedGrads):
        raise TypeError(
            "allreduce_gradients got a zero.ShardedGrads: stage-2 gradients "
            "are already the reduced local shard — feed them straight to a "
            "partition-aligned zero.sharded_adamw / zero.sharded_update "
            "instead of re-reducing them")
    if buckets_mod.is_prereduced():
        return grads
    leaves, treedef = jax.tree_util.tree_flatten(
        grads, is_leaf=sparse_mod.is_sparse)
    out = list(leaves)
    dense_eager = []
    for i, g in enumerate(leaves):
        if g is None:
            continue
        if sparse_mod.is_sparse(g):
            if sparse_as_dense:
                g = sparse_mod.densify_leaf(g)
            else:
                out[i] = sparse_mod.exchange_sparse_grad(
                    g, average=average, compression=compression,
                    axis_name=axis_name,
                    bound_axes=_bound_axes(axis_name))
                continue
        if isinstance(g, jax.core.Tracer):
            out[i] = _allreduce_leaf(g, average, compression, axis_name,
                                     False)
            continue
        out[i] = g
        dense_eager.append(i)
    if dense_eager:
        # submit reverse-topological (last layer first): tree-flatten
        # order follows the forward layer order, but backward finalizes
        # gradients back-to-front, so fronting the tail of the tree puts
        # the earliest-ready gradients at the head of the fusion queue —
        # same ordering the bucket-release plan uses
        submit = list(reversed(dense_eager))
        reduced = collectives.grouped_allreduce(
            [out[i] for i in submit], average=average,
            compression=compression, axis_name=axis_name)
        for i, r in zip(submit, reduced):
            out[i] = r
    return jax.tree_util.tree_unflatten(treedef, out)


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    compression=Compression.none,
    average: bool = True,
    backward_passes_per_step: int = 1,
    axis_name=None,
    sparse_as_dense: bool = False,
    shard_optimizer_states: bool = False,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so gradients are allreduced across workers
    before each update.

    Usage mirrors the reference (reference: examples/*.py, API
    horovod/torch/__init__.py:205-253):

        opt = hvd.DistributedOptimizer(optax.sgd(0.01 * hvd.size()))

    ``compression`` casts gradients to a 16-bit wire type for the
    collective; ``backward_passes_per_step`` accumulates N micro-batches
    between allreduces (reference: torch/__init__.py:82-143);
    ``sparse_as_dense`` densifies ``SparseGrad`` leaves before the
    exchange instead of allgathering them (reference:
    tensorflow/__init__.py:200-203).

    ``shard_optimizer_states=True`` switches to the ZeRO-1 data plane
    (:mod:`horovod_tpu.parallel.zero`): the allreduce decomposes into
    reduce-scatter + update-on-shard + allgather, so the inner
    optimizer's state lives 1/N per chip. Same wire bytes, bit-identical
    updates for elementwise inner transforms. Requires
    ``backward_passes_per_step == 1`` (MultiSteps' internal ``lax.cond``
    would trace the eager sharded data plane).

    Stages 2/3 ride the same wrapper: pass a ``zero.ShardedGrads`` (from
    ``zero.scatter_gradients`` or a ``GradReleasePlan(reduce_scatter=True)``)
    as the grads and the reduce-scatter phase is skipped — the wire cost
    drops to half an allreduce because only the scatter half ran. Params
    sharded at rest (``zero.shard_params``) make the update return a
    ``zero.ShardedParams`` and skip the trailing allgather too (stage 3);
    gather buckets on demand with ``zero.iter_param_buckets``.
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    if shard_optimizer_states:
        if backward_passes_per_step != 1:
            raise ValueError(
                "shard_optimizer_states does not compose with "
                "backward_passes_per_step > 1: accumulate in the training "
                "loop instead")
        from horovod_tpu.parallel import zero

        return zero.sharded_update(
            optimizer, average=average, compression=compression,
            axis_name=axis_name, sparse_as_dense=sparse_as_dense)

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(grads, opt_state, params=None, **extra):
        # step-profiler hook (profiler.py): on the eager path each update
        # is a step boundary, and the inner update is the optimizer phase.
        # Inside jit/shard_map everything is a tracer — the whole step is
        # one program and the profiler attributes it as compute.
        from horovod_tpu import integrity as _integrity
        from horovod_tpu import profiler as _profiler

        traced = any(isinstance(g, jax.core.Tracer)
                     for g in jax.tree_util.tree_leaves(grads))
        eager = _profiler.enabled() and not traced
        if eager:
            _profiler.auto_step()
        if not traced:
            # memory plane: grads/params live-bytes (shape math only);
            # inside jit these are tracers and the step owns the bytes
            from horovod_tpu import memory as _memory

            _t = _memory.tracker()
            if _t.enabled:
                _t.note_tree_bytes("grads", grads)
                if params is not None:
                    _t.note_tree_bytes("params", params)
        reduced = allreduce_gradients(
            grads, average=average, compression=compression,
            axis_name=axis_name, sparse_as_dense=sparse_as_dense,
        )
        if _integrity.enabled() and not traced:
            from horovod_tpu.integrity import guards as _guards

            # the guard observes the globally-reduced grad norm, so every
            # rank sees the same stream and skips the same steps; a skip
            # suppresses the update (zero deltas, state untouched) while
            # the batch stays consumed
            if not _guards.guard_gradients(reduced):
                zeros = jax.tree_util.tree_map(jnp.zeros_like, reduced)
                return zeros, opt_state
        if eager:
            with _profiler.annotate("optimizer"):
                return optimizer.update(reduced, opt_state, params, **extra)
        return optimizer.update(reduced, opt_state, params, **extra)

    tx = optax.GradientTransformationExtraArgs(init_fn, update_fn)
    if backward_passes_per_step > 1:
        multi = optax.MultiSteps(tx, every_k_schedule=backward_passes_per_step)

        def accum_update(grads, opt_state, params=None, **extra):
            # MultiSteps accumulates into a dense zeros_like(params) tree,
            # so SparseGrad leaves must densify before accumulation (the
            # sparse wire saving doesn't combine with accumulate-then-
            # exchange; correctness first).
            grads = jax.tree_util.tree_map(
                lambda g: sparse_mod.densify_leaf(g)
                if sparse_mod.is_sparse(g) else g,
                grads, is_leaf=sparse_mod.is_sparse)
            return multi.update(grads, opt_state, params, **extra)

        return optax.GradientTransformationExtraArgs(multi.init, accum_update)
    return tx


def DistributedGradientTape(
    grad_fn: Callable[..., Any],
    *,
    compression=Compression.none,
    average: bool = True,
    axis_name=None,
    returns: str = "grads",
    sparse_as_dense: bool = False,
) -> Callable[..., Any]:
    """Wrap a gradient-producing function so its gradients are allreduced.

    JAX has no tape; the analogue of wrapping ``tf.GradientTape``
    (reference: horovod/tensorflow/__init__.py:323-376) is wrapping the
    function returned by ``jax.grad``/``jax.value_and_grad``. Because a
    2-tuple output is ambiguous (grads-over-tuple-params vs (value, grads)
    vs (grads, aux)), the convention is stated explicitly:

    * ``returns="grads"`` (default) — the whole output is the gradient
      pytree (``jax.grad(f)``, including tuple params).
    * ``returns="value_and_grads"`` — output is ``(value, grads)``
      (``jax.value_and_grad(f)``; value may itself be ``(loss, aux)``).
    * ``returns="grads_and_aux"`` — output is ``(grads, aux)``
      (``jax.grad(f, has_aux=True)``).
    """
    if returns not in ("grads", "value_and_grads", "grads_and_aux"):
        raise ValueError(
            "returns must be 'grads', 'value_and_grads' or 'grads_and_aux', "
            f"got {returns!r}")

    def reduce(grads):
        return allreduce_gradients(
            grads, average=average, compression=compression,
            axis_name=axis_name, sparse_as_dense=sparse_as_dense)

    def wrapped(*args, **kwargs):
        out = grad_fn(*args, **kwargs)
        if returns == "value_and_grads":
            value, grads = out
            return value, reduce(grads)
        if returns == "grads_and_aux":
            grads, aux = out
            return reduce(grads), aux
        return reduce(out)

    return wrapped


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a parameter pytree from ``root_rank`` to all workers, the
    init-sync convention (reference: horovod/torch/__init__.py:255-403
    ``broadcast_parameters``, tensorflow/__init__.py:104-113
    ``broadcast_variables``).

    In single-controller SPMD the parameters are already globally
    consistent; this forces replicated sharding over the mesh (a no-op for
    already-replicated arrays) so later steps see identical layouts — and in
    multi-process mode it is the collective that makes rank 0's values
    authoritative.
    """
    return jax.tree_util.tree_map(
        lambda p: collectives.broadcast(p, root_rank)
        if isinstance(p, (jax.Array,)) or hasattr(p, "shape")
        else p,
        params,
    )


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state from ``root_rank`` (reference:
    horovod/torch/__init__.py:306-403). Array leaves are broadcast;
    non-array leaves (step counters, None, hyperparams) pass through — in
    JAX they are part of the jit-replicated program state already."""
    return broadcast_parameters(opt_state, root_rank=root_rank)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    """Broadcast an arbitrary picklable object from ``root_rank``.

    Single-process: identity. Multi-process: value is shipped through the
    coordination service KV store (the analogue of the reference's
    rendezvous store, reference: gloo/http_store.cc).
    """
    st = basics._ensure_init()
    if st.cross_size <= 1 or jax.process_count() == 1:
        return obj
    from horovod_tpu.runtime import coordination

    return coordination.broadcast_object(obj, root_rank=root_rank, name=name)
