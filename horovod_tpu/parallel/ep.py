"""Expert parallelism: Switch-style MoE with all_to_all token routing.

TPU-first extension (the reference is DP-only — SURVEY.md §2.4). Experts
live one-per-device along a mesh axis; each device's tokens are routed
top-1, packed into per-expert capacity buffers, exchanged with
``lax.all_to_all`` over ICI (the canonical TPU MoE dispatch), processed by
the local expert, and exchanged back to be combined with the gate
probabilities. Static shapes throughout: tokens beyond an expert's
capacity are dropped (their output is zero), the standard Switch
Transformer contract.

Composes with DP/TP/PP/SP on other mesh axes. The router is caller-owned
(any ``(tokens, n_experts)`` logits); :func:`load_balance_loss` is the
Switch auxiliary loss that keeps routing uniform.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.utils import compat

from horovod_tpu.parallel._util import consume_stage_axis


def switch_moe(x, gate_logits, expert_fn: Callable, expert_params,
               axis_name: str, capacity: int):
    """Top-1 MoE over experts sharded on ``axis_name`` (inside shard_map).

    ``x``: (tokens, d) this device's tokens; ``gate_logits``: (tokens,
    n_experts); ``expert_params``: this device's expert weights (leading
    stage axis of length 1 from the shard_map spec is consumed);
    ``expert_fn(params, h) -> h`` is the expert body; ``capacity`` is the
    per-(device, expert) token budget.

    Takes ONE mesh axis name (the all_to_all routes over a single axis);
    reshape the mesh if experts should span multiple axes.

    Returns ``(y, router_probs)`` where dropped tokens contribute zeros.
    """
    if not isinstance(axis_name, str):
        raise ValueError(
            f"switch_moe takes ONE mesh axis name (got {axis_name!r}); "
            "the all_to_all routes over a single axis — reshape the mesh "
            "if experts should span multiple axes")
    n_exp = compat.axis_size(axis_name)
    d = x.shape[-1]
    if gate_logits.shape[-1] != n_exp:
        raise ValueError(
            f"router has {gate_logits.shape[-1]} experts but axis "
            f"'{axis_name}' has {n_exp} devices; expert parallelism needs "
            "one expert per device on the axis")
    expert_params = consume_stage_axis(expert_params)

    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert_idx, n_exp, dtype=jnp.int32)  # (T, E)
    pos_in_expert = jnp.sum(
        (jnp.cumsum(onehot, axis=0) - 1) * onehot, axis=-1)  # (T,)
    keep = pos_in_expert < capacity

    # pack: (E, C, d) dispatch buffer; dropped tokens never land
    safe_pos = jnp.where(keep, pos_in_expert, 0)
    dispatch = jnp.zeros((n_exp, capacity, d), x.dtype)
    dispatch = dispatch.at[expert_idx, safe_pos].add(
        x * keep[:, None].astype(x.dtype))

    # route: chunk e of every device -> device e; received layout is
    # (source_device, C, d) for MY expert
    received = lax.all_to_all(dispatch, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
    expert_out = expert_fn(expert_params,
                           received.reshape(n_exp * capacity, d))
    expert_out = expert_out.reshape(n_exp, capacity, d)

    # route back: chunk s returns to source device s
    returned = lax.all_to_all(expert_out, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)

    # unpack + weight by the gate; dropped tokens stay zero
    y = returned[expert_idx, safe_pos]
    y = y * (gate * keep.astype(gate.dtype))[:, None].astype(y.dtype)
    return y, probs


def load_balance_loss(probs, axis_name=None):
    """Switch Transformer auxiliary loss: n_exp * Σ_e f_e · P_e, minimized
    (=1) by uniform routing. ``probs``: (tokens, n_experts) router
    softmax. With ``axis_name``, statistics aggregate across devices."""
    n_exp = probs.shape[-1]
    assignment = jax.nn.one_hot(jnp.argmax(probs, -1), n_exp,
                                dtype=probs.dtype)
    frac_tokens = jnp.mean(assignment, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    if axis_name is not None:
        frac_tokens = lax.pmean(frac_tokens, axis_name)
        frac_probs = lax.pmean(frac_probs, axis_name)
    return n_exp * jnp.sum(frac_tokens * frac_probs)


def default_capacity(tokens_per_device: int, n_experts: int,
                     capacity_factor: float = 1.25) -> int:
    """Per-(device, expert) buffer size: even-split load times the safety
    factor, rounded up so the factor's headroom survives small ratios
    (the Switch convention)."""
    return max(1, math.ceil(tokens_per_device * capacity_factor / n_experts))
