"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

TPU-first extension (the reference is DP-only — SURVEY.md §2.4). Stages
live one-per-device along ``axis_name``; activations circulate with
``lax.ppermute`` while ``lax.scan`` runs the schedule. The forward is the
classic GPipe fill-drain pipeline (n_micro + n_stages - 1 ticks), and the
backward comes from autodiff: ppermute's transpose is the reverse
rotation, so the reversed schedule emerges from ``jax.grad`` without any
hand-written backward pass.

The stage function must be shape-preserving ``(stage_params, x) -> y``
(true of transformer blocks: (microbatch, seq, d_model) in and out);
embedding/head layers run outside the pipelined trunk. Per-stage params
are stacked on a leading axis sharded over ``axis_name``, so each device
holds only its stage's weights.

Composes with DP (batch over another axis) and TP (shard stage weights'
inner dims) the usual mesh way.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.utils import compat

from horovod_tpu.parallel._util import (  # noqa: F401 — re-exported API
    consume_stage_axis,
    stack_stage_params,
)


def pipeline_apply(stage_fn: Callable, stage_params, x,
                   axis_name: str):
    """Run the pipeline inside ``shard_map``.

    ``stage_params``: this device's stage weights (the caller shard_maps a
    (n_stages, ...) stack over ``axis_name``, leading axis consumed).
    ``x``: (n_micro, microbatch, ...) microbatched input, replicated over
    the pipeline axis. Returns (n_micro, microbatch, ...) outputs, valid
    on the LAST stage (zeros elsewhere — combine with
    :func:`last_stage_value` or compute the loss per-device and select).
    """
    if not isinstance(axis_name, str):
        raise ValueError(
            "pipeline_apply takes ONE mesh axis name (the ppermute ring "
            f"is a single axis); got {axis_name!r} — reshape the mesh so "
            "the pipeline spans one axis")
    n_stages = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = x.shape[0]
    ticks = n_micro + n_stages - 1
    stage_params = consume_stage_axis(stage_params)
    # send to the NEXT stage: device i's output becomes i+1's input
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # first stage feeds microbatch t (clamped; masked out after drain)
        mb = lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
        state_in = jnp.where(idx == 0, mb, state)
        out = stage_fn(stage_params, state_in)
        # last stage emits microbatch t - (n_stages - 1)
        out_t = t - (n_stages - 1)
        emit = jnp.logical_and(idx == n_stages - 1, out_t >= 0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(emit, out, lax.dynamic_index_in_dim(
                outputs, jnp.clip(out_t, 0, n_micro - 1), axis=0,
                keepdims=False)),
            jnp.clip(out_t, 0, n_micro - 1), axis=0)
        state = lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    out_shape = jax.eval_shape(stage_fn, stage_params, x[0])
    state0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    outputs0 = jnp.zeros((n_micro,) + out_shape.shape, out_shape.dtype)
    # mark device-varying over the pipeline axis (lax.pvary successor)
    state0 = compat.pvary(state0, (axis_name,))
    outputs0 = compat.pvary(outputs0, (axis_name,))
    (final_state, outputs), _ = lax.scan(
        tick, (state0, outputs0), jnp.arange(ticks))
    return outputs


def last_stage_value(value, axis_name: str):
    """Select the last pipeline stage's ``value`` on every device — the
    broadcast collective with the last stage as root (differentiable,
    unlike a gather)."""
    from horovod_tpu.ops import collectives

    n_stages = compat.axis_size(axis_name)
    return collectives.broadcast(value, n_stages - 1, axis_name=axis_name)


