"""Ring attention: exact attention over sequence shards via ppermute.

Long-context context parallelism. The sequence is sharded across a mesh axis;
each device holds one query/key/value shard. Key/value shards rotate around
the ring with ``lax.ppermute`` while each device accumulates its queries'
attention against every shard using the blockwise Pallas kernel
(ops/pallas/flash_attention.py) and exact log-sum-exp merging — so the full
``(seq, seq)`` attention is never materialised on any chip, memory stays
O(seq/N · d) per device, and communication overlaps the per-step compute.

The backward pass makes a second ring sweep: with the *final* softmax
normaliser (lse) saved from the forward, each (q-shard, kv-shard) pair's
gradient contribution is independent, so dk/dv accumulators simply ride
around the ring with their chunks.

The reference framework is data-parallel only (SURVEY.md §5.7 — no sequence
parallelism of any kind exists there); this is a TPU-first extension built on
the idioms its survey prescribes (shard_map + collective permute over an ICI
mesh axis).

Causal masking works on *global* sequence positions (each device derives its
shard's offset from ``lax.axis_index``); kv shards that are entirely in a
query shard's future are self-skipping — the kernel predicates those grid
steps to no-ops, so causal ring attention does ~half the FLOPs of the
bidirectional case just like a single-chip causal kernel.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.utils import compat

from horovod_tpu.ops.pallas.flash_attention import (
    LANES,
    NEG_INF,
    _as_offset,
    _flash_bwd,
    _use_interpret,
    compute_delta,
    flash_attention_partial,
    merge_partials,
)


def _axis_perm(axis_name):
    n = compat.axis_size(axis_name)
    # send to the left neighbour: device i receives the chunk held by i+1,
    # so after s steps device i holds the chunk owned by (i + s) % n.
    return [(j, (j - 1) % n) for j in range(n)]


def _ppermute_tree(xs, axis_name, perm):
    return jax.tree_util.tree_map(
        lambda x: lax.ppermute(x, axis_name, perm), xs)


def _pcast(x, axis_name):
    """Mark a freshly created array as device-varying over ``axis_name`` so
    it can carry through a scan whose outputs vary (lax.pvary successor)."""
    return compat.pvary(x, axis_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def ring_attention(q, k, v, axis_name, causal=False, sm_scale=None,
                   block_q=512, block_k=1024,
                   bwd_block_q=1024, bwd_block_k=1024):
    """Exact flash attention over a sequence sharded on ``axis_name``.

    Must be called inside ``shard_map`` (or another context binding
    ``axis_name``); ``q``/``k``/``v`` are the local shards, shaped
    ``(batch, heads, seq_local, head_dim)``. Returns the local output shard.

    ``block_q``/``block_k`` tune the forward kernel; ``bwd_block_q``/
    ``bwd_block_k`` the backward sweep (larger square blocks win there).
    """
    o, _ = _ring_fwd(q, k, v, axis_name, causal, sm_scale, block_q, block_k)
    return o


def _ring_fwd(q, k, v, axis_name, causal, sm_scale, block_q, block_k):
    n = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_local = k.shape[2]
    q_off = my * q.shape[2]
    perm = _axis_perm(axis_name)

    def compute(o, lse, k_cur, v_cur, s):
        src = (my + s) % n
        o_p, lse_p = flash_attention_partial(
            q, k_cur, v_cur, causal=causal, sm_scale=sm_scale,
            q_offset=q_off, k_offset=src * s_local,
            block_q=block_q, block_k=block_k)
        # float32 accumulation across the ring; cast once at the end.
        return merge_partials(o, lse, o_p.astype(jnp.float32), lse_p)

    def step(carry, s):
        o, lse, k_cur, v_cur = carry
        o, lse = compute(o, lse, k_cur, v_cur, s)
        k_cur, v_cur = _ppermute_tree((k_cur, v_cur), axis_name, perm)
        return (o, lse, k_cur, v_cur), None

    o0 = _pcast(jnp.zeros(q.shape, jnp.float32), axis_name)
    lse0 = _pcast(jnp.full(q.shape[:3], NEG_INF, jnp.float32), axis_name)
    if n > 1:
        # Rotate inside the first n-1 steps only; the last shard's result
        # needs no further ppermute.
        (o, lse, k_cur, v_cur), _ = lax.scan(
            step, (o0, lse0, k, v), jnp.arange(n - 1))
    else:
        o, lse, k_cur, v_cur = o0, lse0, k, v
    o, lse = compute(o, lse, k_cur, v_cur, n - 1)
    return o.astype(q.dtype), lse


def _ring_vjp_fwd(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
                  bwd_block_q, bwd_block_k):
    o, lse = _ring_fwd(q, k, v, axis_name, causal, sm_scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _ring_vjp_bwd(axis_name, causal, sm_scale, block_q, block_k,
                  bwd_block_q, bwd_block_k, res, do):
    q, k, v, o, lse = res
    n = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_local = k.shape[2]
    q_off = my * q.shape[2]
    perm = _axis_perm(axis_name)
    lse4 = jnp.broadcast_to(lse[..., None], lse.shape + (LANES,))
    # delta depends only on (o, do) — loop-invariant across the ring sweep,
    # so compute its O(B·H·S·D) reduction once, not once per ring step.
    delta = compute_delta(o, do)
    scale = (1.0 / math.sqrt(q.shape[-1]) if sm_scale is None else sm_scale)

    def step(carry, s):
        dq, k_cur, v_cur, dk_acc, dv_acc = carry
        src = (my + s) % n
        dq_p, dk_p, dv_p = _flash_bwd(
            q, k_cur, v_cur, o, lse4, do,
            _as_offset(q_off), _as_offset(src * s_local),
            sm_scale=float(scale), causal=causal,
            block_q=bwd_block_q, block_k=bwd_block_k,
            interpret=_use_interpret(), delta=delta)
        dq = dq + dq_p.astype(dq.dtype)
        dk_acc = dk_acc + dk_p.astype(dk_acc.dtype)
        dv_acc = dv_acc + dv_p.astype(dv_acc.dtype)
        # dk/dv accumulators travel with their chunks; after n rotations
        # every chunk (and its gradient) is back on its owner.
        k_cur, v_cur, dk_acc, dv_acc = _ppermute_tree(
            (k_cur, v_cur, dk_acc, dv_acc), axis_name, perm)
        return (dq, k_cur, v_cur, dk_acc, dv_acc), None

    dq0 = _pcast(jnp.zeros(q.shape, jnp.float32), axis_name)
    dk0 = _pcast(jnp.zeros(k.shape, jnp.float32), axis_name)
    dv0 = _pcast(jnp.zeros(v.shape, jnp.float32), axis_name)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)
