"""Sparse (embedding) gradient exchange: allgather instead of allreduce.

Capability parity with the reference's sparse path (reference:
horovod/tensorflow/__init__.py:64-75 — ``tf.IndexedSlices`` gradients are
exchanged as allgather(values) + allgather(indices) rather than densified
and allreduced; ``sparse_as_dense`` densifies first,
horovod/tensorflow/__init__.py:200-203).

JAX produces dense gradients, so the sparse representation is explicit: a
:class:`SparseGrad` pytree holds the touched row ids and their gradient
rows. For an embedding table of V rows where a step touches n ≪ V rows,
exchanging ``n·d`` values per worker over ICI beats allreducing ``V·d``
— the same bandwidth argument the reference makes for NCCL.

The exchange is mathematically exact: the dense gradient is
``scatter_add(zeros, ids, rows)`` and scatter-add commutes with
concatenation, so densify(allgather(sparse)) == allreduce(densify(sparse)).

Canonical usage (see also tests/test_sparse.py)::

    value_and_grad = hvd.with_sparse_embedding_grad(
        lambda rows, labels: loss(rows, labels))
    loss, table_grad = value_and_grad(table, ids, labels)
    # table_grad is a SparseGrad; DistributedOptimizer/allreduce_gradients
    # exchange it via allgather and hand the optimizer a dense average.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.utils import compat

from horovod_tpu.core import mesh as mesh_mod


class SparseGrad:
    """Gradient of an embedding table concentrated on ``indices``.

    ``indices``: (nnz,) int32 row ids (duplicates allowed — they add).
    ``values``: (nnz, ...) gradient rows, one per index.
    ``num_rows``: static leading dimension of the dense table.

    Registered as a pytree (static ``num_rows``) so it can cross ``jit``
    boundaries and live inside gradient pytrees.
    """

    def __init__(self, indices, values, num_rows: int):
        self.indices = indices
        self.values = values
        self.num_rows = int(num_rows)

    def densify(self) -> jax.Array:
        """Scatter-add to the dense gradient."""
        dense_shape = (self.num_rows,) + tuple(self.values.shape[1:])
        zeros = jnp.zeros(dense_shape, self.values.dtype)
        return zeros.at[self.indices].add(self.values)

    def __repr__(self):
        return (f"SparseGrad(nnz={self.indices.shape[0]}, "
                f"num_rows={self.num_rows}, values={self.values.shape})")


jax.tree_util.register_pytree_node(
    SparseGrad,
    lambda sg: ((sg.indices, sg.values), sg.num_rows),
    lambda num_rows, children: SparseGrad(children[0], children[1], num_rows),
)


def is_sparse(x: Any) -> bool:
    return isinstance(x, SparseGrad)


def densify_leaf(sg: SparseGrad) -> jax.Array:
    """Densify in either representation: plain ``(nnz,)`` indices, or the
    eager mode's worker-stacked ``(N, nnz)`` components (one dense gradient
    per worker, stacked)."""
    if not isinstance(sg.indices, jax.core.Tracer) and sg.indices.ndim == 2:
        return jax.vmap(
            lambda i, v: SparseGrad(i, v, sg.num_rows).densify())(
                sg.indices, sg.values)
    return sg.densify()


def with_sparse_embedding_grad(apply_fn, extra_argnums=()):
    """Make a value-and-grad function whose embedding-table gradient is a
    :class:`SparseGrad`.

    ``apply_fn(rows, *args)`` computes the scalar loss from the *gathered*
    embedding rows (shape ``ids.shape + (d,)``). The returned function has
    signature ``(table, ids, *args) -> (value, SparseGrad)``. Only the rows
    are differentiated by default — extra args (labels, masks) are treated
    as constants; pass their ``apply_fn`` argnums via ``extra_argnums`` to
    also get their gradients, as ``(value, (SparseGrad, *extra_grads))``.

    This is the TPU-native analogue of the reference relying on TF to emit
    ``IndexedSlices`` for ``tf.gather`` (reference:
    horovod/tensorflow/__init__.py:64-75): the lookup is split out so the
    backward never materialises the dense V×d gradient.
    """
    extra_argnums = tuple(extra_argnums)
    if 0 in extra_argnums:
        raise ValueError("argnum 0 (the rows) is always differentiated")

    def value_and_grads(table, ids, *args):
        flat_ids = ids.reshape(-1)
        rows = jnp.take(table, flat_ids, axis=0).reshape(
            ids.shape + table.shape[1:])
        value, grads = jax.value_and_grad(
            apply_fn, argnums=(0,) + extra_argnums)(rows, *args)
        d_rows = grads[0].reshape((flat_ids.shape[0],) + table.shape[1:])
        sparse = SparseGrad(flat_ids, d_rows, table.shape[0])
        if extra_argnums:
            return value, (sparse,) + tuple(grads[1:])
        return value, sparse

    return value_and_grads


def sparse_allgather(sg: SparseGrad, axis_name=None) -> SparseGrad:
    """Concatenate a per-device SparseGrad across the mesh axes — the
    reference's allgather(values)+allgather(indices) exchange. Must run
    inside ``shard_map`` (axes bound)."""
    axes = axis_name if axis_name is not None else mesh_mod.GLOBAL_AXES
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    indices = lax.all_gather(sg.indices, axes, tiled=True)
    values = lax.all_gather(sg.values, axes, tiled=True)
    return SparseGrad(indices, values, sg.num_rows)


def exchange_sparse_grad(sg: SparseGrad, *, average: bool,
                         compression, axis_name, bound_axes) -> jax.Array:
    """Exchange one SparseGrad leaf across workers; return the dense
    averaged (or summed) gradient for the optimizer.

    In-jit under ``shard_map``: allgather(ids)+allgather(values) over the
    bound axes, then one scatter-add — wire cost O(nnz·N·d), not O(V·d).
    In-jit without bound axes (global-batch pjit): the ids/rows are already
    global, so this is just the scatter-add.
    Eager: components are worker-stacked; densify per worker and allreduce.
    """
    if isinstance(sg.values, jax.core.Tracer) or isinstance(
            sg.indices, jax.core.Tracer):
        if bound_axes:
            world = 1
            for a in bound_axes:
                world *= compat.axis_size(a)
            c_values, ctx = compression.compress(sg.values)
            gathered = sparse_allgather(
                SparseGrad(sg.indices, c_values, sg.num_rows),
                axis_name=bound_axes)
            values = compression.decompress(gathered.values, ctx)
            dense = SparseGrad(gathered.indices, values,
                               sg.num_rows).densify()
            return dense / world if average else dense
        # Global-batch pjit: gradients of a global-mean loss are already
        # the global average once scattered.
        return sg.densify()

    # Eager: leaves are worker-stacked (N, ...) arrays — densify each
    # worker's slice, then ride the dense eager allreduce.
    from horovod_tpu.ops import collectives

    return collectives.allreduce(
        densify_leaf(sg), average=average, compression=compression,
        axis_name=axis_name)
