"""Tensor parallelism: Megatron-style sharded transformer layers, the
GSPMD way.

TPU-first extension beyond the reference's DP-only model (SURVEY.md §2.4
notes TP is absent there). On TPU, tensor parallelism is *sharding
annotations*, not hand-written collectives: attention heads and the MLP
hidden dimension are sharded over a mesh axis, parameters and activations
carry `NamedSharding`s, and XLA inserts the all-reduces the Megatron
recipe would place by hand (column-parallel in, row-parallel out). This is
the "pick a mesh, annotate shardings, let XLA insert collectives" design
the scaling playbook prescribes.

Composes with data parallelism: shard params over one axis (default
``local`` — TP collectives ride ICI every layer), batch over the other
(``cross``).

Usage::

    params = model.init(...)["params"]
    placed, step, batch_sharding = tp_train_step(
        model, opt, params, transformer_tp_rules(axis="local"),
        loss_fn=causal_lm_loss, batch_axis="cross")
    opt_state = opt.init(placed)  # inherits the TP layout
    loss, placed, _, opt_state = step(placed, {}, opt_state, xb, xb)
"""

from __future__ import annotations

import re
from typing import Callable, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.core import basics, mesh as mesh_mod


def xla_attention(q, k, v, causal):
    """GSPMD-partitionable attention for TP models.

    Pallas kernels do not auto-partition under pjit, so TP models swap the
    flash kernel for this einsum formulation: XLA shards it over the heads
    axis for free (scores are (batch, heads/N, seq, seq) per device). For
    long sequences combine TP with shard_map sequence parallelism (ring /
    Ulysses) instead, where the flash kernel applies per-shard.
    """
    from horovod_tpu.ops.pallas.flash_attention import attention_reference

    return attention_reference(q, k, v, causal=causal)


def transformer_tp_rules(axis: str = mesh_mod.LOCAL_AXIS):
    """(regex, PartitionSpec) rules for the models.transformer family:
    q/k/v projections and the MLP input are column-parallel (output
    features sharded over ``axis``), the attention output projection and
    MLP output are row-parallel (input features sharded) — one XLA
    all-reduce per block half, exactly the Megatron layout."""
    return [
        # attention: kernel (d_model, heads, head_dim) — shard heads
        (r".*attention/(query|key|value)/kernel", P(None, axis, None)),
        (r".*attention/(query|key|value)/bias", P(axis, None)),
        # out projection: kernel (heads, head_dim, d_model) — shard heads
        (r".*attention/out/kernel", P(axis, None, None)),
        # mlp: wi (d_model, d_ff) column-parallel; wo (d_ff, d_model)
        # row-parallel
        (r".*mlp/wi/kernel", P(None, axis)),
        (r".*mlp/wi/bias", P(axis)),
        (r".*mlp/wo/kernel", P(axis, None)),
        # token embedding (vocab, d_model): shard the vocab rows; the tied
        # output projection contracts over d_model so logits come out
        # vocab-sharded and XLA gathers where needed
        (r".*token_embed/embedding", P(axis, None)),
    ]


def params_shardings(params, mesh, rules, default=P()):
    """Build a NamedSharding pytree for ``params``: first rule whose regex
    matches the '/'-joined param path wins; everything else replicates."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(path_key, leaf):
        path = "/".join(
            getattr(k, "key", getattr(k, "name", str(k))) for k in path_key)
        for pat, spec in compiled:
            if pat.fullmatch(path):
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, default)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def tp_train_step(model, optimizer, params, rules,
                  loss_fn: Optional[Callable] = None,
                  batch_axis: Optional[str] = mesh_mod.CROSS_AXIS,
                  donate: bool = True):
    """Jitted train step with Megatron-sharded parameters.

    ``params`` (an unsharded init tree) is placed per ``rules`` over the
    TP axis; optimizer state initialized from the placed params inherits
    the same layout. The batch is sharded over ``batch_axis`` (data
    parallelism on the other mesh axis; ``None`` replicates it). Returns
    ``(placed_params, step, batch_sharding)`` with ``step`` having the
    make_train_step signature ``(params, batch_stats, opt_state, x, y) ->
    (loss, params, batch_stats, opt_state)``.
    """
    from horovod_tpu import training

    st = basics._ensure_init()
    mesh = st.mesh
    batch_sharding = NamedSharding(
        mesh, P(batch_axis) if batch_axis else P())
    repl = NamedSharding(mesh, P())

    one_step = training._make_one_step(
        model, optimizer, loss_fn or training._default_loss_fn)

    shardings = params_shardings(params, mesh, rules)
    placed = jax.device_put(params, shardings)
    step = jax.jit(
        one_step,
        # opt_state/batch_stats shardings (None) follow the arguments' own
        # placement — optimizer.init(placed_params) inherits the layout
        in_shardings=(shardings, repl, None, batch_sharding,
                      batch_sharding),
        out_shardings=(repl, shardings, repl, None),
        donate_argnums=(0, 1, 2) if donate else (),
    )
    return placed, step, batch_sharding
