"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second long-context strategy (complement of ring attention): the
sequence axis is sharded across the mesh for every layer *except* attention;
at the attention boundary an ``all_to_all`` re-shards from
``(batch, heads, seq/N, dim)`` to ``(batch, heads/N, seq, dim)`` so each
device runs ordinary full-sequence flash attention on a subset of heads,
then a second ``all_to_all`` restores sequence sharding. Communication is
2 all-to-alls per attention call (O(activations/N) bytes over ICI) versus
ring attention's N ppermute steps — cheaper when heads ≥ N and the
interconnect favours all-to-all; ring wins when seq is huge or heads < N.

Like ring attention this is a TPU-first extension (the reference framework
has no sequence parallelism — SURVEY.md §5.7); both compose with data
parallelism over the remaining mesh axes, and both are exact.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax import lax

from horovod_tpu.utils import compat

from horovod_tpu.ops.pallas.flash_attention import flash_attention


def ulysses_attention(q, k, v, axis_name, *, causal: bool = False,
                      sm_scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None,
                      block_q: int = 512, block_k: int = 1024,
                      bwd_block_q: int = 1024, bwd_block_k: int = 1024):
    """Attention over a sequence sharded on ``axis_name`` via all-to-all.

    Must run inside ``shard_map``; ``q``/``k``/``v`` are local sequence
    shards ``(batch, heads, seq/N, dim)`` with ``heads`` divisible by the
    axis size. Returns the local output shard, same shape as ``q``.

    ``attn_fn(q, k, v, causal=..., sm_scale=...)`` defaults to the Pallas
    flash kernel; it sees full-sequence inputs with ``heads/N`` heads.
    """
    n = compat.axis_size(axis_name)
    heads = q.shape[1]
    if heads % n:
        raise ValueError(
            f"ulysses_attention needs heads ({heads}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring attention otherwise")

    def to_seq(x):  # (b, h, s/N, d) -> (b, h/N, s, d)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_heads(x):  # (b, h/N, s, d) -> (b, h, s/N, d)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    if attn_fn is None:
        o = flash_attention(qs, ks, vs, causal=causal, sm_scale=sm_scale,
                            block_q=block_q, block_k=block_k,
                            bwd_block_q=bwd_block_q, bwd_block_k=bwd_block_k)
    else:
        o = attn_fn(qs, ks, vs, causal=causal, sm_scale=sm_scale)
    return to_heads(o)
